#!/usr/bin/env bash
# Smoke-test autonomous fleet elasticity end to end:
#
#   1. the elasticity bench row (serving_autoscale_ramp) — a step-load
#      ramp through an in-process router + autoscale control loop,
#      router.replica.partition fired mid-scale-up, with scale-out,
#      the loadgen invariant verdict, and drain-based scale-down all
#      ASSERTED inside the row;
#   2. the real SUBPROCESS drill — `serve-autoscale` stands up a
#      router + supervisor + SLO-driven policy loop and spawns
#      serve-gateway replicas as child processes (port-0
#      {"listening": ...} handshake, --register self-registration, a
#      shared AOT store so scale-out starts warm). Then:
#        a. a `serve-loadgen --ramp` staircase drives the fleet past
#           one replica's capacity — the supervisor must GROW the
#           fleet (scale_up decision events + /fleetz shows >= 2
#           replicas + keystone_autoscale_* series on /metrics);
#        b. MID-SURGE — while the fleet is hot, so no scale-down can
#           race the victim — one replica process is kill -9'd: the
#           supervisor must REPLACE it (replica_died /
#           replicas_replaced events) and the loadgen verdict must
#           stay green through the death;
#        c. the load stops — the control loop must DRAIN-RETIRE back
#           to the 1-replica baseline (scale_down events, /fleetz
#           back to 1, retired replicas deregistered not just dead);
#      and the loadgen invariant verdict for the ramp must be green
#      (nothing lost, typed sheds only).
#
# CI-friendly: CPU backend, localhost only, small pipeline, short
# windows/cooldowns (the policy ARITHMETIC is under test, not
# production wall clocks). ~4 min.
#
#   bin/smoke-autoscale.sh
set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
TMPDIR="$(mktemp -d)"
AS_LOG="$TMPDIR/autoscale.log"
BENCH_LOG="$TMPDIR/bench.log"
VERDICT="$TMPDIR/verdict.json"
AOT_CACHE="$TMPDIR/aot"
REPLICA_LOGS="$TMPDIR/replicas"
cleanup() {
    [[ -n "${AS_PID:-}" ]] && kill "$AS_PID" 2>/dev/null || true
    # give the supervisor a moment to drain its children, then sweep
    # any stragglers — matched by THIS run's unique AOT-cache path on
    # their command lines, so a concurrent fleet drill on the same
    # box is never collateral
    sleep 3
    pkill -f "serve-gateway.*$AOT_CACHE" 2>/dev/null || true
    rm -rf "$TMPDIR"
}
trap cleanup EXIT

D=48

# ---- 1. the elasticity bench row (everything asserted in-row) -------------
echo "== serving_autoscale_ramp bench row =="
# the row carries its own bounded retry; the compile/AOT caches keep
# per-replica warmup (which the scale-up reaction time includes) short
if ! JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    KEYSTONE_COMPILE_CACHE="$TMPDIR/xc" KEYSTONE_AOT_CACHE="$AOT_CACHE" \
    python -m keystone_tpu serve-bench --autoscale-only \
    | tee "$BENCH_LOG" \
    || ! grep '"metric": "serving_autoscale_ramp"' "$BENCH_LOG" \
        | grep -q '"verdict": "green"'; then
    echo "FAIL: serving_autoscale_ramp not green"; exit 1
fi
echo "PASS serving_autoscale_ramp (scale-out, green verdict, scale-down)"

# ---- 2. the subprocess drill ----------------------------------------------
echo "== serve-autoscale: router + subprocess replicas =="
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    KEYSTONE_COMPILE_CACHE="$TMPDIR/xc" \
    python -m keystone_tpu serve-autoscale \
    --min-replicas 1 --max-replicas 3 \
    --slo-latency-ms 200 --slo-fast-window 6 --slo-sample-interval 0.5 \
    --interval 1 --up-consecutive 2 --down-consecutive 3 \
    --up-cooldown 3 --down-cooldown 3 \
    --d "$D" --hidden "$D" --depth 2 --buckets 8 --lanes 1 \
    --aot-cache "$AOT_CACHE" --replica-log-dir "$REPLICA_LOGS" \
    --startup-timeout 240 \
    >"$AS_LOG" 2>&1 &
AS_PID=$!

listen_url() {
    python -c '
import json, sys
for line in open(sys.argv[1]):
    line = line.strip()
    if line.startswith("{"):
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if "listening" in doc:
            print(doc["listening"])
            break
' "$1"
}
ROUTER=""
for _ in $(seq 1 60); do
    ROUTER="$(listen_url "$AS_LOG")"
    [[ -n "$ROUTER" ]] && break
    kill -0 "$AS_PID" 2>/dev/null || {
        echo "FAIL: serve-autoscale died before binding"; cat "$AS_LOG"; exit 1; }
    sleep 0.5
done
[[ -n "$ROUTER" ]] || { echo "FAIL: no router URL"; cat "$AS_LOG"; exit 1; }
echo "autoscaler router on $ROUTER"

fetch() {
    python -c 'import sys, urllib.request; \
sys.stdout.write(urllib.request.urlopen(sys.argv[1], timeout=float(sys.argv[2])).read().decode())' \
        "$1" "${2:-15}"
}

ready_replicas() {
    fetch "$ROUTER/fleetz" | python -c '
import json, sys
doc = json.load(sys.stdin)
print(sum(1 for r in doc["replicas"] if r["ready"] and r["healthy"]))'
}

# the first replica registers and goes ready (cold start populates the
# shared AOT store, so every LATER replica starts warm)
for _ in $(seq 1 240); do
    [[ "$(ready_replicas 2>/dev/null || echo 0)" == "1" ]] && break
    kill -0 "$AS_PID" 2>/dev/null || {
        echo "FAIL: serve-autoscale died"; tail -40 "$AS_LOG"; exit 1; }
    sleep 1
done
[[ "$(ready_replicas)" == "1" ]] || {
    echo "FAIL: first replica never became ready"; tail -40 "$AS_LOG"; exit 1; }
echo "PASS baseline (1 subprocess replica registered + ready)"

# ---- 2a+2b. ramp load -> scale-out; kill -9 MID-SURGE -> replacement -----
echo "== ramp: scale-out under SLO pressure + kill -9 mid-surge =="
# calibrate the surge to this host: time one sequential request and
# offer ~4x that rate (a fixed rate would be a no-op on a fast box)
HIGH_RATE="$(PYTHONPATH="$ROOT" python -c '
import json, sys, time, urllib.request
router, d = sys.argv[1], int(sys.argv[2])
body = json.dumps({"instances": [[0.1] * d] * 8}).encode()
def one():
    req = urllib.request.Request(router + "/predict", data=body,
                                 headers={"Content-Type": "application/json"})
    t0 = time.perf_counter()
    urllib.request.urlopen(req, timeout=60).read()
    return time.perf_counter() - t0
for _ in range(3): one()
lat = sorted(one() for _ in range(6))
base = lat[len(lat) // 2]
print(min(200, max(10, int(4.0 / max(base, 1e-3)))))
' "$ROUTER" "$D")"
echo "calibrated surge rate: ${HIGH_RATE} rps"
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-loadgen --target "$ROUTER" --d "$D" \
    --ramp "2:4,${HIGH_RATE}:30,2:6" --size-mix 8:1.0 \
    --max-outstanding 64 --settle-s 4 --max-shed-rate 0.9 \
    --report "$VERDICT" >"$TMPDIR/loadgen.log" 2>&1 &
LG_PID=$!

# wait for the supervisor to grow the fleet while the surge runs
GREW=""
for _ in $(seq 1 120); do
    if grep -q '"action": "scale_up"' "$AS_LOG" \
        && [[ "$(grep -c '"event": "replica_started"' "$AS_LOG")" -ge 2 ]]; then
        GREW=1; break
    fi
    kill -0 "$LG_PID" 2>/dev/null || break
    sleep 0.5
done
[[ -n "$GREW" ]] || {
    echo "FAIL: supervisor never scaled out under the surge"
    tail -60 "$AS_LOG"; kill "$LG_PID" 2>/dev/null || true; exit 1; }
PEAK="$(grep -c '"event": "replica_started"' "$AS_LOG")"
echo "PASS scale-out (scale_up decisions, $PEAK replicas started)"

# kill the newest replica NOW, mid-surge: the fleet is hot, so no
# drain-based retirement can race the victim — this death is
# unambiguously a crash the supervisor must repair, under live load
VICTIM_PID="$(grep '"event": "replica_started"' "$AS_LOG" | tail -1 \
    | python -c 'import json,sys; print(json.loads(sys.stdin.read())["pid"])')"
kill -9 "$VICTIM_PID" 2>/dev/null || {
    echo "FAIL: could not kill replica pid $VICTIM_PID"
    kill "$LG_PID" 2>/dev/null || true; exit 1; }
REPLACED=""
for _ in $(seq 1 120); do
    if grep -q '"event": "replicas_replaced"' "$AS_LOG"; then REPLACED=1; break; fi
    sleep 1
done
[[ -n "$REPLACED" ]] || {
    echo "FAIL: killed replica (pid $VICTIM_PID) never replaced"
    tail -60 "$AS_LOG"; kill "$LG_PID" 2>/dev/null || true; exit 1; }
grep -q '"event": "replica_died"' "$AS_LOG" || {
    echo "FAIL: replica death not reported as an event"; exit 1; }
grep '"event": "replicas_replaced"' "$AS_LOG" | tail -1 \
    | grep -q '"replaced": 0' && {
    echo "FAIL: death detected but replacement never came up"
    tail -60 "$AS_LOG"; exit 1; }
echo "PASS kill -9 mid-surge (died -> replaced under load)"

# the whole run — surge, death, replacement — must still verdict green
wait "$LG_PID" || {
    echo "FAIL: ramp loadgen verdict red"; cat "$TMPDIR/loadgen.log"; exit 1; }
grep -q '"passed": true' "$VERDICT" || {
    echo "FAIL: invariant verdict not green"; cat "$VERDICT"; exit 1; }
echo "PASS ramp verdict green (nothing lost, typed sheds only, kill absorbed)"

# the autoscaler's own series ride the router's federated /metrics
fetch "$ROUTER/metrics" | grep -q 'keystone_autoscale_decisions_total' || {
    echo "FAIL: keystone_autoscale_* series missing from /metrics"; exit 1; }
fetch "$ROUTER/metrics" \
    | grep 'keystone_autoscale_decisions_total' \
    | grep -q 'action="scale_up"' || {
    echo "FAIL: scale_up not counted on keystone_autoscale_decisions_total"; exit 1; }
fetch "$ROUTER/metrics" \
    | grep -q 'keystone_autoscale_replicas_replaced_total' || {
    echo "FAIL: replacement not counted on keystone_autoscale_replicas_replaced_total"; exit 1; }
echo "PASS keystone_autoscale_* exported"

# ---- 2c. load gone -> drain-based scale-down to baseline ------------------
echo "== idle: drain-based scale-down to the 1-replica baseline =="
BASELINE=""
for _ in $(seq 1 120); do
    if [[ "$(ready_replicas 2>/dev/null || echo 0)" == "1" ]] \
        && grep -q '"action": "scale_down"' "$AS_LOG"; then
        BASELINE=1; break
    fi
    sleep 1
done
[[ -n "$BASELINE" ]] || {
    echo "FAIL: fleet never drained back to 1 replica"
    fetch "$ROUTER/fleetz" || true; tail -60 "$AS_LOG"; exit 1; }
grep -q '"event": "replica_retired"' "$AS_LOG" || {
    echo "FAIL: scale-down did not retire gracefully (no replica_retired)"; exit 1; }
# retirement deregisters: the roster must hold exactly the survivors,
# not dead entries lingering until probes fail them
ROSTER="$(fetch "$ROUTER/fleetz" | python -c '
import json, sys; print(len(json.load(sys.stdin)["replicas"]))')"
[[ "$ROSTER" == "1" ]] || {
    echo "FAIL: roster still lists $ROSTER replicas after scale-down"
    fetch "$ROUTER/fleetz"; exit 1; }
echo "PASS scale-down (scale_down decisions, graceful retire, roster clean)"

# ---- graceful shutdown ----------------------------------------------------
kill "$AS_PID"
for _ in $(seq 1 30); do
    kill -0 "$AS_PID" 2>/dev/null || break
    sleep 1
done
kill -0 "$AS_PID" 2>/dev/null && {
    echo "FAIL: serve-autoscale did not exit on SIGTERM"; exit 1; }
AS_PID=""

echo "smoke-autoscale: all checks passed"
