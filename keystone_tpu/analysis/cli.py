"""``keystone-lint``: the command-line front end.

    python -m keystone_tpu keystone-lint [paths...]
        [--root DIR] [--json] [--baseline FILE] [--write-baseline]
        [--changed-only] [--list-rules]

Exit codes: 0 = clean (every finding suppressed or baselined and no
stale baseline entries), 1 = unbaselined findings (or stale baseline
entries — the baseline only shrinks; an unparseable linted file
surfaces as a `parse-error` finding here, so one broken file fails
the gate without killing the report), 2 = usage trouble (bad flags,
missing paths, unreadable baseline).

Kept argparse-free on purpose: the other serving CLIs hand-peel argv
the same way, and the lint entry must start fast enough to sit in a
pre-commit hook (no jax import anywhere on this path).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import List, Optional, Sequence

from keystone_tpu.analysis.core import (
    Baseline,
    build_project,
    run_analysis,
)
from keystone_tpu.analysis.rules import ALL_RULES, default_rules

DEFAULT_BASELINE = "LINT_BASELINE.json"
DEFAULT_PATHS = ("keystone_tpu",)

# files that feed the cross-file drift rule: touching any of them in
# --changed-only mode re-runs the project-level pass
_PROJECT_RULE_TRIGGERS = (
    "keystone_tpu/loadgen/faults.py",
    "README.md",
)


def _detect_root(explicit: Optional[str]) -> str:
    if explicit:
        return os.path.abspath(explicit)
    cwd = os.getcwd()
    if os.path.isdir(os.path.join(cwd, "keystone_tpu")):
        return cwd
    # fall back to the checkout this module was imported from, so the
    # CLI works from any working directory
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def _changed_files(root: str) -> Optional[List[str]]:
    """``git diff --name-only HEAD`` + untracked — the fast local
    loop. None when git is unavailable (caller falls back to full)."""
    try:
        diff = subprocess.run(
            ["git", "-C", root, "diff", "--name-only", "HEAD"],
            capture_output=True, text=True, timeout=30,
        )
        if diff.returncode != 0:
            return None
        untracked = subprocess.run(
            ["git", "-C", root, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, timeout=30,
        )
        names = diff.stdout.splitlines()
        if untracked.returncode == 0:
            names += untracked.stdout.splitlines()
        return sorted({n.strip() for n in names if n.strip()})
    except (OSError, subprocess.SubprocessError):
        return None


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = False
    write_baseline = False
    changed_only = False
    baseline_path: Optional[str] = None
    root_arg: Optional[str] = None
    paths: List[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            print(__doc__)
            return 0
        if a == "--list-rules":
            for cls in ALL_RULES:
                print(f"{cls.name:24s} {cls.description}")
            return 0
        if a == "--json":
            as_json = True
        elif a == "--write-baseline":
            write_baseline = True
        elif a == "--changed-only":
            changed_only = True
        elif a == "--baseline":
            i += 1
            if i >= len(argv):
                print("--baseline requires a path", file=sys.stderr)
                return 2
            baseline_path = argv[i]
        elif a == "--root":
            i += 1
            if i >= len(argv):
                print("--root requires a directory", file=sys.stderr)
                return 2
            root_arg = argv[i]
        elif a.startswith("-"):
            print(f"unknown option {a!r}", file=sys.stderr)
            return 2
        else:
            paths.append(a)
        i += 1

    if changed_only and paths:
        # explicit paths already narrow the run; silently honoring one
        # and not the other (and skipping the stale-baseline check)
        # made full-looking runs weaker than they claimed
        print(
            "--changed-only and explicit paths are mutually "
            "exclusive", file=sys.stderr,
        )
        return 2
    if write_baseline and (changed_only or paths):
        # regenerating from a slice would rewrite the file with only
        # the slice's findings, silently dropping every other file's
        # grandfathered entries — the baseline is a full-run artifact
        print(
            "--write-baseline requires a full run (no explicit "
            "paths, no --changed-only)", file=sys.stderr,
        )
        return 2

    root = _detect_root(root_arg)
    if baseline_path is None:
        baseline_path = os.path.join(root, DEFAULT_BASELINE)
    elif not os.path.isabs(baseline_path):
        baseline_path = os.path.join(root, baseline_path)

    rules = default_rules()
    run_project_rules = True
    if not paths:
        paths = list(DEFAULT_PATHS)
        if changed_only:
            changed = _changed_files(root)
            if changed is None:
                print(
                    "keystone-lint: --changed-only needs git; "
                    "linting everything", file=sys.stderr,
                )
            else:
                paths = [
                    c for c in changed
                    if c.endswith(".py")
                    and c.startswith("keystone_tpu/")
                    and os.path.exists(os.path.join(root, c))
                ]
                run_project_rules = any(
                    c in _PROJECT_RULE_TRIGGERS
                    or c.startswith("tests/")
                    for c in changed
                ) or bool(paths)
                if not paths and not run_project_rules:
                    if as_json:
                        print(json.dumps({
                            "version": 1, "root": root, "clean": True,
                            "changed_only": True, "files": 0,
                            "counts": {
                                "findings": 0, "baselined": 0,
                                "suppressed": 0, "stale_baseline": 0,
                            },
                            "findings": [],
                        }, indent=2))
                    else:
                        print("keystone-lint: no changed files to lint")
                    return 0
    if not run_project_rules:
        from keystone_tpu.analysis.rules import FaultPointDriftRule

        rules = [
            r for r in rules
            if not isinstance(r, FaultPointDriftRule)
        ]

    # a typo'd path must not become a gate that silently checks
    # nothing and exits 0 forever
    missing = [
        p for p in paths
        if not os.path.exists(
            p if os.path.isabs(p) else os.path.join(root, p)
        )
    ]
    if missing:
        print(
            f"keystone-lint: no such path(s): {', '.join(missing)}",
            file=sys.stderr,
        )
        return 2

    try:
        project = build_project(root, paths)
        result = run_analysis(root, paths, rules, project=project)
    except OSError as e:
        print(f"keystone-lint: {e}", file=sys.stderr)
        return 2

    if write_baseline:
        Baseline.from_findings(result.findings).save(baseline_path)
        print(
            f"keystone-lint: wrote {len(result.findings)} finding(s) "
            f"to {baseline_path} — replace every 'TODO: justify or "
            "fix' justification before committing"
        )
        return 0

    try:
        baseline = Baseline.load(baseline_path)
    except (ValueError, json.JSONDecodeError) as e:
        print(f"keystone-lint: bad baseline: {e}", file=sys.stderr)
        return 2
    live = result.unbaselined(baseline)
    baselined = len(result.findings) - len(live)
    # stale entries fail the run in full mode only: a --changed-only
    # slice legitimately misses files whose baselined findings live on
    stale = (
        baseline.stale_entries(result.findings)
        if not changed_only else []
    )

    if as_json:
        doc = {
            "version": 1,
            "root": root,
            "clean": not live and not stale,
            "changed_only": changed_only,
            "files": len(project.files),
            "rules": [cls.name for cls in ALL_RULES],
            "counts": {
                "findings": len(live),
                "baselined": baselined,
                "suppressed": result.suppressed,
                "stale_baseline": len(stale),
            },
            "findings": [f.to_dict() for f in live],
            "stale_baseline": stale,
        }
        print(json.dumps(doc, indent=2))
    else:
        for f in live:
            print(f.render())
        for e in stale:
            print(
                f"stale baseline entry (fixed or line changed — "
                f"delete it): {e.get('path')}: {e.get('rule')}: "
                f"{e.get('line_text', '')!r}"
            )
        print(
            f"keystone-lint: {len(live)} finding(s), "
            f"{baselined} baselined, {result.suppressed} suppressed"
            + (f", {len(stale)} stale baseline entr(y/ies)" if stale
               else "")
        )
    return 1 if (live or stale) else 0


if __name__ == "__main__":
    raise SystemExit(main())
