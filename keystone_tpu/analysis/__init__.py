"""keystone-lint: AST-driven contract analysis for this repo's own
concurrency and hot-path invariants.

KeystoneML's core idea — a rule engine that mechanically checks and
rewrites pipeline DAGs — pointed at our own source: every rule here is
a defect class a human review actually caught in PRs 1–8 (lock
discipline around the tracer ring / staging-bytes gauge / request-log
close, blocking work under the pool lock, ``-O``-strippable asserts in
enforcement paths, zeros stamped on degradable metric series, host
syncs on the serving hot path, fault-point catalog drift), turned into
a checked invariant so refactors keep them for free.

Stdlib-only by design (``ast`` + ``tokenize`` comments): the linter
must run in CI images and pre-commit hooks without paying the jax
import, so nothing in this package may import jax or any keystone
module that does.

Entry points: ``python -m keystone_tpu keystone-lint`` (cli.py),
``bin/smoke-lint.sh`` (CI), and ``tests/analysis/test_self_clean.py``
(the tier-1 gate — the analyzer runs over ``keystone_tpu/`` inside the
normal test suite and fails on any unbaselined finding).
"""

from keystone_tpu.analysis.core import (
    Baseline,
    FileContext,
    Finding,
    Project,
    Rule,
    run_analysis,
)
from keystone_tpu.analysis.rules import ALL_RULES, default_rules

__all__ = [
    "ALL_RULES",
    "Baseline",
    "FileContext",
    "Finding",
    "Project",
    "Rule",
    "default_rules",
    "run_analysis",
]
