"""The seven repo-native rules. Each encodes a defect class review
actually caught in PRs 1–8; the module docstring of each rule names
the incident it generalizes.

Rules are deliberately *lexical*: they check what can be decided from
one file's AST plus the shared class/lock resolution — no type
inference, no data flow. That keeps every rule O(nodes), keeps
findings explainable (the message quotes the lock or allowlist
involved), and makes the false-positive escape hatch explicit: a
``# lint: disable=<rule>`` with a justification comment, reviewed like
any other code.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from keystone_tpu.analysis.core import (
    FileContext,
    Finding,
    Project,
    Rule,
    Scope,
    ScopedRule,
    make_finding,
)

# -- rule 1: guarded-by -----------------------------------------------------

# mutating container methods: calling one on a guarded attribute is a
# write for lock-discipline purposes (the tracer-ring / fault-spec /
# admission-queue state is all dict/deque mutation, not rebinding)
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert",
    "pop", "popleft", "remove", "discard", "clear",
    "add", "update", "setdefault",
})


class GuardedByRule(ScopedRule):
    """An attribute annotated ``# guarded-by: <lock>`` on its class may
    only be written — rebound, item-assigned, or mutated through a
    container method — inside a ``with self.<lock>`` block.

    The incident class: the tracer ring swap (PR 4 review), the
    staging-bytes gauge stamped on a retired engine (PR 6 review), the
    request-log stop() close race (PR 7 review) — all writes to
    lock-protected state that compiled fine and raced rarely.

    Exemptions: ``__init__`` (construction happens-before publication)
    and methods named ``*_locked`` (the caller-holds-the-lock
    convention, e.g. ``FaultInjector._disarm_locked``)."""

    name = "guarded-by"
    description = (
        "writes to `# guarded-by:`-annotated attributes must hold the "
        "named lock"
    )

    def _exempt(self, scope: Scope) -> bool:
        fn = scope.func
        return fn is not None and (
            fn == "__init__" or fn.endswith("_locked")
        )

    def _check_attr_write(
        self,
        target: ast.AST,
        node: ast.AST,
        ctx: FileContext,
        scope: Scope,
        findings: List[Finding],
        via: str,
    ) -> None:
        if not isinstance(target, ast.Attribute):
            return
        attr = target.attr
        base = target.value
        try:
            base_text = ast.unparse(base)
        except Exception:
            return
        if base_text == "self":
            info = ctx.classes.get(scope.cls) if scope.cls else None
            if info is None or attr not in info.guarded:
                return
            lock = info.guarded[attr]
            owner = scope.cls
        else:
            # cross-object write: `_global_tracer._ring = ...` — only
            # when the attr is annotated in exactly one class of this
            # module, so the association is unambiguous
            if attr not in ctx.unique_guarded:
                return
            owner, lock = ctx.unique_guarded[attr]
        if self._exempt(scope):
            return
        want = f"{base_text}.{lock}"
        if want in scope.lock_stack:
            return
        findings.append(
            make_finding(
                self.name, ctx, node,
                f"`{base_text}.{attr}` is `# guarded-by: {lock}` "
                f"(class {owner}) but {via} outside `with {want}`",
            )
        )

    def on_node(self, node, ctx, scope, findings):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Subscript):
                    self._check_attr_write(
                        t.value, node, ctx, scope, findings,
                        via="item-assigned",
                    )
                elif isinstance(t, ast.Tuple):
                    for elt in t.elts:
                        self._check_attr_write(
                            elt, node, ctx, scope, findings,
                            via="written",
                        )
                else:
                    self._check_attr_write(
                        t, node, ctx, scope, findings, via="written"
                    )
        elif isinstance(node, ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in _MUTATORS
            ):
                self._check_attr_write(
                    fn.value, node, ctx, scope, findings,
                    via=f"mutated (`.{fn.attr}()`)",
                )
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                self._check_attr_write(
                    base, node, ctx, scope, findings, via="deleted"
                )


# -- rule 2: blocking-under-lock --------------------------------------------

# dotted call texts that block outright
_BLOCKING_DOTTED = frozenset({
    "time.sleep",
    "sleep",
    "urllib.request.urlopen",
    "subprocess.run",
    "subprocess.check_output",
    "subprocess.check_call",
    "socket.create_connection",
})

# attribute calls that block: futures, sockets/HTTP, and — the PR 8
# incident class — engine build/warmup/dispatch (an XLA compile under
# the pool lock stalls every lane; "build engines OUTSIDE the lock" is
# the checked design)
_BLOCKING_ATTRS = frozenset({
    "result",            # Future.result
    "warmup",            # CompiledPipeline.warmup (compiles)
    "apply",             # CompiledPipeline.apply (device dispatch)
    "compute_staged",    # compiled bucket dispatch
    "build_replacements",  # EnginePool generation build
    "build_engines",     # Gateway generation build
    "urlopen", "getresponse", "recv", "accept", "connect",
})


class BlockingUnderLockRule(ScopedRule):
    """Blocking work — sleeps, thread joins, ``Future.result``,
    socket/HTTP calls, engine dispatch/warmup/build — flagged when
    lexically inside a lock's ``with`` body.

    ``<expr>.join(...)`` counts only as a *statement* (result unused):
    that is a thread join; ``str.join``/``os.path.join`` results are
    always consumed. ``Condition.wait`` is exempt — it releases the
    lock it waits on."""

    name = "blocking-under-lock"
    description = (
        "no sleeps / joins / Future.result / sockets / engine "
        "dispatch+warmup inside a lock's `with` body"
    )

    def on_node(self, node, ctx, scope, findings):
        if not scope.lock_stack:
            return
        held = scope.lock_stack[-1]
        call: Optional[ast.Call] = None
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            stmt_call = node.value
            fn = stmt_call.func
            if isinstance(fn, ast.Attribute) and fn.attr == "join":
                findings.append(
                    make_finding(
                        self.name, ctx, node,
                        f"`.join()` (statement form: a thread join) "
                        f"inside `with {held}`",
                    )
                )
                return
        if isinstance(node, ast.Call):
            call = node
        if call is None:
            return
        try:
            fn_text = ast.unparse(call.func)
        except Exception:
            return
        if fn_text in _BLOCKING_DOTTED:
            findings.append(
                make_finding(
                    self.name, ctx, call,
                    f"blocking call `{fn_text}(...)` inside "
                    f"`with {held}`",
                )
            )
            return
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr in _BLOCKING_ATTRS:
            findings.append(
                make_finding(
                    self.name, ctx, call,
                    f"blocking call `{fn_text}(...)` inside "
                    f"`with {held}` (build/dispatch work belongs "
                    "outside the lock; re-pointing alone goes under it)",
                )
            )


# -- rule 3: strippable-assert ----------------------------------------------


class StrippableAssertRule(Rule):
    """Bare ``assert`` outside ``tests/`` must be an explicit raise:
    ``python -O`` strips asserts, so an enforcement/gating path that
    asserts is a path that silently stops enforcing in optimized runs
    (the PR 7 chaos-row fix, applied as a rule)."""

    name = "strippable-assert"
    description = (
        "enforcement paths must raise, not assert (`-O` strips asserts)"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        rel = ctx.rel
        if rel.startswith("tests/") or "/tests/" in rel:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assert):
                yield make_finding(
                    self.name, ctx, node,
                    "bare `assert` is stripped under `python -O`; "
                    "raise AssertionError/ValueError explicitly",
                )


# -- rule 4: absent-not-zero ------------------------------------------------

# the degradable families: series that exist only when their input
# exists (cost analysis present, peaks detected, sampler live). The
# PR 6 contract: a backend that reports nothing yields ABSENT series —
# pre-registering one of these, or stamping 0 on the unavailable path,
# turns "unknown" into a confident lie on every dashboard
DEGRADABLE_SERIES = frozenset({
    "keystone_serving_mfu",
    "keystone_device_roofline_bound",
    "keystone_device_flops_per_dispatch",
    "keystone_device_bytes_per_dispatch",
    "keystone_device_temp_hbm_bytes",
    "keystone_serving_device_flops_total",
    "keystone_serving_padding_efficiency",
    "keystone_serving_staging_bytes",
    "keystone_device_memory_bytes",
})

# receiver/method-name shapes whose `.set(0)` / `set_x(0)` means
# "stamp zero where the honest value is absent" (staging bytes are
# excluded: an empty pool is a real measured zero, not an unknown)
_DEGRADABLE_ATTR_RE = re.compile(
    r"(mfu|roofline|cost_model|device_mem|temp_hbm|flops)",
    re.IGNORECASE,
)

_REGISTRATION_METHODS = frozenset(
    {"gauge", "counter", "histogram", "gauge_func", "summary"}
)


def _zero_const(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value in (0, 0.0)


class AbsentNotZeroRule(ScopedRule):
    """Degradable metric series (cost-model, MFU, roofline,
    device-memory families) must never be pre-registered or ``.set(0)``
    on the unavailable path. Three shapes are flagged:

    - registering a degradable family *unlabeled* at module scope or in
      ``__init__`` (a labeled family with no cells scrapes as absent;
      an unlabeled one scrapes as a lying 0 the moment it exists);
    - ``<x>.set(0)`` / ``set_mfu(0)``-shaped calls whose receiver or
      method names a degradable family;
    - ``X if X is not None else 0`` fallbacks inside a call that emits
      a degradable family (the absent case must skip the sample, not
      zero it)."""

    name = "absent-not-zero"
    description = (
        "degradable metric series stay ABSENT when unavailable — "
        "never pre-registered, never zero-stamped"
    )

    def _first_str_arg(self, call: ast.Call) -> Optional[str]:
        if call.args and isinstance(call.args[0], ast.Constant) and (
            isinstance(call.args[0].value, str)
        ):
            return call.args[0].value
        return None

    def _has_labels(self, call: ast.Call) -> bool:
        # gauge(name, help, labelnames) / labelnames= kwarg: a
        # non-empty labels tuple means no cell exists until set(labels)
        for kw in call.keywords:
            if kw.arg in ("labelnames", "labels"):
                return not (
                    isinstance(kw.value, (ast.Tuple, ast.List))
                    and not kw.value.elts
                )
        if len(call.args) >= 3:
            a = call.args[2]
            return not (
                isinstance(a, (ast.Tuple, ast.List)) and not a.elts
            )
        return False

    def on_node(self, node, ctx, scope, findings):
        if not isinstance(node, ast.Call):
            return
        fn = node.func
        name_arg = self._first_str_arg(node)
        # (a) eager registration of a degradable family
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in _REGISTRATION_METHODS
            and name_arg in DEGRADABLE_SERIES
            and (scope.func is None or scope.func == "__init__")
            and not self._has_labels(node)
        ):
            findings.append(
                make_finding(
                    self.name, ctx, node,
                    f"degradable series `{name_arg}` pre-registered "
                    "unlabeled at construction — it scrapes as 0 "
                    "before its input exists; register lazily on the "
                    "available path (or label it)",
                )
            )
            return
        # (b) zero-stamp: receiver/method names a degradable family
        if isinstance(fn, ast.Attribute):
            stamped = None
            if (
                fn.attr == "set"
                and len(node.args) >= 1
                and _zero_const(node.args[0])
            ):
                try:
                    recv = ast.unparse(fn.value)
                except Exception:
                    recv = ""
                if _DEGRADABLE_ATTR_RE.search(recv.split(".")[-1]):
                    stamped = recv
            elif (
                fn.attr.startswith("set_")
                and _DEGRADABLE_ATTR_RE.search(fn.attr)
                and node.args
                and _zero_const(node.args[0])
            ):
                stamped = fn.attr
            if stamped is not None:
                findings.append(
                    make_finding(
                        self.name, ctx, node,
                        f"`{stamped}` stamped with literal 0 — the "
                        "unavailable path must leave the series "
                        "absent, not zero",
                    )
                )
                return
        # (c) `X if X is not None else 0` — or the inverted spelling
        # `0 if X is None else X` — feeding a degradable family (the
        # test must be an is[-not]-None check: one-hot encodings like
        # `1.0 if side == r else 0.0` are real values, not absence
        # fallbacks)
        if name_arg in DEGRADABLE_SERIES:
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.IfExp)
                    and (
                        _zero_const(sub.orelse)
                        or _zero_const(sub.body)
                    )
                    and isinstance(sub.test, ast.Compare)
                    and any(
                        isinstance(op, (ast.IsNot, ast.Is))
                        for op in sub.test.ops
                    )
                    and any(
                        isinstance(c, ast.Constant) and c.value is None
                        for c in sub.test.comparators
                    )
                ):
                    findings.append(
                        make_finding(
                            self.name, ctx, sub,
                            f"zero fallback inside the emission of "
                            f"degradable series `{name_arg}` — skip "
                            "the sample when the input is absent",
                        )
                    )
                    return


# -- rule 5: hot-path host-sync ---------------------------------------------

# designated hot-path modules -> allowlisted gather-once points
# (qualname prefixes). The incident: per-row jax.Array slicing in the
# delivery path dispatched one device op per request — PR 5 measured
# it as THE pipelined-lane bottleneck and fixed it with a single host
# gather; these allowlist entries are exactly those gather points.
HOT_PATH_MODULES: Dict[str, Set[str]] = {
    "keystone_tpu/serving/engine.py": {
        # host-side pad into the pooled staging buffer: numpy in,
        # numpy out, by design (the prep stage burns host cores while
        # the device computes the previous window)
        "CompiledPipeline.host_stage",
        # NOT listed, deliberately: compute_staged's H2D-bytes read
        # (`a.nbytes` over the staged leaves) is array METADATA —
        # shape x itemsize, no device round-trip — so the
        # device-featurize accounting needs no gather-once exemption;
        # adding one here would license real syncs on the dispatch path
    },
    "keystone_tpu/serving/pipeline.py": {
        # THE gather-once point: one np.asarray per window, futures
        # resolve with row views of it
        "resolve_window_futures",
        "LanePipeline._deliver",
    },
    "keystone_tpu/gateway/pool.py": set(),
}

_HOST_SYNC_CALLS = {
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "jax.device_get",
}


class HotPathHostSyncRule(ScopedRule):
    """In the designated hot-path modules, ``float()`` on a value,
    ``.item()``, ``np.asarray()``/``np.array()``, ``jax.device_get()``
    and per-row loop-index subscripting are host syncs — each one
    round-trips the device per call. They are allowed only at the
    allowlisted gather-once points."""

    name = "hot-path-host-sync"
    description = (
        "host syncs (float/.item()/np.asarray/per-row indexing) only "
        "at allowlisted gather-once points in hot-path modules"
    )

    def __init__(
        self, modules: Optional[Dict[str, Set[str]]] = None
    ):
        self.modules = modules if modules is not None else HOT_PATH_MODULES

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.rel not in self.modules:
            return ()
        return super().check_file(ctx)

    def _allowlisted(self, ctx: FileContext, scope: Scope) -> bool:
        allowed = self.modules.get(ctx.rel, set())
        qual = scope.qualname()
        return any(
            qual == a or qual.startswith(a + ".") for a in allowed
        )

    def on_node(self, node, ctx, scope, findings):
        if self._allowlisted(ctx, scope):
            return
        if isinstance(node, ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Name)
                and fn.id == "float"
                and len(node.args) == 1
                and isinstance(
                    node.args[0],
                    (ast.Name, ast.Attribute, ast.Subscript),
                )
            ):
                findings.append(
                    make_finding(
                        self.name, ctx, node,
                        "`float(...)` on a value is a device->host "
                        "sync on the hot path",
                    )
                )
                return
            try:
                fn_text = ast.unparse(fn)
            except Exception:
                return
            if fn_text in _HOST_SYNC_CALLS:
                findings.append(
                    make_finding(
                        self.name, ctx, node,
                        f"`{fn_text}(...)` gathers to host — hot-path "
                        "code must gather once at an allowlisted "
                        "point, not per call",
                    )
                )
                return
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "item"
                and not node.args
            ):
                findings.append(
                    make_finding(
                        self.name, ctx, node,
                        "`.item()` is a per-element device->host sync",
                    )
                )
            return
        if (
            isinstance(node, ast.Subscript)
            and isinstance(node.ctx, ast.Load)
            # bare-Name receivers only: `a[i]` in a row loop is the
            # per-request device-op pattern; `self._aot[b]` and other
            # attribute-rooted subscripts are dict/config lookups
            and isinstance(node.value, ast.Name)
            and isinstance(node.slice, ast.Name)
            and node.slice.id in scope.loop_vars
        ):
            findings.append(
                make_finding(
                    self.name, ctx, node,
                    "per-row indexing with a loop variable dispatches "
                    "one device op per row — gather the window once "
                    "and slice host-side",
                )
            )


# -- rule 6: fault-point-drift ----------------------------------------------


class FaultPointDriftRule(Rule):
    """The fault-point names wired in code (``faults.fire(...)`` /
    ``register_trigger(...)`` literals), cataloged in ``FAULT_POINTS``,
    documented in README's catalog table, and exercised in ``tests/``
    must agree — a chaos point that exists in only some of those places
    is a drill that silently stopped covering what it claims to."""

    name = "fault-point-drift"
    description = (
        "fault points must agree across FAULT_POINTS, call sites, the "
        "README catalog table, and tests/"
    )

    _POINT_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")
    _README_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_.]+)`\s*\|")

    def __init__(
        self,
        faults_rel: str = "keystone_tpu/loadgen/faults.py",
        readme_rel: str = "README.md",
        tests_rel: str = "tests",
        package_rel: str = "keystone_tpu",
        catalog_var: str = "FAULT_POINTS",
    ):
        self.faults_rel = faults_rel
        self.readme_rel = readme_rel
        self.tests_rel = tests_rel
        self.package_rel = package_rel
        self.catalog_var = catalog_var

    def _catalog(
        self, project: Project
    ) -> Tuple[Optional[Dict[str, int]], Optional[Finding]]:
        """FAULT_POINTS keys -> their source lines (from the AST)."""
        path = os.path.join(project.root, self.faults_rel)
        ctx = project.by_rel.get(self.faults_rel.replace(os.sep, "/"))
        if ctx is None:
            if not os.path.exists(path):
                return None, None  # project without a fault plane
            with open(path, "r", encoding="utf-8") as fh:
                ctx = FileContext(path, self.faults_rel, fh.read())
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            names = {
                t.id for t in targets if isinstance(t, ast.Name)
            }
            if self.catalog_var not in names:
                continue
            if not isinstance(node.value, ast.Dict):
                break
            out: Dict[str, int] = {}
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(
                    k.value, str
                ):
                    out[k.value] = k.lineno
            return out, None
        return None, Finding(
            rule=self.name,
            path=self.faults_rel.replace(os.sep, "/"),
            line=1,
            col=0,
            message=(
                f"no `{self.catalog_var} = {{...}}` dict literal found "
                "— the fault-point catalog is the drift check's anchor"
            ),
        )

    def _wired(self, project: Project) -> Dict[str, Tuple[str, int]]:
        """point -> (rel, line) of one call site arming/firing it.
        Always scans the WHOLE package from disk: a --changed-only
        slice must not make unchanged call sites look unwired."""
        from keystone_tpu.analysis.core import iter_python_files

        wired: Dict[str, Tuple[str, int]] = {}
        faults_rel = self.faults_rel.replace(os.sep, "/")
        for full in iter_python_files(project.root, [self.package_rel]):
            rel = os.path.relpath(full, project.root).replace(
                os.sep, "/"
            )
            if rel == faults_rel:
                continue  # the registry itself, not a wiring site
            ctx = project.by_rel.get(rel)
            if ctx is None:
                try:
                    with open(full, "r", encoding="utf-8") as fh:
                        ctx = FileContext(full, rel, fh.read())
                except (OSError, SyntaxError, ValueError):
                    continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                is_fire = (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in ("fire", "register_trigger")
                ) or (
                    isinstance(fn, ast.Name)
                    and fn.id in ("fire", "register_trigger")
                )
                if not is_fire or not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ) and self._POINT_RE.match(arg.value):
                    wired.setdefault(
                        arg.value, (ctx.rel, arg.lineno)
                    )
        return wired

    def _readme_points(
        self, project: Project
    ) -> Tuple[Optional[Dict[str, int]], int]:
        path = os.path.join(project.root, self.readme_rel)
        if not os.path.exists(path):
            return None, 1
        with open(path, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
        start = None
        for i, line in enumerate(lines, start=1):
            if "Fault-point catalog" in line:
                start = i
                break
        if start is None:
            return None, 1
        points: Dict[str, int] = {}
        for i in range(start, len(lines) + 1):
            line = lines[i - 1]
            if i > start and (
                line.startswith("#") or line.startswith("**")
            ):
                break  # next section/paragraph heading ends the table
            m = self._README_ROW_RE.match(line)
            if m and self._POINT_RE.match(m.group(1)):
                points[m.group(1)] = i
        return points, start

    def _tests_corpus(self, project: Project) -> str:
        """Every test file's text, read ONCE per analysis run (not
        once per cataloged point — the walk is the expensive part)."""
        tests_dir = os.path.join(project.root, self.tests_rel)
        chunks: List[str] = []
        for dirpath, dirnames, filenames in os.walk(tests_dir):
            dirnames[:] = [
                d for d in dirnames if d != "__pycache__"
            ]
            for f in filenames:
                if not f.endswith(".py"):
                    continue
                try:
                    with open(
                        os.path.join(dirpath, f), "r", encoding="utf-8"
                    ) as fh:
                        chunks.append(fh.read())
                except OSError:
                    continue
        return "\n".join(chunks)

    def check_project(self, project: Project) -> Iterable[Finding]:
        catalog, err = self._catalog(project)
        if err is not None:
            yield err
            return
        if catalog is None:
            return
        faults_rel = self.faults_rel.replace(os.sep, "/")
        readme_rel = self.readme_rel.replace(os.sep, "/")
        wired = self._wired(project)
        readme, table_line = self._readme_points(project)
        if readme is None:
            yield Finding(
                rule=self.name, path=readme_rel, line=1, col=0,
                message=(
                    "no 'Fault-point catalog' table found in README — "
                    "the catalog must be documented where operators "
                    "look for it"
                ),
            )
        else:
            for point, line in sorted(catalog.items()):
                if point not in readme:
                    yield Finding(
                        rule=self.name, path=readme_rel,
                        line=table_line, col=0,
                        message=(
                            f"cataloged fault point `{point}` missing "
                            "from the README fault-point table"
                        ),
                    )
            for point, line in sorted(readme.items()):
                if point not in catalog:
                    yield Finding(
                        rule=self.name, path=readme_rel, line=line,
                        col=0,
                        message=(
                            f"README documents fault point `{point}` "
                            "that FAULT_POINTS does not catalog"
                        ),
                    )
        corpus = self._tests_corpus(project)
        for point, line in sorted(catalog.items()):
            if point not in wired:
                yield Finding(
                    rule=self.name, path=faults_rel, line=line, col=0,
                    message=(
                        f"cataloged fault point `{point}` has no "
                        "`fire(...)`/`register_trigger(...)` call site "
                        "in keystone_tpu/ — a point nothing consults "
                        "never fires"
                    ),
                )
            elif point not in corpus:
                yield Finding(
                    rule=self.name, path=faults_rel, line=line, col=0,
                    message=(
                        f"cataloged fault point `{point}` appears "
                        f"nowhere under {self.tests_rel}/ — every "
                        "chaos point needs a test exercising it"
                    ),
                )
        for point, (rel, line) in sorted(wired.items()):
            if point not in catalog:
                yield Finding(
                    rule=self.name, path=rel, line=line, col=0,
                    message=(
                        f"fault point `{point}` is wired here but "
                        "missing from FAULT_POINTS — /chaosz can't "
                        "validate arms against it"
                    ),
                )

    # README/line-text note: README findings anchor to markdown, where
    # `line_text` stays empty (the baseline key still works: path +
    # rule + message-stable anchor line text "").


# -- rule 7: metric-family-drift ---------------------------------------------


class MetricFamilyDriftRule(Rule):
    """The ``keystone_*`` metric families registered in code and the
    README's metric-family catalog table must agree in both
    directions — a family operators can't find documented is a dark
    series, and a documented family nothing registers is a dashboard
    pointed at nothing.

    Registration sites are the registry methods
    (``counter``/``gauge``/``gauge_func``/``summary``/``histogram``/
    ``latency``) and direct ``MetricFamily(...)`` construction, scanned
    over the WHOLE package from disk like the fault-point rule (a
    ``--changed-only`` slice must not make unchanged registrations look
    undocumented). F-string family names (``f"keystone_attr_{f}_total"``)
    become wildcard patterns: each must match at least one catalog row,
    and rows they match count as registered.

    Asymmetry by design: the registered→documented direction only
    counts names the scan can prove are registered (literal first args
    of registration calls), but the documented→registered direction
    accepts any catalog row whose name appears as a string literal
    anywhere in the package — families registered through a variable
    (the ``device_families`` per-key loop) would otherwise read as
    phantom rows."""

    name = "metric-family-drift"
    description = (
        "registered keystone_* metric families and the README "
        "metric-family catalog table must agree both ways"
    )

    _FAMILY_RE = re.compile(r"^keystone_[a-z0-9_]+$")
    _README_ROW_RE = re.compile(r"^\|\s*`(keystone_[a-z0-9_]+)`")
    _REGISTER_FUNCS = frozenset(
        ("counter", "gauge", "gauge_func", "summary", "histogram",
         "latency", "MetricFamily")
    )

    def __init__(
        self,
        readme_rel: str = "README.md",
        package_rel: str = "keystone_tpu",
        table_heading: str = "Metric-family catalog",
    ):
        self.readme_rel = readme_rel
        self.package_rel = package_rel
        self.table_heading = table_heading

    def _registered(
        self, project: Project
    ) -> Tuple[
        Dict[str, Tuple[str, int]],
        List[Tuple["re.Pattern", str, str, int]],
        Set[str],
    ]:
        """Literal family -> one registration site, the wildcard
        patterns compiled from f-string registrations, and every
        family-shaped string literal seen anywhere (the
        phantom-suppression set for indirect registrations)."""
        from keystone_tpu.analysis.core import iter_python_files

        literals: Dict[str, Tuple[str, int]] = {}
        patterns: List[Tuple[re.Pattern, str, str, int]] = []
        mentioned: Set[str] = set()
        for full in iter_python_files(project.root, [self.package_rel]):
            rel = os.path.relpath(full, project.root).replace(
                os.sep, "/"
            )
            ctx = project.by_rel.get(rel)
            if ctx is None:
                try:
                    with open(full, "r", encoding="utf-8") as fh:
                        ctx = FileContext(full, rel, fh.read())
                except (OSError, SyntaxError, ValueError):
                    continue
            for node in ast.walk(ctx.tree):
                if (
                    isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and self._FAMILY_RE.match(node.value)
                ):
                    mentioned.add(node.value)
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                fn = node.func
                fn_name = (
                    fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None
                )
                if fn_name not in self._REGISTER_FUNCS:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    if self._FAMILY_RE.match(arg.value):
                        literals.setdefault(
                            arg.value, (rel, arg.lineno)
                        )
                elif isinstance(arg, ast.JoinedStr):
                    pieces: List[str] = []
                    for part in arg.values:
                        if isinstance(part, ast.Constant) and isinstance(
                            part.value, str
                        ):
                            pieces.append(re.escape(part.value))
                        else:
                            pieces.append("[a-z0-9_]+")
                    raw = "".join(pieces)
                    if raw.startswith("keystone_"):
                        patterns.append((
                            re.compile(f"^{raw}$"), raw, rel,
                            arg.lineno,
                        ))
        return literals, patterns, mentioned

    def _readme_rows(
        self, project: Project
    ) -> Tuple[Optional[Dict[str, int]], int]:
        path = os.path.join(project.root, self.readme_rel)
        if not os.path.exists(path):
            return None, 1
        with open(path, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
        start = None
        for i, line in enumerate(lines, start=1):
            if self.table_heading in line:
                start = i
                break
        if start is None:
            return None, 1
        rows: Dict[str, int] = {}
        for i in range(start, len(lines) + 1):
            line = lines[i - 1]
            if i > start and (
                line.startswith("#") or line.startswith("**")
            ):
                break  # next section/paragraph heading ends the table
            m = self._README_ROW_RE.match(line)
            if m:
                rows[m.group(1)] = i
        return rows, start

    def check_project(self, project: Project) -> Iterable[Finding]:
        readme_rel = self.readme_rel.replace(os.sep, "/")
        literals, patterns, mentioned = self._registered(project)
        if not literals and not patterns:
            return  # project without a metrics plane
        rows, table_line = self._readme_rows(project)
        if rows is None:
            yield Finding(
                rule=self.name, path=readme_rel, line=1, col=0,
                message=(
                    f"no '{self.table_heading}' table found in README "
                    "— the exported families must be documented where "
                    "operators look for them"
                ),
            )
            return
        for family, (rel, line) in sorted(literals.items()):
            if family not in rows:
                yield Finding(
                    rule=self.name, path=readme_rel, line=table_line,
                    col=0,
                    message=(
                        f"registered metric family `{family}` "
                        f"({rel}:{line}) missing from the README "
                        "metric-family catalog table"
                    ),
                )
        for pattern, raw, rel, line in sorted(
            patterns, key=lambda p: (p[1], p[2])
        ):
            if not any(pattern.match(r) for r in rows):
                yield Finding(
                    rule=self.name, path=rel, line=line, col=0,
                    message=(
                        f"f-string-registered family `{raw}` matches "
                        "no row of the README metric-family catalog "
                        "table — document each concrete family it "
                        "expands to"
                    ),
                )
        for family, line in sorted(rows.items()):
            if family in literals or family in mentioned:
                continue
            if any(p.match(family) for p, _, _, _ in patterns):
                continue
            yield Finding(
                rule=self.name, path=readme_rel, line=line, col=0,
                message=(
                    f"README catalogs metric family `{family}` that "
                    "nothing in the package registers"
                ),
            )


# -- registry ---------------------------------------------------------------

ALL_RULES = (
    GuardedByRule,
    BlockingUnderLockRule,
    StrippableAssertRule,
    AbsentNotZeroRule,
    HotPathHostSyncRule,
    FaultPointDriftRule,
    MetricFamilyDriftRule,
)


def default_rules() -> List[Rule]:
    return [cls() for cls in ALL_RULES]
