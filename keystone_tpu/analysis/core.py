"""Rule framework: file contexts, shared class/lock/scope resolution,
inline suppressions, the baseline store, and the analysis runner.

The division of labor mirrors the workflow optimizer it is modeled on
(``workflow/rules.py``: one ``Rule.apply`` per rewrite over a shared
``Graph`` IR): here the IR is a ``FileContext`` — parsed ``ast`` plus
the comment-derived side tables ``ast`` drops (``# lint:
disable=<rule>`` suppressions, ``# guarded-by: <lock>`` annotations) —
and every rule is a visitor over it. Cross-file rules (the fault-point
catalog drift check) run once over the whole ``Project`` after the
per-file pass.

Baseline discipline: a finding's identity is ``(path, rule, stripped
source line text, occurrence index)`` — NOT the line number, so
grandfathered findings survive unrelated edits above them and go stale
the moment the offending line itself changes (stale entries are
reported so the baseline shrinks monotonically instead of rotting).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# trailing or standalone suppression: `# lint: disable=rule[,rule]`.
# A standalone comment line suppresses the next code line (and itself);
# a trailing comment suppresses its own line.
_SUPPRESS_RE = re.compile(r"#\s*lint:\s*disable=([\w.,\-\s]+)")

# `self._attr = ... # guarded-by: _lock` — the annotation rule (1)
# reads; associated with the attribute assigned on the same line
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")

# with-items that count as taking a lock: `with self._lock:`,
# `with self._cond:`, `with _global_lock:` — a Name/Attribute whose
# terminal name contains lock/cond/mutex (or is a known lock attribute
# of the enclosing class, resolved by the rule)
_LOCKY_NAME_RE = re.compile(r"(lock|cond|mutex)", re.IGNORECASE)

# constructors that make an attribute a lock for class resolution
_LOCK_CTORS = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "Lock",
    "RLock",
    "Condition",
}


@dataclasses.dataclass
class Finding:
    """One rule violation, anchored to a source line."""

    rule: str
    path: str  # project-root-relative, forward slashes
    line: int
    col: int
    message: str
    line_text: str = ""  # stripped source of the line (baseline key)
    index: int = 0  # nth finding sharing (path, rule, line_text)
    # last physical line of the flagged node: a trailing suppression
    # on any line of a wrapped multi-line statement must still count
    # (not serialized — anchoring and baseline keys stay on `line`)
    end_line: int = 0

    def key(self) -> Tuple[str, str, str, int]:
        return (self.path, self.rule, self.line_text, self.index)

    def to_dict(self) -> Dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "line_text": self.line_text,
            "index": self.index,
        }

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule}: {self.message}"
        )


@dataclasses.dataclass
class ClassInfo:
    """Shared per-class resolution every lock rule reads."""

    name: str
    locks: Set[str] = dataclasses.field(default_factory=set)
    # attribute -> lock name it is annotated `# guarded-by:` with
    guarded: Dict[str, str] = dataclasses.field(default_factory=dict)


class FileContext:
    """One parsed file plus the comment side tables ``ast`` drops."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> set of rule names suppressed there ("*" = all)
        self.suppressions: Dict[int, Set[str]] = {}
        # line -> guarded-by lock name on that line's comment
        self.guarded_comments: Dict[int, str] = {}
        self._scan_comments()
        self.classes: Dict[str, ClassInfo] = {}
        # attr -> (class name, lock) when the attr is annotated in
        # exactly ONE class of this module — lets rule (1) check writes
        # through a non-self base (`_global_tracer._ring = ...`)
        self.unique_guarded: Dict[str, Tuple[str, str]] = {}
        self._resolve_classes()

    # -- comment side tables ------------------------------------------------

    def _scan_comments(self) -> None:
        # real COMMENT tokens only (tokenize): a string literal that
        # happens to contain "# lint: disable=..." must not become an
        # unreviewable escape hatch
        try:
            comments = [
                (tok.start[0], tok.string)
                for tok in tokenize.generate_tokens(
                    io.StringIO(self.source).readline
                )
                if tok.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # ast parsed it, tokenize didn't (pathological): no
            # comments rather than string-confused ones
            comments = []
        for i, text in comments:
            m = _SUPPRESS_RE.search(text)
            if m:
                rules = {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }
                target = i
                if self.lines[i - 1].lstrip().startswith("#"):
                    # standalone comment: suppress the next CODE line,
                    # skipping blanks and further comment lines (a
                    # justification comment may sit between the
                    # suppression and the code it covers)
                    j = i + 1
                    while j <= len(self.lines) and (
                        not self.lines[j - 1].strip()
                        or self.lines[j - 1].lstrip().startswith("#")
                    ):
                        j += 1
                    target = j
                    self.suppressions.setdefault(i, set()).update(rules)
                self.suppressions.setdefault(target, set()).update(rules)
            g = _GUARDED_RE.search(text)
            if g:
                self.guarded_comments[i] = g.group(1)

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self.suppressions.get(line)
        return bool(rules) and (rule in rules or "*" in rules)

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    # -- class / lock resolution --------------------------------------------

    def _resolve_classes(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            info = ClassInfo(name=node.name)
            for sub in ast.walk(node):
                if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (
                    sub.targets
                    if isinstance(sub, ast.Assign)
                    else [sub.target]
                )
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    value = sub.value
                    if value is not None and _is_lock_ctor(value):
                        info.locks.add(attr)
                    # the annotation may trail any line of a
                    # multi-line assignment (black-wrapped inits)
                    end = getattr(sub, "end_lineno", None) or sub.lineno
                    for ln in range(sub.lineno, end + 1):
                        lock = self.guarded_comments.get(ln)
                        if lock is not None:
                            info.guarded[attr] = lock
                            break
            self.classes[node.name] = info
        seen: Dict[str, List[Tuple[str, str]]] = {}
        for cname, info in self.classes.items():
            for attr, lock in info.guarded.items():
                seen.setdefault(attr, []).append((cname, lock))
        self.unique_guarded = {
            attr: owners[0]
            for attr, owners in seen.items()
            if len(owners) == 1
        }


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` target -> ``"X"``; anything else -> None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    try:
        fn = ast.unparse(node.func)
    except Exception:
        return False
    return fn in _LOCK_CTORS


def lock_expr_name(node: ast.AST, class_locks: Set[str]) -> Optional[str]:
    """If a ``with`` context expression looks like taking a lock,
    return its normalized source text (``self._lock``); else None.
    A Name/Attribute counts when its terminal name matches
    lock/cond/mutex or is a known lock attribute of the class."""
    expr = node
    # `with self._lock.acquire_timeout(...)` style: not supported —
    # only plain Name/Attribute context managers are lock-shaped
    if not isinstance(expr, (ast.Name, ast.Attribute)):
        return None
    terminal = expr.id if isinstance(expr, ast.Name) else expr.attr
    if _LOCKY_NAME_RE.search(terminal) or terminal in class_locks:
        try:
            return ast.unparse(expr)
        except Exception:
            return None
    return None


@dataclasses.dataclass
class Scope:
    """Lexical position during a scoped walk."""

    class_stack: List[str] = dataclasses.field(default_factory=list)
    func_stack: List[str] = dataclasses.field(default_factory=list)
    # normalized source text of every enclosing with-lock item
    lock_stack: List[str] = dataclasses.field(default_factory=list)
    # Name -> True for names bound as for-loop targets in scope
    loop_vars: Set[str] = dataclasses.field(default_factory=set)

    @property
    def cls(self) -> Optional[str]:
        return self.class_stack[-1] if self.class_stack else None

    @property
    def func(self) -> Optional[str]:
        return self.func_stack[-1] if self.func_stack else None

    def qualname(self) -> str:
        return ".".join(self.class_stack + self.func_stack)


class Rule:
    """One checked invariant. Subclasses set ``name``/``description``
    and override ``check_file`` (per-file) or ``check_project``
    (cross-file, runs once after every file parsed)."""

    name: str = "rule"
    description: str = ""

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_project(self, project: "Project") -> Iterable[Finding]:
        return ()


class ScopedRule(Rule):
    """Base for rules that need class/function/lock scope: drives one
    recursive walk per file and calls ``on_node`` with the live
    ``Scope`` at every node."""

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        scope = Scope()
        self._walk(ctx.tree, ctx, scope, findings)
        return findings

    def on_node(
        self,
        node: ast.AST,
        ctx: FileContext,
        scope: Scope,
        findings: List[Finding],
    ) -> None:
        raise NotImplementedError

    def _class_locks(self, ctx: FileContext, scope: Scope) -> Set[str]:
        info = ctx.classes.get(scope.cls) if scope.cls else None
        return info.locks if info else set()

    def _walk(
        self,
        node: ast.AST,
        ctx: FileContext,
        scope: Scope,
        findings: List[Finding],
    ) -> None:
        if isinstance(node, ast.ClassDef):
            scope.class_stack.append(node.name)
            for child in ast.iter_child_nodes(node):
                self._walk(child, ctx, scope, findings)
            scope.class_stack.pop()
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope.func_stack.append(node.name)
            for child in ast.iter_child_nodes(node):
                self._walk(child, ctx, scope, findings)
            scope.func_stack.pop()
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # items evaluate left-to-right with earlier locks already
            # held: `with self._lock, fut.result():` blocks under the
            # lock, so each item's expression is walked BEFORE later
            # items push — and after its own push, matching runtime
            pushed = 0
            for item in node.items:
                self._walk(item.context_expr, ctx, scope, findings)
                if item.optional_vars is not None:
                    self._walk(
                        item.optional_vars, ctx, scope, findings
                    )
                name = lock_expr_name(
                    item.context_expr, self._class_locks(ctx, scope)
                )
                if name is not None:
                    scope.lock_stack.append(name)
                    pushed += 1
            self.on_node(node, ctx, scope, findings)
            for child in node.body:
                self._walk(child, ctx, scope, findings)
            for _ in range(pushed):
                scope.lock_stack.pop()
            return
        if isinstance(
            node,
            (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp),
        ):
            added = []
            for gen in node.generators:
                for t in ast.walk(gen.target):
                    if (
                        isinstance(t, ast.Name)
                        and t.id not in scope.loop_vars
                    ):
                        scope.loop_vars.add(t.id)
                        added.append(t.id)
            self.on_node(node, ctx, scope, findings)
            for child in ast.iter_child_nodes(node):
                self._walk(child, ctx, scope, findings)
            for name in added:
                scope.loop_vars.discard(name)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            added = []
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name) and t.id not in scope.loop_vars:
                    scope.loop_vars.add(t.id)
                    added.append(t.id)
            self.on_node(node, ctx, scope, findings)
            for child in ast.iter_child_nodes(node):
                if child is not node.target:
                    self._walk(child, ctx, scope, findings)
            for name in added:
                scope.loop_vars.discard(name)
            return
        self.on_node(node, ctx, scope, findings)
        for child in ast.iter_child_nodes(node):
            self._walk(child, ctx, scope, findings)


class Project:
    """The file set one analysis run covers, plus the root the
    cross-file rules resolve their catalog/README/tests paths from."""

    def __init__(self, root: str, files: Sequence[FileContext]):
        self.root = os.path.abspath(root)
        self.files = list(files)
        self.by_rel = {f.rel: f for f in self.files}
        # parse errors surfaced as findings (path, message)
        self.errors: List[Finding] = []


def make_finding(
    rule: str, ctx: FileContext, node: ast.AST, message: str
) -> Finding:
    line = getattr(node, "lineno", 1)
    col = getattr(node, "col_offset", 0)
    return Finding(
        rule=rule,
        path=ctx.rel,
        line=line,
        col=col,
        message=message,
        line_text=ctx.line_text(line),
        end_line=getattr(node, "end_lineno", None) or line,
    )


# -- runner -----------------------------------------------------------------


def iter_python_files(root: str, paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            if full.endswith(".py"):
                out.append(full)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [
                d for d in dirnames
                if d != "__pycache__" and not d.startswith(".")
            ]
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    # stable order, no duplicates
    seen: Set[str] = set()
    uniq = []
    for f in sorted(out):
        if f not in seen:
            seen.add(f)
            uniq.append(f)
    return uniq


def build_project(root: str, paths: Sequence[str]) -> Project:
    root = os.path.abspath(root)
    files: List[FileContext] = []
    errors: List[Finding] = []
    for full in iter_python_files(root, paths):
        rel = os.path.relpath(full, root)
        try:
            with open(full, "r", encoding="utf-8") as fh:
                source = fh.read()
            files.append(FileContext(full, rel, source))
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(
                Finding(
                    rule="parse-error",
                    path=rel.replace(os.sep, "/"),
                    line=getattr(e, "lineno", None) or 1,
                    col=0,
                    message=f"could not parse: {e}",
                )
            )
    project = Project(root, files)
    project.errors = errors
    return project


@dataclasses.dataclass
class AnalysisResult:
    findings: List[Finding]  # live, unsuppressed (pre-baseline)
    suppressed: int

    def unbaselined(self, baseline: "Baseline") -> List[Finding]:
        return [f for f in self.findings if not baseline.covers(f)]


def run_analysis(
    root: str,
    paths: Sequence[str],
    rules: Sequence[Rule],
    project: Optional[Project] = None,
) -> AnalysisResult:
    """Parse ``paths`` under ``root``, run every rule, apply inline
    suppressions, and return the surviving findings (baseline handling
    is the caller's — the CLI and the self-clean test share it)."""
    if project is None:
        project = build_project(root, paths)
    raw: List[Finding] = list(project.errors)
    for ctx in project.files:
        for rule in rules:
            for f in rule.check_file(ctx):
                raw.append(f)
    for rule in rules:
        for f in rule.check_project(project):
            raw.append(f)
    live: List[Finding] = []
    suppressed = 0
    for f in raw:
        ctx = project.by_rel.get(f.path)
        if ctx is not None and any(
            ctx.suppressed(f.rule, ln)
            for ln in range(f.line, max(f.line, f.end_line) + 1)
        ):
            suppressed += 1
            continue
        live.append(f)
    live.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    # occurrence indices make duplicate line texts distinguishable in
    # the baseline (two identical offending lines in one file)
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in live:
        k = (f.path, f.rule, f.line_text)
        f.index = counts.get(k, 0)
        counts[k] = f.index + 1
    return AnalysisResult(findings=live, suppressed=suppressed)


# -- baseline ---------------------------------------------------------------


class Baseline:
    """Checked-in grandfathered findings. Each entry must carry a
    ``justification`` — the baseline is for violations that are *by
    design*, not a dumping ground; ``--write-baseline`` stamps a
    placeholder that review is expected to replace."""

    VERSION = 1

    def __init__(self, entries: Optional[List[Dict]] = None):
        self.entries: List[Dict] = entries or []
        self._keys = {
            (
                e.get("path", ""),
                e.get("rule", ""),
                e.get("line_text", ""),
                int(e.get("index", 0)),
            )
            for e in self.entries
        }

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
        if not isinstance(doc, dict) or "findings" not in doc:
            raise ValueError(
                f"{path}: not a keystone-lint baseline "
                "(want {'version': 1, 'findings': [...]})"
            )
        return cls(list(doc["findings"]))

    @classmethod
    def from_findings(
        cls, findings: Sequence[Finding],
        justification: str = "TODO: justify or fix",
    ) -> "Baseline":
        return cls(
            [
                {**f.to_dict(), "justification": justification}
                for f in findings
            ]
        )

    def save(self, path: str) -> None:
        doc = {
            "version": self.VERSION,
            "tool": "keystone-lint",
            "findings": self.entries,
        }
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=2, sort_keys=False)
            fh.write("\n")

    def covers(self, finding: Finding) -> bool:
        return finding.key() in self._keys

    def stale_entries(self, findings: Sequence[Finding]) -> List[Dict]:
        """Entries no longer matching any live finding — fixed (or the
        line changed); they should be deleted so the baseline only
        shrinks."""
        live = {f.key() for f in findings}
        return [
            e
            for e in self.entries
            if (
                e.get("path", ""),
                e.get("rule", ""),
                e.get("line_text", ""),
                int(e.get("index", 0)),
            )
            not in live
        ]

    def __len__(self) -> int:
        return len(self.entries)
