"""Text/speech dataset loaders.

Reference: loaders/NewsgroupsDataLoader.scala (per-class directories of
plaintext files), loaders/AmazonReviewsDataLoader.scala (JSON reviews,
rating threshold -> binary label), loaders/TimitFeaturesDataLoader.scala
(CSV features + "row label" sparse label files, 440 dims / 147 classes).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from keystone_tpu.loaders.csv_loader import LabeledData
from keystone_tpu.parallel.dataset import Dataset

NEWSGROUPS_CLASSES = [
    "comp.graphics",
    "comp.os.ms-windows.misc",
    "comp.sys.ibm.pc.hardware",
    "comp.sys.mac.hardware",
    "comp.windows.x",
    "rec.autos",
    "rec.motorcycles",
    "rec.sport.baseball",
    "rec.sport.hockey",
    "sci.crypt",
    "sci.electronics",
    "sci.med",
    "sci.space",
    "misc.forsale",
    "talk.politics.misc",
    "talk.politics.guns",
    "talk.politics.mideast",
    "talk.religion.misc",
    "alt.atheism",
    "soc.religion.christian",
]

TIMIT_DIMENSION = 440
TIMIT_NUM_CLASSES = 147


def NewsgroupsDataLoader(data_dir: str) -> LabeledData:
    """train_or_test_dir/class_label/docs as separate plaintext files."""
    labels: List[int] = []
    texts: List[str] = []
    for index, class_name in enumerate(NEWSGROUPS_CLASSES):
        class_dir = os.path.join(data_dir, class_name)
        if not os.path.isdir(class_dir):
            continue
        for fname in sorted(os.listdir(class_dir)):
            path = os.path.join(class_dir, fname)
            try:
                with open(path, errors="replace") as f:
                    texts.append(f.read())
                labels.append(index)
            except OSError:
                continue
    return LabeledData(
        labels=Dataset.from_array(jnp.asarray(labels, jnp.int32)),
        data=Dataset.from_items(texts),
    )


def AmazonReviewsDataLoader(path: str, threshold: float = 3.5) -> LabeledData:
    """JSON-lines reviews with "overall" and "reviewText" fields; label 1
    iff rating >= threshold."""
    labels: List[int] = []
    texts: List[str] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            labels.append(1 if float(row["overall"]) >= threshold else 0)
            texts.append(row["reviewText"])
    return LabeledData(
        labels=Dataset.from_array(jnp.asarray(labels, jnp.int32)),
        data=Dataset.from_items(texts),
    )


@dataclasses.dataclass
class TimitFeaturesData:
    train: LabeledData
    test: LabeledData


def _parse_sparse_labels(path: str) -> dict:
    out = {}
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                out[int(parts[0]) - 1] = int(parts[1])
    return out


def TimitFeaturesDataLoader(
    train_data_location: str,
    train_labels_location: str,
    test_data_location: str,
    test_labels_location: str,
) -> TimitFeaturesData:
    def load(data_path, labels_path):
        feats = np.loadtxt(data_path, delimiter=",", dtype=np.float32,
                           ndmin=2)
        label_map = _parse_sparse_labels(labels_path)
        labels = np.asarray(
            [label_map[i] - 1 for i in range(feats.shape[0])], np.int32
        )
        return LabeledData(
            labels=Dataset.from_array(jnp.asarray(labels)),
            data=Dataset.from_array(jnp.asarray(feats)),
        )

    return TimitFeaturesData(
        train=load(train_data_location, train_labels_location),
        test=load(test_data_location, test_labels_location),
    )
