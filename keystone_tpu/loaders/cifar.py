"""CIFAR-10 binary loader.

Reference: loaders/CifarLoader.scala:13 — parses the binary record format
(1 label byte + 3·1024 channel-plane bytes per image) driver-locally then
parallelizes. Images come out as (32, 32, 3) arrays indexed [x, y, c] with
x = row.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel.dataset import Dataset

CIFAR_DIM = 32
CIFAR_CHANNELS = 3
RECORD_LEN = 1 + CIFAR_DIM * CIFAR_DIM * CIFAR_CHANNELS


@dataclasses.dataclass
class LabeledImages:
    """(labels, images) pair — the CifarLoader output shape."""

    labels: Dataset
    images: Dataset


def CifarLoader(path: str) -> LabeledImages:
    from keystone_tpu.native import read_cifar

    import os

    if os.path.getsize(path) % RECORD_LEN != 0:
        raise ValueError(f"{path}: not a whole number of CIFAR records")
    labels, imgs = read_cifar(path, CIFAR_CHANNELS, CIFAR_DIM)
    return LabeledImages(
        labels=Dataset.from_array(jnp.asarray(labels)),
        images=Dataset.from_array(jnp.asarray(imgs)),
    )
