from keystone_tpu.loaders.csv_loader import CsvDataLoader, LabeledData

__all__ = ["CsvDataLoader", "LabeledData"]
