# LAZY re-exports (PEP 562) — see keystone_tpu/__init__.py: the
# streaming loader's spawn decode workers import this package and must
# not pull in jax (csv_loader -> parallel.dataset -> jax).
_EXPORTS = {
    "CsvDataLoader": "keystone_tpu.loaders.csv_loader",
    "LabeledData": "keystone_tpu.loaders.csv_loader",
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    import importlib

    if name in _EXPORTS:
        return getattr(importlib.import_module(_EXPORTS[name]), name)
    try:
        return importlib.import_module(f"{__name__}.{name}")
    except ModuleNotFoundError as e:
        if e.name == f"{__name__}.{name}":
            # the submodule itself doesn't exist -> attribute error
            raise AttributeError(
                f"module {__name__!r} has no attribute {name!r}"
            ) from None
        raise  # a real missing dependency inside the submodule
