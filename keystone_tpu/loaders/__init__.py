# LAZY re-exports (PEP 562) — see keystone_tpu/_lazy.py: the streaming
# loader's spawn decode workers import this package and must not pull
# in jax (csv_loader -> parallel.dataset -> jax).
from keystone_tpu._lazy import make_getattr

_EXPORTS = {
    "CsvDataLoader": "keystone_tpu.loaders.csv_loader",
    "LabeledData": "keystone_tpu.loaders.csv_loader",
}

__all__ = list(_EXPORTS)

__getattr__ = make_getattr(__name__, _EXPORTS)
