# LAZY re-exports (PEP 562) — see keystone_tpu/__init__.py: the
# streaming loader's spawn decode workers import this package and must
# not pull in jax (csv_loader -> parallel.dataset -> jax).
_EXPORTS = {
    "CsvDataLoader": "keystone_tpu.loaders.csv_loader",
    "LabeledData": "keystone_tpu.loaders.csv_loader",
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        return getattr(importlib.import_module(_EXPORTS[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
