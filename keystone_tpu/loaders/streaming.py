"""Out-of-core streaming image input pipeline.

Reference: loaders/ImageLoaderUtils.scala:22-47 — the reference never
materializes a dataset: it builds an RDD of tar-file paths, and each
executor streams its assigned tar archives member-by-member, decoding
one image at a time. ImageNetLoader.scala:11 / VOCLoader.scala:15 are
thin label-mapping wrappers over that stream.

TPU-native equivalent (no RDD): a host-side bounded pipeline per
process —

    tar paths ──(per-process shard: paths[rank::world])──▶ member bytes
      ──(window of decode futures, order-preserving)──▶ decoded arrays
      ──(fixed-shape assembly)──▶ (B, H, W, 3) float32 batches + labels

Memory is bounded by construction: at most ``decode_window`` raw/decoded
images plus one assembly batch are alive at any time, independent of the
dataset size — full ImageNet streams through a few hundred MB of host
RAM instead of the ~250 GB an eager load needs. Multi-host sharding is
by tar file, round-robin on ``jax.process_index()`` (the analogue of the
reference's file-path RDD partitioning): shards are disjoint and their
union is the whole dataset, so shard-and-sum statistics (Gram matrices,
label counts — everything the solvers consume) equal the single-reader
result exactly.

Decode uses JPEG draft mode when a target size is given: the DCT can be
decoded at 1/2, 1/4, 1/8 scale nearly for free, so a 256² target skips
most of the inverse transform of a full-resolution photo — decode is
the host bottleneck at ImageNet scale, and draft mode is the difference
between the pipeline feeding the chip or starving it. The default
decoder is the native libjpeg fast path (native/jpeg.cc, GIL-free so
decode_threads scale across cores); PIL is the per-image fallback.
"""

from __future__ import annotations

import csv
import io
import multiprocessing
import os
import tarfile
from collections import deque
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from typing import (
    Any,
    Callable,
    Dict,
    Iterator,
    List,
    Optional,
    Sequence,
    Tuple,
)

import numpy as np


def _decode_payload(args: Tuple[bytes, Optional[int]], use_native: bool = True):
    """Decode one image (standalone so process-pool workers can pickle
    it). Workers import only this module's PIL/numpy chain: the package
    ``__init__``s are lazy (PEP 562) precisely so unpickling this
    function does not drag jax into every worker. (A site-level hook
    that preloads jax — as this CI's axon site does — is outside the
    package's control; even then no jax BACKEND ever initializes in a
    worker.)

    When a fixed decode size is requested, the native libjpeg fast path
    (native/jpeg.cc via keystone_tpu.native) is tried first: it releases
    the GIL for the whole decode, so the THREAD pool scales across cores
    (measured on the fixture tar at 256²: 379 imgs/s/core native vs 264
    PIL, and threads add cores where PIL's GIL hold serializes them).
    Falls back to PIL per image (library unavailable, CMYK input,
    corrupt stream) — both paths decode the JPEG DCT at draft scale and
    triangle-resize to the target, matching within ±1/255 level."""
    data, decode_size = args
    if decode_size is not None and use_native:
        from keystone_tpu.native import jpeg_decode_f32

        arr = jpeg_decode_f32(data, decode_size)
        if arr is not None:
            return arr
    from PIL import Image as PILImage

    try:
        img = PILImage.open(io.BytesIO(data))
        if decode_size is not None:
            # draft: decode the JPEG DCT at the coarsest scale still
            # >= target — the decode-speed lever at ImageNet scale
            img.draft("RGB", (decode_size, decode_size))
        img = img.convert("RGB")
        if decode_size is not None:
            img = img.resize(
                (decode_size, decode_size), PILImage.BILINEAR
            )
        return np.asarray(img, dtype=np.float32)
    except Exception:
        return None

__all__ = [
    "StreamingImageLoader",
    "StreamingImageNetLoader",
    "StreamingVOCLoader",
    "imagenet_label_fn",
    "voc_label_fn",
    "tar_shard_paths",
]


def tar_shard_paths(
    location: str,
    shard_index: Optional[int] = None,
    num_shards: Optional[int] = None,
) -> List[str]:
    """Tar files under ``location`` assigned to this process's shard,
    round-robin by file (the file-path-RDD partitioning of
    ImageLoaderUtils.scala:22). Defaults to the jax process grid."""
    if os.path.isdir(location):
        paths = sorted(
            os.path.join(location, f)
            for f in os.listdir(location)
            if f.endswith(".tar")
        )
    else:
        paths = [location]
    if shard_index is None or num_shards is None:
        import jax

        shard_index = jax.process_index()
        num_shards = jax.process_count()
    return paths[shard_index::num_shards]


def imagenet_label_fn(labels_path: str) -> Callable[[str], Optional[int]]:
    """Member name -> class via the WNID map file ("n15075141 12" lines,
    ImageNetLoader.scala label map)."""
    label_map: Dict[str, int] = {}
    with open(labels_path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                label_map[parts[0]] = int(parts[1])

    def fn(name: str) -> Optional[int]:
        wnid = name.split("/")[0].split("_")[0]
        return label_map.get(wnid)

    return fn


def voc_label_fn(labels_path: str) -> Callable[[str], Optional[List[int]]]:
    """Member name -> multi-label class list via voclabels.csv
    (VOCLoader.scala:15)."""
    by_file: Dict[str, List[int]] = {}
    with open(labels_path) as f:
        for row in csv.DictReader(f):
            fname = row["filename"].split("/")[-1]
            by_file.setdefault(fname, []).append(int(row["class"]) - 1)

    def fn(name: str) -> Optional[List[int]]:
        return by_file.get(name.split("/")[-1])

    return fn


class StreamingImageLoader:
    """Bounded-memory tar → batch pipeline (see module docstring).

    Args:
      paths: tar files THIS process reads (use ``tar_shard_paths`` for
        the multi-host round-robin assignment).
      label_fn: member name -> label (int, list, or any object); None
        skips the member (reference: unmapped WNIDs are dropped).
      decode_size: if set, every image is decoded+resized to
        (decode_size, decode_size, 3) so batches are fixed-shape arrays;
        None keeps native sizes (``items()`` iteration only).
      cycle: read the tar list this many times (bench mode: a small
        fixture tar cycled to ImageNet-scale image counts).
      decode_threads / decode_window: decode pool size and the bound on
        in-flight images (the RSS bound).
      decode_processes: when > 0, decode in a spawn-based PROCESS pool
        of this size instead of threads. With the native libjpeg path
        (the default when decode_size is set) the THREAD pool already
        scales across cores — the C decode releases the GIL — so
        processes only pay off on the PIL fallback path, where
        PIL+numpy conversion holds the GIL enough that thread decoding
        saturates ~1 core (measured at 256² on the fixture tar: 379
        imgs/s/core native, 264 imgs/s/core PIL). Workers never
        initialize a jax backend.
      use_native_decode: use native/jpeg.cc (DCT-draft decode +
        triangle resize, ±1 level vs PIL) when decode_size is set;
        False forces the PIL path (parity testing).
    """

    def __init__(
        self,
        paths: Sequence[str],
        label_fn: Callable[[str], Optional[object]],
        decode_size: Optional[int] = None,
        cycle: int = 1,
        decode_threads: int = 8,
        decode_window: int = 64,
        limit: Optional[int] = None,
        decode_processes: int = 0,
        use_native_decode: bool = True,
    ):
        self.paths = list(paths)
        self.label_fn = label_fn
        self.decode_size = decode_size
        self.cycle = cycle
        self.decode_threads = decode_threads
        self.decode_window = decode_window
        self.limit = limit
        self.decode_processes = decode_processes
        self.use_native_decode = use_native_decode

    # -- raw member stream -------------------------------------------------

    def _iter_raw(self) -> Iterator[Tuple[str, object, bytes]]:
        """(name, label, jpeg bytes) for labeled members, streamed one
        tar member at a time (tarfile reads sequentially; nothing is
        extracted to disk or held beyond the current member)."""
        emitted = 0
        for _ in range(self.cycle):
            for path in self.paths:
                with tarfile.open(path) as tf:
                    for member in tf:
                        if not member.isfile():
                            continue
                        label = self.label_fn(member.name)
                        if label is None:
                            continue
                        f = tf.extractfile(member)
                        if f is None:
                            continue
                        yield member.name, label, f.read()
                        emitted += 1
                        if self.limit is not None and emitted >= self.limit:
                            return

    def items(self) -> Iterator[Tuple[str, object, np.ndarray]]:
        """Order-preserving decoded stream with a bounded window of
        decode futures in flight (the eager loaders' list materialized
        one element at a time)."""
        # both pools run the same module-level _decode_payload through
        # the concurrent.futures API: ProcessPoolExecutor (vs
        # multiprocessing.Pool) raises BrokenProcessPool if a spawn
        # worker is OOM-killed or segfaults mid-decode instead of
        # hanging the in-flight .get() forever
        if self.decode_processes > 0:
            ex = ProcessPoolExecutor(
                self.decode_processes,
                mp_context=multiprocessing.get_context("spawn"),
            )
        else:
            ex = ThreadPoolExecutor(self.decode_threads)
        with ex:
            yield from self._bounded_ordered_decode(
                lambda data: ex.submit(
                    _decode_payload,
                    (data, self.decode_size),
                    self.use_native_decode,
                ),
                lambda fut: fut.result(),
            )

    def _bounded_ordered_decode(
        self, submit, get
    ) -> Iterator[Tuple[str, object, np.ndarray]]:
        """The one window invariant both pools share: at most
        ``decode_window`` decodes in flight, results yielded in
        submission order, failed decodes skipped."""
        pending: deque = deque()
        for name, label, data in self._iter_raw():
            pending.append((name, label, submit(data)))
            if len(pending) >= self.decode_window:
                n, l, handle = pending.popleft()
                arr = get(handle)
                if arr is not None:
                    yield n, l, arr
        while pending:
            n, l, handle = pending.popleft()
            arr = get(handle)
            if arr is not None:
                yield n, l, arr

    # -- fixed-shape batches ----------------------------------------------

    def batches(
        self, batch_size: int, dtype=np.float32
    ) -> Iterator[Tuple[np.ndarray, List[object], int]]:
        """(images (B, s, s, 3) ``dtype``, labels, n_valid) batches; the
        final batch is zero-padded past n_valid. Requires decode_size.
        ``dtype=np.uint8`` quarters the batch's footprint — the right
        feed when the device program starts with a cast anyway (H2D
        transfer of raw pixels is the narrow stage on remote-attached
        devices)."""
        if self.decode_size is None:
            raise ValueError("batches() requires decode_size")
        s = self.decode_size
        buf = np.zeros((batch_size, s, s, 3), dtype)
        labels: List[object] = []
        fill = 0
        for _, label, arr in self.items():
            buf[fill] = arr  # stores cast decode's f32 to ``dtype``
            labels.append(label)
            fill += 1
            if fill == batch_size:
                yield buf, labels, fill
                buf = np.zeros((batch_size, s, s, 3), dtype)
                labels = []
                fill = 0
        if fill:
            yield buf, labels, fill

    def featurized_batches(
        self, engine, batch_size: int
    ) -> Iterator[Tuple[Any, List[object], int]]:
        """(features (B, F) device array, labels, n_valid) batches:
        the decode stream feeds RAW uint8 into a fused serving engine
        (``CompiledPipeline`` — typically a frozen featurize chain
        ``compiled()``, or a model engine with ``featurize=``), so the
        H2D wire carries pixels, not f32 features, and cast + featurize
        run inside the engine's per-bucket XLA program. This is the
        TRAINING loaders' route onto the same fused featurize
        implementation the serving gateway runs — one chain, one set of
        compiled programs, one ``h2d_bytes`` accounting, fit and serve.

        Dispatch is async (the engine enqueues; decode of batch k+1
        overlaps device compute of batch k). The final short batch is
        served zero-padded at ``batch_size`` rows — the engine pads to
        a bucket anyway, and a constant batch shape keeps the compile
        count at one program; slice features to ``n_valid``. Callers
        own the sync point (materialize the yielded arrays)."""
        for buf, labels, n_valid in self.batches(batch_size, np.uint8):
            yield engine.apply(buf), labels, n_valid


def StreamingImageNetLoader(
    location: str,
    labels_path: str,
    decode_size: Optional[int] = None,
    shard_index: Optional[int] = None,
    num_shards: Optional[int] = None,
    **kw,
) -> StreamingImageLoader:
    """Sharded streaming ImageNet reader (ImageNetLoader.scala:11 over
    the streaming substrate)."""
    return StreamingImageLoader(
        tar_shard_paths(location, shard_index, num_shards),
        imagenet_label_fn(labels_path),
        decode_size=decode_size,
        **kw,
    )


def StreamingVOCLoader(
    location: str,
    labels_path: str,
    decode_size: Optional[int] = None,
    shard_index: Optional[int] = None,
    num_shards: Optional[int] = None,
    **kw,
) -> StreamingImageLoader:
    """Sharded streaming VOC2007 reader (VOCLoader.scala:15 over the
    streaming substrate)."""
    return StreamingImageLoader(
        tar_shard_paths(location, shard_index, num_shards),
        voc_label_fn(labels_path),
        decode_size=decode_size,
        **kw,
    )
