"""CSV loading + labeled-data pair holder.

Reference: loaders/CsvDataLoader.scala:10 (textFile -> split -> DenseVector)
and loaders/LabeledData.scala:12 (labeled-RDD pair holder). Host-side IO
feeding a sharded device array — the input-pipeline stand-in for RDD reads.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel.dataset import Dataset


def CsvDataLoader(path: str, delimiter: str = ",") -> Dataset:
    """Load a numeric CSV into one array-mode Dataset (n, d). Uses the
    native multi-threaded parser when built (keystone_tpu/native.py)."""
    from keystone_tpu.native import read_csv_f32

    arr = read_csv_f32(path, delimiter=delimiter)
    return Dataset.from_array(jnp.asarray(arr))


@dataclasses.dataclass
class LabeledData:
    """Holds (labels, data) with convenience accessors (reference:
    loaders/LabeledData.scala)."""

    labels: Dataset
    data: Dataset

    @staticmethod
    def from_csv(
        path: str,
        label_col: int = 0,
        label_offset: int = 0,
        delimiter: str = ",",
    ) -> "LabeledData":
        """First (or ``label_col``-th) column is the integer label;
        ``label_offset`` is subtracted (MNIST CSVs are 1-indexed in the
        reference app, MnistRandomFFT.scala:31-38)."""
        arr = np.loadtxt(path, delimiter=delimiter, dtype=np.float32, ndmin=2)
        labels = arr[:, label_col].astype(np.int32) - label_offset
        data = np.delete(arr, label_col, axis=1)
        return LabeledData(
            labels=Dataset.from_array(jnp.asarray(labels)),
            data=Dataset.from_array(jnp.asarray(data)),
        )

    @staticmethod
    def of(labels, data) -> "LabeledData":
        return LabeledData(labels=Dataset.of(labels), data=Dataset.of(data))
