"""ImageNet / VOC tar-archive image loaders.

Reference: loaders/ImageNetLoader.scala:11 (tar archives -> labeled images
via a WNID->class map file), loaders/ImageLoaderUtils.scala:22-47
(per-file tar streaming + decode), loaders/VOCLoader.scala:15 (VOC2007
multi-label tar loader + voclabels.csv).

Host-side streaming IO feeding device arrays — the input-pipeline side of
the framework. Images decode to (x=row, y=col, c) float arrays.
"""

from __future__ import annotations

import csv
import dataclasses
import io
import os
import tarfile
from typing import Dict, List, Optional, Sequence

import numpy as np

from keystone_tpu.parallel.dataset import Dataset

NUM_IMAGENET_CLASSES = 1000


def _decode(data: bytes) -> Optional[np.ndarray]:
    from PIL import Image as PILImage

    try:
        img = PILImage.open(io.BytesIO(data))
        img = img.convert("RGB")
        return np.asarray(img, dtype=np.float32)
    except Exception:
        return None


def _iter_tar_images(path: str):
    with tarfile.open(path) as tf:
        for member in tf:
            if not member.isfile():
                continue
            f = tf.extractfile(member)
            if f is None:
                continue
            arr = _decode(f.read())
            if arr is not None:
                yield member.name, arr


@dataclasses.dataclass
class LabeledImage:
    image: np.ndarray
    label: int
    filename: str = ""


def _tar_paths(location: str) -> List[str]:
    if os.path.isdir(location):
        return sorted(
            os.path.join(location, f)
            for f in os.listdir(location)
            if f.endswith(".tar")
        )
    return [location]


def ImageNetLoader(location: str, labels_path: str) -> Dataset:
    """Load labeled ImageNet images from tar archive(s). ``labels_path``
    maps WNID -> integer class ("n15075141 12" lines, reference:
    ImageNetLoader.scala label map)."""
    label_map: Dict[str, int] = {}
    with open(labels_path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                label_map[parts[0]] = int(parts[1])
    items: List[LabeledImage] = []
    for tar in _tar_paths(location):
        for name, arr in _iter_tar_images(tar):
            wnid = name.split("/")[0].split("_")[0]
            label = label_map.get(wnid)
            if label is None:
                continue
            items.append(LabeledImage(arr, label, name))
    return Dataset.from_items(items)


def VOCLoader(location: str, labels_path: str) -> Dataset:
    """VOC2007 loader: labels CSV has (id, class, classname, traintesteval,
    filename) rows; an image may appear under several classes (multi-label,
    reference: VOCLoader.scala:15)."""
    by_file: Dict[str, List[int]] = {}
    with open(labels_path) as f:
        for row in csv.DictReader(f):
            fname = row["filename"].split("/")[-1]
            by_file.setdefault(fname, []).append(int(row["class"]) - 1)
    items: List[LabeledImage] = []
    for tar in _tar_paths(location):
        for name, arr in _iter_tar_images(tar):
            fname = name.split("/")[-1]
            if fname in by_file:
                items.append(
                    LabeledImage(arr, -1, fname)
                )
                items[-1].labels = by_file[fname]  # multi-label
    return Dataset.from_items(items)


class ImageExtractor:
    """LabeledImage dataset -> image dataset (reference:
    utils/LabeledImageExtractors)."""

    @staticmethod
    def apply(ds: Dataset) -> Dataset:
        return ds.map(lambda li: li.image)

    def __call__(self, ds: Dataset) -> Dataset:
        return self.apply(ds)


class LabelExtractor:
    @staticmethod
    def apply(ds: Dataset) -> Dataset:
        import jax.numpy as jnp

        return Dataset.from_array(
            jnp.asarray([li.label for li in ds.items()], jnp.int32)
        )

    def __call__(self, ds: Dataset) -> Dataset:
        return self.apply(ds)


class MultiLabelExtractor:
    @staticmethod
    def apply(ds: Dataset) -> Dataset:
        return ds.map(lambda li: np.asarray(getattr(li, "labels", [li.label])))

    def __call__(self, ds: Dataset) -> Dataset:
        return self.apply(ds)
