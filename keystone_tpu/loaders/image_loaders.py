"""ImageNet / VOC tar-archive image loaders.

Reference: loaders/ImageNetLoader.scala:11 (tar archives -> labeled images
via a WNID->class map file), loaders/ImageLoaderUtils.scala:22-47
(per-file tar streaming + decode), loaders/VOCLoader.scala:15 (VOC2007
multi-label tar loader + voclabels.csv).

These are the EAGER loaders (materialize a ``Dataset`` of decoded
images) for datasets that fit in host RAM — tests, CIFAR-scale work,
fixture tars. They are thin collectors over the out-of-core streaming
substrate in ``loaders/streaming.py``; at ImageNet scale use
``StreamingImageNetLoader`` directly and never materialize.

Images decode to (x=row, y=col, c) float arrays.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from keystone_tpu.loaders.streaming import (
    StreamingImageLoader,
    imagenet_label_fn,
    tar_shard_paths,
    voc_label_fn,
)
from keystone_tpu.parallel.dataset import Dataset

NUM_IMAGENET_CLASSES = 1000


@dataclasses.dataclass
class LabeledImage:
    image: np.ndarray
    label: int
    filename: str = ""


def ImageNetLoader(location: str, labels_path: str) -> Dataset:
    """Load labeled ImageNet images from tar archive(s). ``labels_path``
    maps WNID -> integer class ("n15075141 12" lines, reference:
    ImageNetLoader.scala label map)."""
    stream = StreamingImageLoader(
        tar_shard_paths(location, 0, 1), imagenet_label_fn(labels_path)
    )
    return Dataset.from_items(
        [LabeledImage(arr, label, name) for name, label, arr in stream.items()]
    )


def VOCLoader(location: str, labels_path: str) -> Dataset:
    """VOC2007 loader: labels CSV has (id, class, classname, traintesteval,
    filename) rows; an image may appear under several classes (multi-label,
    reference: VOCLoader.scala:15)."""
    stream = StreamingImageLoader(
        tar_shard_paths(location, 0, 1), voc_label_fn(labels_path)
    )
    items = []
    for name, labels, arr in stream.items():
        li = LabeledImage(arr, -1, name.split("/")[-1])
        li.labels = labels  # multi-label
        items.append(li)
    return Dataset.from_items(items)


class ImageExtractor:
    """LabeledImage dataset -> image dataset (reference:
    utils/LabeledImageExtractors)."""

    @staticmethod
    def apply(ds: Dataset) -> Dataset:
        return ds.map(lambda li: li.image)

    def __call__(self, ds: Dataset) -> Dataset:
        return self.apply(ds)


class LabelExtractor:
    @staticmethod
    def apply(ds: Dataset) -> Dataset:
        import jax.numpy as jnp

        return Dataset.from_array(
            jnp.asarray([li.label for li in ds.items()], jnp.int32)
        )

    def __call__(self, ds: Dataset) -> Dataset:
        return self.apply(ds)


class MultiLabelExtractor:
    @staticmethod
    def apply(ds: Dataset) -> Dataset:
        return ds.map(lambda li: np.asarray(getattr(li, "labels", [li.label])))

    def __call__(self, ds: Dataset) -> Dataset:
        return self.apply(ds)
