"""VOC-style 11-point interpolated mean average precision.

Reference: evaluation/MeanAveragePrecisionEvaluator.scala:11.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from keystone_tpu.parallel.dataset import Dataset


class MeanAveragePrecisionEvaluator:
    """evaluate(actuals: list of per-example positive-class index arrays,
    scores: (n, classes) score matrix) -> (classes,) per-class AP."""

    def __init__(self, num_classes: int):
        self.num_classes = num_classes

    def evaluate(self, actuals: Any, scores: Any) -> np.ndarray:
        if hasattr(actuals, "get"):
            actuals = actuals.get()
        if hasattr(scores, "get"):
            scores = scores.get()
        if isinstance(actuals, Dataset):
            actuals = actuals.items()
        if isinstance(scores, Dataset):
            scores = scores.array()
        scores = np.asarray(scores)
        n = scores.shape[0]
        aps = np.zeros(self.num_classes)
        for c in range(self.num_classes):
            labels = np.array(
                [c in np.atleast_1d(np.asarray(a)) for a in actuals]
            )
            aps[c] = self._average_precision(scores[:, c], labels)
        return aps

    __call__ = evaluate

    @staticmethod
    def _average_precision(scores: np.ndarray, labels: np.ndarray) -> float:
        """11-point interpolated AP (VOC2007 convention, matching the
        reference's implementation)."""
        order = np.argsort(-scores, kind="stable")
        sorted_labels = labels[order]
        tp = np.cumsum(sorted_labels)
        n_pos = labels.sum()
        if n_pos == 0:
            return 0.0
        recall = tp / n_pos
        precision = tp / np.arange(1, len(scores) + 1)
        ap = 0.0
        for t in np.linspace(0, 1, 11):
            mask = recall >= t
            p = precision[mask].max() if mask.any() else 0.0
            ap += p / 11.0
        return float(ap)
