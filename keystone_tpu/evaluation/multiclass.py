"""Multiclass evaluation — one-pass confusion matrix + derived metrics.

Reference: evaluation/MulticlassClassifierEvaluator.scala:22,123 (RDD
``aggregate`` of a confusion matrix; micro/macro precision/recall/F1;
Mahout-style pretty-print). Here the confusion matrix is one scatter-add
over the sharded prediction/label arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel.dataset import Dataset


@dataclasses.dataclass
class MulticlassMetrics:
    confusion_matrix: np.ndarray  # (classes, classes); [actual, predicted]

    @property
    def num_classes(self) -> int:
        return self.confusion_matrix.shape[0]

    @property
    def total(self) -> float:
        return float(self.confusion_matrix.sum())

    def class_metrics(self, c: int) -> "BinaryMetricsView":
        cm = self.confusion_matrix
        tp = cm[c, c]
        fp = cm[:, c].sum() - tp
        fn = cm[c, :].sum() - tp
        tn = self.total - tp - fp - fn
        return BinaryMetricsView(tp, fp, tn, fn)

    @property
    def total_accuracy(self) -> float:
        return float(np.trace(self.confusion_matrix) / max(self.total, 1.0))

    @property
    def total_error(self) -> float:
        return 1.0 - self.total_accuracy

    # micro-averaged metrics equal total accuracy in single-label multiclass
    @property
    def micro_precision(self) -> float:
        return self.total_accuracy

    @property
    def micro_recall(self) -> float:
        return self.total_accuracy

    @property
    def micro_f1(self) -> float:
        return self.total_accuracy

    def _macro(self, f) -> float:
        return float(
            np.mean([f(self.class_metrics(c)) for c in range(self.num_classes)])
        )

    @property
    def macro_precision(self) -> float:
        return self._macro(lambda m: m.precision)

    @property
    def macro_recall(self) -> float:
        return self._macro(lambda m: m.recall)

    @property
    def macro_f1(self) -> float:
        return self._macro(lambda m: m.f1)

    def summary(self, class_names: Optional[list] = None) -> str:
        """Mahout-style text summary (reference:
        MulticlassClassifierEvaluator.scala pprint)."""
        lines = [
            f"Accuracy: {self.total_accuracy:.4f}",
            f"Error: {self.total_error:.4f}",
            f"Macro Precision/Recall/F1: "
            f"{self.macro_precision:.4f}/{self.macro_recall:.4f}/{self.macro_f1:.4f}",
            "Confusion matrix (rows=actual, cols=predicted):",
        ]
        names = class_names or [str(i) for i in range(self.num_classes)]
        header = "\t" + "\t".join(names)
        lines.append(header)
        for i, row in enumerate(self.confusion_matrix.astype(np.int64)):
            lines.append(names[i] + "\t" + "\t".join(str(v) for v in row))
        return "\n".join(lines)


@dataclasses.dataclass
class BinaryMetricsView:
    tp: float
    fp: float
    tn: float
    fn: float

    @property
    def precision(self) -> float:
        d = self.tp + self.fp
        return float(self.tp / d) if d else 1.0

    @property
    def recall(self) -> float:
        d = self.tp + self.fn
        return float(self.tp / d) if d else 1.0

    @property
    def f1(self) -> float:
        p, r = self.precision, self.recall
        return 2 * p * r / (p + r) if (p + r) else 0.0

    @property
    def accuracy(self) -> float:
        t = self.tp + self.fp + self.tn + self.fn
        return float((self.tp + self.tn) / t) if t else 0.0


class MulticlassClassifierEvaluator:
    """evaluate(predictions, labels) -> MulticlassMetrics. Accepts
    PipelineResults, Datasets, or arrays of int class ids."""

    def __init__(self, num_classes: int):
        self.num_classes = num_classes

    def evaluate(self, predictions: Any, labels: Any) -> MulticlassMetrics:
        pred = _to_int_array(predictions)
        lab = _to_int_array(labels)
        if pred.shape[0] != lab.shape[0]:
            raise ValueError(
                f"length mismatch: {pred.shape[0]} vs {lab.shape[0]}"
            )
        c = self.num_classes
        # int32 accumulator: float32 counts would saturate at 2^24
        cm = jnp.zeros((c, c), jnp.int32).at[lab, pred].add(1)
        return MulticlassMetrics(np.asarray(cm, dtype=np.float64))

    __call__ = evaluate


def _to_int_array(x: Any) -> jnp.ndarray:
    if hasattr(x, "get"):  # PipelineResult
        x = x.get()
    if isinstance(x, Dataset):
        x = x.array()
    return jnp.asarray(np.asarray(x).reshape(-1), jnp.int32)
