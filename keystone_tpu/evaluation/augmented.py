"""Merge predictions over augmented copies of each example.

Reference: evaluation/AugmentedExamplesEvaluator.scala:9 — group the
augmented copies by source image id, combine per-class scores by averaging
(or Borda rank counting), then evaluate multiclass metrics on the merged
predictions.
"""

from __future__ import annotations

import enum
from typing import Any, Sequence

import numpy as np

from keystone_tpu.evaluation.multiclass import (
    MulticlassClassifierEvaluator,
    MulticlassMetrics,
)
from keystone_tpu.parallel.dataset import Dataset


class AggregationPolicy(enum.Enum):
    average = "average"
    borda = "borda"


class AugmentedExamplesEvaluator:
    def __init__(
        self,
        names: Sequence[Any],
        num_classes: int,
        policy: AggregationPolicy = AggregationPolicy.average,
    ):
        self.names = list(names)
        self.num_classes = num_classes
        self.policy = policy

    def evaluate(self, scores: Any, labels: Any) -> MulticlassMetrics:
        """``scores``: (n_augmented, classes); ``labels``: (n_augmented,)
        int class ids; ``self.names[i]`` identifies the source example of
        augmented row i."""
        if hasattr(scores, "get"):
            scores = scores.get()
        if isinstance(scores, Dataset):
            scores = scores.array()
        if hasattr(labels, "get"):
            labels = labels.get()
        if isinstance(labels, Dataset):
            labels = labels.array()
        scores = np.asarray(scores)
        labels = np.asarray(labels).reshape(-1)

        by_name: dict = {}
        for i, name in enumerate(self.names):
            by_name.setdefault(name, []).append(i)

        merged_preds, merged_labels = [], []
        for name, idxs in by_name.items():
            s = scores[idxs]
            if self.policy is AggregationPolicy.average:
                combined = s.mean(axis=0)
            else:  # borda: sum of per-copy ranks
                combined = np.argsort(np.argsort(s, axis=1), axis=1).sum(axis=0)
            merged_preds.append(int(np.argmax(combined)))
            merged_labels.append(int(labels[idxs[0]]))
        ev = MulticlassClassifierEvaluator(self.num_classes)
        return ev.evaluate(np.asarray(merged_preds), np.asarray(merged_labels))

    __call__ = evaluate
