from keystone_tpu.evaluation.multiclass import (
    MulticlassClassifierEvaluator,
    MulticlassMetrics,
)
from keystone_tpu.evaluation.binary import (
    BinaryClassifierEvaluator,
    BinaryClassificationMetrics,
)
from keystone_tpu.evaluation.mean_average_precision import (
    MeanAveragePrecisionEvaluator,
)
from keystone_tpu.evaluation.augmented import AugmentedExamplesEvaluator

__all__ = [
    "AugmentedExamplesEvaluator",
    "BinaryClassificationMetrics",
    "BinaryClassifierEvaluator",
    "MeanAveragePrecisionEvaluator",
    "MulticlassClassifierEvaluator",
    "MulticlassMetrics",
]
