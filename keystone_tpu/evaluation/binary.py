"""Binary evaluation — one-pass contingency table.

Reference: evaluation/BinaryClassifierEvaluator.scala:17,59.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from keystone_tpu.evaluation.multiclass import BinaryMetricsView
from keystone_tpu.parallel.dataset import Dataset


@dataclasses.dataclass
class BinaryClassificationMetrics(BinaryMetricsView):
    @property
    def specificity(self) -> float:
        d = self.tn + self.fp
        return float(self.tn / d) if d else 1.0

    def summary(self) -> str:
        return (
            f"Accuracy: {self.accuracy:.4f}  Precision: {self.precision:.4f}"
            f"  Recall: {self.recall:.4f}  F1: {self.f1:.4f}"
        )


class BinaryClassifierEvaluator:
    """evaluate(predictions: bool, labels: bool) -> metrics."""

    def evaluate(self, predictions: Any, labels: Any) -> BinaryClassificationMetrics:
        pred = _to_bool(predictions)
        lab = _to_bool(labels)
        if pred.shape[0] != lab.shape[0]:
            raise ValueError("length mismatch")
        tp = float(np.sum(pred & lab))
        fp = float(np.sum(pred & ~lab))
        fn = float(np.sum(~pred & lab))
        tn = float(np.sum(~pred & ~lab))
        return BinaryClassificationMetrics(tp, fp, tn, fn)

    __call__ = evaluate


def _to_bool(x: Any) -> np.ndarray:
    if hasattr(x, "get"):
        x = x.get()
    if isinstance(x, Dataset):
        x = x.array()
    return np.asarray(x).reshape(-1).astype(bool)
