"""Gateway instrumentation: one handle bundle over the global registry.

Every gateway series is a REGISTRY metric (counters / gauges /
histograms in ``observability/registry.py``), not a ``ServingMetrics``
clone: the gateway is control plane, its counters are few and labeled,
and the two latency series use the native-histogram type
(``RegistryHistogram``) precisely because gateway quantiles must
aggregate across replicas and scrapes — ``le`` buckets add, summary
quantiles don't.

Families (all carry a ``gateway`` label so several gateways in one
process stay distinguishable; get-or-create semantics make the handles
shared):

- ``keystone_gateway_requests_total{gateway,status}`` — terminal
  request outcomes: ``ok`` | ``shed`` | ``error``.
- ``keystone_gateway_shed_total{gateway,reason}`` — load-shed detail:
  ``queue_full`` | ``slo_pressure`` | ``deadline`` | ``expired`` |
  ``closed``.
- ``keystone_gateway_retries_total{gateway}`` — lane-failure retries.
- ``keystone_gateway_engine_swaps_total{gateway}`` — live re-buckets.
- ``keystone_gateway_queue_depth{gateway}`` / ``_inflight`` /
  ``_ready`` / ``_slo_pressure`` gauges.
- ``keystone_gateway_queue_wait_seconds`` /
  ``keystone_gateway_request_latency_seconds`` histograms; the latency
  histogram's buckets carry ``trace_id`` OpenMetrics exemplars when the
  request was traced, linking the aggregate to ``/debugz`` forensics.
"""

from __future__ import annotations

from typing import Optional

from keystone_tpu.observability.registry import (
    MetricsRegistry,
    get_global_registry,
)


class GatewayMetrics:
    """Pre-resolved metric handles for one named gateway."""

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        gateway: str = "gateway",
    ):
        reg = registry if registry is not None else get_global_registry()
        self.registry = reg
        self.gateway = gateway
        self._requests = reg.counter(
            "keystone_gateway_requests_total",
            "terminal request outcomes through the gateway",
            ("gateway", "status"),
        )
        self._shed = reg.counter(
            "keystone_gateway_shed_total",
            "requests rejected by admission control, by reason",
            ("gateway", "reason"),
        )
        self._retries = reg.counter(
            "keystone_gateway_retries_total",
            "requests retried on another lane after a lane failure",
            ("gateway",),
        )
        self._swaps = reg.counter(
            "keystone_gateway_engine_swaps_total",
            "live engine swaps (re-bucket / replacement) completed",
            ("gateway",),
        )
        self._queue_depth = reg.gauge(
            "keystone_gateway_queue_depth",
            "requests admitted but not yet routed to a lane",
            ("gateway",),
        )
        self._inflight = reg.gauge(
            "keystone_gateway_inflight",
            "requests routed to a lane and not yet resolved",
            ("gateway",),
        )
        self._ready = reg.gauge(
            "keystone_gateway_ready",
            "1 while the gateway admits traffic, 0 once draining",
            ("gateway",),
        )
        self._slo_pressure = reg.gauge(
            "keystone_gateway_slo_pressure",
            "admission tightening applied by the SLO burn watchdog "
            "(0 = none, toward 1 = queue bound shrunk)",
            ("gateway",),
        )
        self.queue_wait = reg.histogram(
            "keystone_gateway_queue_wait_seconds",
            "admission-queue wait (admit to lane hand-off)",
            ("gateway",),
        )
        self.request_latency = reg.histogram(
            "keystone_gateway_request_latency_seconds",
            "end-to-end gateway request latency (admit to resolution)",
            ("gateway",),
        )
        self.set_ready(False)
        self.set_queue_depth(0)
        self.set_inflight(0)
        self.set_slo_pressure(0.0)

    @property
    def requests_total(self):
        """The outcome counter handle (the availability SLO reads it)."""
        return self._requests

    # -- thin label-bound helpers (hot path: one tuple + one inc) ----------

    def record_outcome(self, status: str) -> None:
        self._requests.inc((self.gateway, status))

    def record_shed(self, reason: str) -> None:
        self._shed.inc((self.gateway, reason))
        self._requests.inc((self.gateway, "shed"))

    def record_retry(self) -> None:
        self._retries.inc((self.gateway,))

    def record_swap(self) -> None:
        self._swaps.inc((self.gateway,))

    def record_queue_wait(self, seconds: float) -> None:
        self.queue_wait.observe(seconds, (self.gateway,))

    def record_latency(
        self, seconds: float, trace_id: Optional[str] = None
    ) -> None:
        self.request_latency.observe(
            seconds, (self.gateway,), trace_id=trace_id
        )

    def set_slo_pressure(self, pressure: float) -> None:
        self._slo_pressure.set(pressure, (self.gateway,))

    def set_queue_depth(self, depth: int) -> None:
        self._queue_depth.set(depth, (self.gateway,))

    def set_inflight(self, n: int) -> None:
        self._inflight.set(n, (self.gateway,))

    def set_ready(self, ready: bool) -> None:
        self._ready.set(1.0 if ready else 0.0, (self.gateway,))

    # -- test/debug conveniences -------------------------------------------

    def shed_count(self, reason: str) -> float:
        return self._shed.get((self.gateway, reason))

    def outcome_count(self, status: str) -> float:
        return self._requests.get((self.gateway, status))

    def retry_count(self) -> float:
        return self._retries.get((self.gateway,))

    def swap_count(self) -> float:
        return self._swaps.get((self.gateway,))
