"""Gateway: engine lifecycle, not just engine execution.

``Gateway`` composes the request plane — ``AdmissionController`` in
front of an ``EnginePool`` whose lanes run as staged pipelines by
default (``pipeline_depth=2``: host-prep, H2D upload, and device
compute of consecutive windows overlap; serving/pipeline.py) — and
owns everything about the engines' *lives*:

- **build + warm** — lanes come up with every bucket compiled before
  the gateway reports ready (``warmup_example``), so cold compiles
  never land in the traffic latency distribution;
- **live re-bucketing** — ``rebucket()`` closes the PR 2 autoscale
  loop: read the lanes' observed request-size histogram
  (``ServingMetrics.request_sizes``), ask
  ``serving/autoscale.suggest_buckets`` for the padding-minimal bucket
  set, and when the proposal differs, build + warm replacement engines
  in the background and atomically swap them behind the micro-batchers
  (``EnginePool.swap``) — zero dropped requests, responses straddling
  the swap numerically identical. A ``maintenance_interval_s`` runs
  this periodically off a daemon thread;
- **graceful shutdown** — ``close()`` (or SIGTERM via
  ``install_signal_handlers``) flips readiness (``/readyz`` goes 503 so
  load balancers stop sending), stops admitting (typed
  ``Overloaded('closed')``), drains the admission queue, and flushes
  every lane's micro-batcher so already-admitted requests resolve;
- **SLO enforcement + forensics** (``slo_latency_s=``) — declares a
  latency SLO (and an availability SLO) over the gateway's own metric
  series, samples multi-window burn rates (``observability/slo.py``),
  and runs a *watchdog*: a sustained fast-window burn tightens
  admission (``AdmissionController.set_pressure`` — shed early, with
  reason ``slo_pressure``, before the queue saturates) and relaxes it
  once the burn subsides. The same threshold drives the tail-sampling
  flight recorder: requests that breach it (or error) get their full
  span tree pinned for ``/debugz``.

Readiness vs liveness: ``ready`` is a routing signal (admitting and
warmed) — the admin endpoint's ``/healthz`` stays the liveness probe
(process up), and a draining gateway is alive but not ready. The burn
state is surfaced in ``/readyz``'s body (still 200 — burning is a
"stop sending so fast", not a "stop sending").
"""

from __future__ import annotations

import logging
import signal
import threading
from concurrent.futures import Future
from typing import Any, Dict, Optional, Sequence

from keystone_tpu.gateway.admission import AdmissionController, Overloaded
from keystone_tpu.gateway.metrics import GatewayMetrics
from keystone_tpu.gateway.pool import EnginePool
from keystone_tpu.loadgen import faults
from keystone_tpu.observability.flight import FlightRecorder
from keystone_tpu.observability.slo import Slo, SloMonitor
from keystone_tpu.serving.batching import MicroBatcher
from keystone_tpu.serving.autoscale import (
    predicted_efficiency,
    suggest_buckets,
)
from keystone_tpu.serving.engine import DEFAULT_BUCKETS

logger = logging.getLogger(__name__)

# observations required before an UNFORCED rebucket may act: a proposal
# from a handful of requests is noise, not traffic
MIN_REBUCKET_OBSERVATIONS = 64

# SLO watchdog defaults: tighten admission after the fast-window burn
# holds >= SHED_BURN for SUSTAIN consecutive samples; relax once it
# falls back under 1.0 (budget no longer being consumed too fast)
SLO_SHED_BURN = 4.0

# swap_model's "keep the current AOT store" default — None is a real
# value there (it means: candidate engines get no store at all)
_UNCHANGED = object()
SLO_SUSTAIN_SAMPLES = 2
SLO_PRESSURE = 0.75


def _fmt_eff(eff) -> str:
    return f"{eff:.3f}" if eff is not None else "n/a"


class Gateway:
    """The serving front door over one fitted pipeline.

    Parameters
    ----------
    fitted:            the ``FittedPipeline`` to serve (each lane gets
                       its own ``CompiledPipeline`` over it).
    buckets:           initial row buckets per lane engine.
    n_lanes:           replica lanes (shared-nothing engine copies).
    warmup_example:    one example (no batch axis) used to pre-compile
                       every bucket at construction and after each
                       swap; without it lanes compile lazily and the
                       first requests eat the compiles.
    pipeline_depth:    stage-queue depth of each lane's STAGED pipeline
                       (serving/pipeline.py): window k+1's host-prep
                       and H2D upload overlap window k's device
                       compute, results bit-identical to serial. The
                       default (2) double-buffers every handoff; 0
                       reverts the lanes to strictly serial dispatch.
    host_featurize:    optional items-mode prep hook — a callable
                       turning one coalesced window of RAW examples
                       (arrays, strings, records...) into the batched
                       array tree the lane engines stage. Runs on the
                       host-prep stage (or inline when serial), so
                       tokenizer/featurizer front-ends burn host cores
                       while the device computes the previous window.
    param_sharding:    shard the MODEL over the process mesh's model
                       axis (serving/sharding.py): ``True`` resolves
                       the default rule set, a rules sequence or
                       ``{name: spec}`` dict partitions explicitly.
                       Every engine generation the factory builds —
                       initial lanes, rebucket replacements, warm-pool
                       swaps — carries the same partitioning, placed
                       over the mesh current at build time (serving
                       CLIs pin it process-wide with
                       ``mesh.set_mesh``). Each lane places its OWN
                       copy of the sharded params, so bigger-than-one-
                       chip models are typically served ``n_lanes=1``.
    device_featurize:  optional fitted featurize pipeline fused into
                       every lane engine's bucket programs IN FRONT of
                       ``fitted`` (``CompiledPipeline(featurize=...)``):
                       clients submit RAW examples (e.g. uint8 images
                       — ~4× fewer H2D bytes than f32 features), the
                       host-prep stage only stacks/pads them into the
                       pooled staging buffers, and cast + featurize +
                       predict ride one compiled dispatch. Requires a
                       traceable (pure-JAX, array-mode) featurize
                       chain; keep ``host_featurize`` for native/
                       items-mode featurizers — the two COMPOSE (host
                       hook decodes raw bytes into uint8 arrays, the
                       device stage featurizes them). Swaps/rebuckets
                       rebuild lane engines with the same fused stage;
                       ``warmup_example`` must be a RAW example in
                       this mode.
    aot_store:         the serialized-executable store engine builds
                       consult: ``"auto"`` (process-configured),
                       ``None``/``False`` (off), or an explicit
                       ``AotStore`` — the model zoo passes per-model
                       NAMESPACED stores here.
    engine_factory:    optional override, ``callable(buckets) ->
                       (lane_name -> engine)`` — replaces the
                       ``fitted.compiled()`` factory for every engine
                       generation (the zoo's cross-model CSE plane
                       builds shared-prefix multi-head engines through
                       this seam).
    max_pending:       admission queue bound.
    default_deadline_ms: deadline applied to requests that don't carry
                       their own.
    maintenance_interval_s: period of the background rebucket loop
                       (None/0 = off; ``rebucket()`` stays callable).
    rebucket_k:        bucket-set size the autoscaler proposes
                       (default: len(buckets)).
    slo_latency_s:     declare + enforce a latency SLO at this
                       threshold (None = whole SLO/forensics plane off,
                       zero overhead): burn-rate monitoring, the
                       admission-tightening watchdog, and tail-sampled
                       flight recording all hang off it.
    slo_target:        fraction of requests that must make the latency
                       threshold (error budget = 1 - target).
    slo_availability_target: fraction of requests that must not error.
    slo_fast_window_s / slo_slow_window_s / slo_sample_interval_s:
                       burn-rate evaluation windows and sampling period
                       (tests shrink these to milliseconds).
    slo_shed_burn:     fast-window burn rate that (sustained for
                       ``slo_sustain_samples``) trips admission
                       tightening.
    slo_pressure:      how hard the watchdog tightens (queue bound
                       shrinks to ``max_pending * (1 - pressure)``).
    flight_capacity:   forensic ring size (records, not spans).
    """

    def __init__(
        self,
        fitted,
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        n_lanes: int = 2,
        max_delay_ms: float = 5.0,
        lane_capacity: Optional[int] = None,
        warmup_example: Any = None,
        pipeline_depth: int = 2,
        host_featurize=None,
        device_featurize=None,
        param_sharding=None,
        aot_store="auto",
        engine_factory=None,
        max_pending: int = 1024,
        default_deadline_ms: Optional[float] = None,
        maintenance_interval_s: Optional[float] = None,
        rebucket_k: Optional[int] = None,
        name: str = "gateway",
        registry=None,
        slo_latency_s: Optional[float] = None,
        slo_target: float = 0.99,
        slo_availability_target: float = 0.999,
        slo_fast_window_s: float = 60.0,
        slo_slow_window_s: float = 1800.0,
        slo_sample_interval_s: float = 5.0,
        slo_shed_burn: float = SLO_SHED_BURN,
        slo_sustain_samples: int = SLO_SUSTAIN_SAMPLES,
        slo_pressure: float = SLO_PRESSURE,
        flight_capacity: int = 64,
    ):
        self.name = name
        self.fitted = fitted
        # normalized exactly like CompiledPipeline normalizes its own
        # bucket set, so buckets[-1] is genuinely the max bucket the
        # rebucket loop must force and proposal comparisons are stable
        self._buckets = tuple(sorted(set(int(b) for b in buckets)))
        self._warmup_example = warmup_example
        # fused into every engine generation the factory builds —
        # initial lanes, rebucket replacements, and warm-pool swaps all
        # carry the same device-side featurize stage and the same
        # model-sharding rules
        self._device_featurize = device_featurize
        self._param_sharding = param_sharding
        # kept for build_model_batcher: a candidate engine's batcher
        # must match the lanes' windowing/featurize config or the
        # shadow diff would measure batching, not the model
        self._max_delay_ms = max_delay_ms
        self._pipeline_depth = pipeline_depth
        self._host_featurize = host_featurize
        # the AOT executable store every engine generation consults:
        # "auto" (the process-configured store), None/False (off), or
        # an explicit AotStore — the model zoo passes each model's
        # NAMESPACED store here so co-hosted models never share a
        # cache slot
        self._aot_store = aot_store
        # engine-factory override: callable(buckets) -> (lane_name ->
        # engine). The zoo's cross-model CSE plane builds shared-prefix
        # multi-head engines this way; when set it fully replaces the
        # fitted.compiled() factory below (the override owns featurize/
        # sharding/store wiring) but still rides every generation —
        # initial build, rebuckets, warm-pool swaps
        self._engine_factory = engine_factory
        self._rebucket_k = rebucket_k or len(self._buckets)
        self.metrics = GatewayMetrics(registry=registry, gateway=name)
        self.pool = EnginePool(
            self._factory_for(self._buckets),
            n_lanes,
            name=name,
            max_delay_ms=max_delay_ms,
            lane_capacity=lane_capacity,
            metrics=self.metrics,
            pipeline_depth=pipeline_depth,
            host_featurize=host_featurize,
        )
        if warmup_example is not None:
            self.pool.warmup(warmup_example)
        # -- SLO + forensics plane (off unless a latency SLO declared) -
        self.flight: Optional[FlightRecorder] = None
        self.slo_monitor: Optional[SloMonitor] = None
        self._latency_slo: Optional[Slo] = None
        self._slo_shed_burn = float(slo_shed_burn)
        self._slo_sustain_samples = int(slo_sustain_samples)
        self._slo_pressure = float(slo_pressure)
        self._slo_hot_samples = 0
        if slo_latency_s is not None:
            self.flight = FlightRecorder(
                flight_capacity,
                latency_threshold_s=slo_latency_s,
                registry=registry,
            )
            self.slo_monitor = SloMonitor(
                fast_window_s=slo_fast_window_s,
                slow_window_s=slo_slow_window_s,
                registry=registry,
            )
            self._latency_slo = self.slo_monitor.add(
                Slo.latency(
                    f"{name}:latency",
                    self.metrics.request_latency,
                    threshold_s=slo_latency_s,
                    target=slo_target,
                    labels=(name,),
                )
            )
            self.slo_monitor.add(
                Slo.availability(
                    f"{name}:availability",
                    self.metrics.requests_total,
                    target=slo_availability_target,
                    base_labels=(name,),
                )
            )
            self.slo_monitor.add_listener(self._slo_watchdog)
            self.slo_monitor.start(slo_sample_interval_s)
        self.admission = AdmissionController(
            self.pool,
            max_pending=max_pending,
            default_deadline_ms=default_deadline_ms,
            metrics=self.metrics,
            name=name,
            flight=self.flight,
            forensic_threshold_s=slo_latency_s,
        )
        # the last re-bucket's goodput audit (observed-before vs
        # model-predicted-after padding efficiency); None until a swap
        self.last_rebucket_audit: Optional[Dict] = None
        self._closed = False
        self._close_lock = threading.Lock()
        self._drained = threading.Event()
        # one swap at a time: the maintenance loop and POST /swap must
        # not interleave build/swap/assign sequences
        self._swap_lock = threading.RLock()
        self._maint_stop = threading.Event()
        # chaos point: arming gateway.swap.force (via code, env, or
        # POST /chaosz; match gateway=<name> to target one of several)
        # forces a live rebucket on a background thread — the "swap
        # under peak load" experiment, driving the same path as
        # POST /swap
        self._chaos_unregister = faults.get_injector().register_trigger(
            "gateway.swap.force",
            self._chaos_forced_swap,
            ctx={"gateway": name},
        )
        self._maint: Optional[threading.Thread] = None
        if maintenance_interval_s:
            self._maint = threading.Thread(
                target=self._maintenance_loop,
                args=(float(maintenance_interval_s),),
                name=f"keystone-{name}-lifecycle",
                daemon=True,
            )
            self._maint.start()

    def _factory_for(self, buckets):
        if self._engine_factory is not None:
            return self._engine_factory(buckets)

        def factory(lane_name: str):
            return self.fitted.compiled(
                buckets=buckets, name=lane_name,
                featurize=self._device_featurize,
                param_sharding=self._param_sharding,
                aot_store=self._aot_store,
            )

        return factory

    # -- serving -----------------------------------------------------------

    def predict(
        self,
        example: Any,
        deadline_ms: Optional[float] = None,
        trace_id: Optional[str] = None,
    ) -> Future:
        """Admit one example; resolves to its pipeline output. Raises
        ``Overloaded`` immediately when shed. ``trace_id`` adopts a
        remote trace identity (see ``AdmissionController.submit``)."""
        return self.admission.submit(
            example, deadline_ms=deadline_ms, trace_id=trace_id
        )

    @property
    def ready(self) -> bool:
        """Routing signal: admitting traffic (drain flips this false
        before any request is refused)."""
        return not self._closed and self.admission.accepting

    @property
    def buckets(self) -> tuple:
        return self._buckets

    # -- SLO watchdog ------------------------------------------------------

    def _slo_watchdog(self, monitor: SloMonitor) -> None:
        """Runs after every burn-rate sample: a sustained fast-window
        burn tightens admission (shed early, before the queue
        saturates); the pressure releases once the burn drops back
        under 1.0 — budget consumption at a sustainable rate again."""
        burns = monitor.burn_rates(self._latency_slo.name)
        fast = burns.get("fast")
        if fast is None:
            return
        if fast >= self._slo_shed_burn:
            self._slo_hot_samples += 1
            if (
                self._slo_hot_samples >= self._slo_sustain_samples
                and self.admission.pressure == 0.0
            ):
                self.admission.set_pressure(self._slo_pressure)
                self.metrics.set_slo_pressure(self._slo_pressure)
                logger.warning(
                    "gateway %s: fast-window SLO burn %.1f sustained "
                    "%d samples; tightening admission (pressure %.2f)",
                    self.name, fast, self._slo_hot_samples,
                    self._slo_pressure,
                )
        else:
            # "sustained" means CONSECUTIVE over-threshold samples: any
            # cooler sample resets the streak, so isolated spikes hours
            # apart can never accumulate into a tightening
            self._slo_hot_samples = 0
            if fast < 1.0 and self.admission.pressure > 0.0:
                # release only once consumption is back under the
                # sustainable rate (hysteresis between shed_burn and 1)
                self.admission.set_pressure(0.0)
                self.metrics.set_slo_pressure(0.0)
                logger.info(
                    "gateway %s: SLO burn subsided (fast %.2f); "
                    "admission pressure released", self.name, fast,
                )

    def slo_status(self) -> Optional[Dict]:
        """The burn state ``/readyz`` surfaces (None with no SLOs)."""
        if self.slo_monitor is None or self._latency_slo is None:
            return None
        return {
            "pressure": self.admission.pressure,
            "burn_rate": self.slo_monitor.burn_rates(
                self._latency_slo.name
            ),
            "breaching": self.slo_monitor.breaching(
                self._latency_slo.name
            ),
        }

    # -- the live autoscale loop -------------------------------------------

    def observed_sizes(self) -> Dict[int, int]:
        """The pool-wide request-size histogram (every lane's engine
        merged) — exactly what ``/metrics`` exports per lane as
        ``keystone_serving_request_size_total``."""
        merged: Dict[int, int] = {}
        for lane in self.pool.lanes:
            for size, count in (
                lane.engine.metrics.request_sizes.snapshot().items()
            ):
                merged[size] = merged.get(size, 0) + count
        return merged

    def observed_goodput(self) -> Dict:
        """Pool-wide LIVE goodput: valid vs padded rows every lane
        engine actually dispatched (the device-truth counters the
        padding-efficiency gauge exports per lane) — what a re-bucket
        decision is audited against."""
        goodput = padded = 0
        for lane in self.pool.lanes:
            m = lane.engine.metrics
            goodput += m.examples.total
            padded += m.padded_rows.total
        total = goodput + padded
        return {
            "goodput_rows": goodput,
            "padded_rows": padded,
            "efficiency": goodput / total if total else None,
        }

    def rebucket(self, force: bool = False) -> bool:
        """One autoscale iteration: histogram -> ``suggest_buckets`` ->
        build + warm replacements -> atomic swap -> old engines drain.
        Returns True when a swap happened. Unforced calls act only on
        enough evidence AND a changed proposal; ``force=True`` swaps
        unconditionally (same buckets if no better proposal — the smoke
        path and swap drills use this).

        Every swap is AUDITED: the observed goodput (live per-bucket
        valid/padded counters) under the outgoing bucket set and the
        model-predicted efficiency of the proposal are logged together
        and kept at ``last_rebucket_audit``, so a ``suggest_buckets``
        decision can be checked against what the traffic then actually
        did (the next audit's observed number)."""
        with self._swap_lock:
            hist = self.observed_sizes()
            observations = sum(hist.values())
            proposal = self._buckets
            if hist and (
                force or observations >= MIN_REBUCKET_OBSERVATIONS
            ):
                proposal = suggest_buckets(
                    hist, self._rebucket_k, max_bucket=self._buckets[-1]
                )
            if not force:
                if observations < MIN_REBUCKET_OBSERVATIONS:
                    return False
                if proposal == self._buckets:
                    return False
            observed = self.observed_goodput()
            audit = {
                "from_buckets": list(self._buckets),
                "to_buckets": list(proposal),
                "observations": observations,
                "observed_efficiency_before": observed["efficiency"],
                "goodput_rows_before": observed["goodput_rows"],
                "padded_rows_before": observed["padded_rows"],
                "predicted_efficiency_after": predicted_efficiency(
                    hist, proposal
                ),
            }
            if not self.swap_engines(proposal):
                # close() won the race: nothing rotated, so no audit,
                # no log line, and the caller (POST /swap) must not be
                # told a swap happened
                return False
            self.last_rebucket_audit = audit
            logger.info(
                "gateway %s rebucket %s -> %s: observed padding "
                "efficiency %s over %d goodput rows; proposal predicts "
                "%s on the observed histogram",
                self.name, audit["from_buckets"], audit["to_buckets"],
                _fmt_eff(audit["observed_efficiency_before"]),
                audit["goodput_rows_before"],
                _fmt_eff(audit["predicted_efficiency_after"]),
            )
            return True

    def build_engines(self, buckets: Sequence[int]) -> list:
        """Build + warm one replacement engine per lane with
        ``buckets`` — the warm-pool half of a swap. Runs outside the
        POOL's lock (so lanes keep serving and the pool stays
        closeable while the next generation compiles) but under the
        gateway's swap lock when driven by ``swap_engines``: engine
        construction claims the per-lane metrics labels
        (newest-claim-wins), so two generations building concurrently
        could rotate in an engine whose label another build claimed —
        one swap at a time stays the invariant. With the AOT
        executable store configured (``serving/aot.py``) the
        "compiles" are deserializes and this returns in milliseconds;
        either way the engines come back fully warmed and ready to
        rotate in."""
        buckets = tuple(sorted(set(int(b) for b in buckets)))
        return self.pool.build_replacements(
            self._factory_for(buckets),
            warmup_example=self._warmup_example,
        )

    def build_model_batcher(
        self, fitted, *, name: str, aot_store=None
    ) -> MicroBatcher:
        """One engine + micro-batcher for a DIFFERENT fitted pipeline
        over THIS gateway's serving config (buckets, device featurize,
        sharding, windowing) — the candidate plane the lifecycle loop
        points shadow and canary traffic at. Deliberately NOT a pool
        lane: the candidate serves copies/fractions, never owns
        routing, and is closed by its controller. ``aot_store`` is the
        candidate's own (typically per-version namespaced) store;
        None means no store — a candidate must never populate the
        incumbent's cache slots."""
        if self._engine_factory is not None:
            raise RuntimeError(
                f"gateway {self.name} runs on an engine-factory "
                "override (zoo CSE plane); its engines aren't "
                "buildable from a fitted pipeline"
            )
        engine = fitted.compiled(
            buckets=self._buckets,
            name=name,
            featurize=self._device_featurize,
            param_sharding=self._param_sharding,
            aot_store=aot_store if aot_store is not None else False,
        )
        return MicroBatcher(
            engine,
            max_delay_ms=self._max_delay_ms,
            pipeline_depth=self._pipeline_depth,
            host_featurize=self._host_featurize,
        )

    def swap_model(self, fitted, *, aot_store=_UNCHANGED) -> bool:
        """Re-point the gateway at a DIFFERENT fitted pipeline and
        rotate every lane onto engines built from it — the promotion
        (and rollback) primitive: build + warm outside the pool lock,
        then the same atomic per-lane ``swap_engine`` a rebucket uses,
        so in-flight windows finish on the old model and nothing is
        dropped. Returns False when ``close()`` won the race (nothing
        rotated); on a build failure the previous fitted (and AOT
        store, when ``aot_store`` was passed) is restored and the old
        engines keep serving. Rolling BACK a promotion is just
        ``swap_model(incumbent)`` — engines rebuilt from the identical
        fitted serve bitwise-identical outputs."""
        if self._engine_factory is not None:
            raise RuntimeError(
                f"gateway {self.name} runs on an engine-factory "
                "override (zoo CSE plane); swap_model cannot rebuild "
                "its engines from a fitted pipeline"
            )
        with self._swap_lock:
            prev_fitted, prev_store = self.fitted, self._aot_store
            self.fitted = fitted
            if aot_store is not _UNCHANGED:
                self._aot_store = aot_store
            try:
                ok = self._build_and_swap(self._buckets)
            except Exception:
                self.fitted, self._aot_store = prev_fitted, prev_store
                raise
            if not ok:
                self.fitted, self._aot_store = prev_fitted, prev_store
            return ok

    def swap_engines(
        self, buckets: Sequence[int], background: bool = False
    ):
        """Rotate the next engine generation in: build + warm one
        replacement per lane (``build_engines`` — outside the pool
        lock, from the AOT store when configured) and atomically
        re-point every lane's batcher (in-flight windows finish on the
        old engines; queued and future requests use the new ones).

        ``background=True`` is the warm-pool mode: the build AND the
        rotation run on a background builder thread and the returned
        ``Future`` resolves True once the rotation happened (False if
        the gateway closed first; a build/swap failure lands on the
        future as its exception, with the old engines still serving).
        Synchronous calls return the same bool directly — False means
        a close() won the race and NOTHING rotated, which callers like
        ``rebucket`` must not report as a swap."""
        buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not background:
            return self._build_and_swap(buckets)
        fut: Future = Future()

        def run():
            try:
                fut.set_result(self._build_and_swap(buckets))
            except Exception as e:
                logger.exception(
                    "gateway %s: background engine swap to %s failed "
                    "(old engines keep serving)", self.name, buckets,
                )
                fut.set_exception(e)

        threading.Thread(
            target=run, name=f"keystone-{self.name}-warmpool",
            daemon=True,
        ).start()
        return fut

    def _build_and_swap(self, buckets: tuple) -> bool:
        if self._closed:
            # already closed before the build even started: skip the
            # whole generation build (per-lane compiles + metrics
            # label re-registration) for a gateway that's gone
            return False
        with self._swap_lock:
            # the BUILD happens under the swap lock too (re-entrant
            # from rebucket): builds claim the lane metrics labels at
            # engine construction, so build order must equal rotation
            # order — what stays unlocked is the POOL, which keeps
            # serving and closeable throughout. That makes this a
            # deliberate blocking-under-lock exception: _swap_lock is
            # the coarse one-swap-at-a-time maintenance lock, held by
            # nothing on the request plane.
            engines = self.build_engines(buckets)  # lint: disable=blocking-under-lock
            if self._closed:
                # a background build that lost the race with close():
                # the fresh engines are dropped, nothing rotated
                return False
            try:
                self.pool.swap(
                    self._factory_for(buckets), engines=engines
                )
            except RuntimeError:
                if self._closed:
                    # close() won the race between our check and the
                    # pool's own: a normal shutdown, not a swap failure
                    return False
                raise
            self._buckets = buckets
        return True

    def _chaos_forced_swap(self, spec) -> None:
        """``gateway.swap.force`` trigger body (injector background
        thread): one forced live swap, mid-whatever-load-is-running."""
        if self._closed:
            return
        logger.warning(
            "gateway %s: chaos-forced live swap (fault point armed)",
            self.name,
        )
        try:
            self.rebucket(force=True)
        except Exception:
            # chaos must surface as symptoms, not crash the trigger
            # thread: the old engines keep serving on a failed swap
            logger.exception(
                "gateway %s: chaos-forced swap failed", self.name
            )

    def _maintenance_loop(self, interval_s: float) -> None:
        while not self._maint_stop.wait(interval_s):
            try:
                if self.rebucket():
                    logger.info(
                        "gateway %s rebucketed to %s",
                        self.name, self._buckets,
                    )
            except Exception:
                # the loop must survive a failed proposal/build — the
                # old engines keep serving either way
                logger.exception("gateway %s rebucket failed", self.name)

    # -- shutdown ----------------------------------------------------------

    def close(self, timeout: Optional[float] = 10.0) -> None:
        """Graceful drain: flip readiness, stop admitting (typed
        ``Overloaded('closed')`` for new arrivals), drain the admission
        queue into the lanes, flush every micro-batcher, and stop the
        maintenance loop. Already-admitted requests resolve. Safe to
        call concurrently: every caller returns only once the drain has
        finished (the SIGTERM/`/drain` thread and the serve loop's own
        close must not race the process exit past in-flight work)."""
        with self._close_lock:
            first = not self._closed
            self._closed = True
        if not first:
            self._drained.wait(timeout)
            return
        # a retired gateway must stop receiving chaos triggers
        self._chaos_unregister()
        self._maint_stop.set()
        if self.slo_monitor is not None:
            self.slo_monitor.stop()
        self.admission.close(timeout=timeout)
        self.pool.close(timeout=timeout)
        if self._maint is not None:
            self._maint.join(timeout=1.0)
        self._drained.set()
        logger.info("gateway %s drained and closed", self.name)

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT -> graceful drain (main thread only; serving
        CLIs call this, libraries shouldn't)."""

        def handle(signum, frame):
            logger.info(
                "gateway %s: signal %d, draining", self.name, signum
            )
            threading.Thread(
                target=self.close, name=f"keystone-{self.name}-drain",
                daemon=True,
            ).start()

        signal.signal(signal.SIGTERM, handle)
        signal.signal(signal.SIGINT, handle)

    def __enter__(self) -> "Gateway":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


__all__ = ["Gateway", "Overloaded", "MIN_REBUCKET_OBSERVATIONS"]
