"""EnginePool: shared-nothing replica lanes behind one submit().

The ROADMAP's "shared-nothing request plane": N lanes, each a private
``MicroBatcher`` + ``CompiledPipeline`` pair — no cross-lane state, so
lanes scale like independent hosts (and the same topology drops onto
one-engine-per-host multi-host serving later). With
``pipeline_depth > 0`` each lane's batcher runs as a STAGED PIPELINE
(serving/pipeline.py: host-prep / upload / compute / deliver threads
behind bounded handoff queues), overlapping one window's host work
with the previous window's device compute; ``host_featurize`` plugs an
items-mode front-end into every lane's prep stage. Device-side
featurization rides the ``engine_factory`` instead: the Gateway's
factory builds each lane engine with
``CompiledPipeline(featurize=...)``, so every generation (initial
build, rebucket replacements, warm-pool swaps) carries the fused
featurize∘model programs and lanes stage raw bytes — bare-pool users
bake ``featurize=`` into their own factory the same way. Model
sharding rides the factory identically
(``CompiledPipeline(param_sharding=...)``; the Gateway's factory
threads its ``param_sharding=`` through): each lane's engine places
its OWN copy of the sharded params over the mesh, so
bigger-than-one-chip models are typically served with one lane. The pool adds
the three things a replica set needs beyond execution:

- **least-loaded routing** — ``submit()`` hands each request to the
  healthy lane with the fewest unresolved requests, so one slow window
  doesn't queue the world behind it;
- **per-lane health** — a lane is charged a health failure only when a
  request it failed SUCCEEDS on another lane (proof the fault was
  lane-specific, not the request's own); ``UNHEALTHY_AFTER`` such
  failures bench it until a cool-down elapses (half-open probe) or
  every other lane is also out. Errors that reproduce on the retry
  lane are request-caused and charge nobody — malformed client traffic
  can never bench the pool and starve well-formed requests;
- **retry-to-another-lane** — a failed request is retried once on a
  different lane before its error propagates, so a single lane's
  transient failure (poisoned window, device hiccup) is invisible to
  callers. Deterministically-bad requests still fail: the retry lane
  reproduces the error and it propagates.

``swap()`` is the live-engine-replacement primitive the lifecycle loop
drives: build + warm replacements for every lane FIRST (any failure
aborts the swap with the old engines still serving), then atomically
re-point each lane's batcher (``MicroBatcher.swap_engine``) — in-flight
windows finish on the old engines, queued and future requests dispatch
through the new ones, and nothing is dropped.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional, Sequence

from keystone_tpu.loadgen import faults
from keystone_tpu.serving.batching import MicroBatcher
from keystone_tpu.serving.engine import CompiledPipeline

logger = logging.getLogger(__name__)

# consecutive failures that bench a lane, and how long it sits out
# before the router half-opens it again
UNHEALTHY_AFTER = 3
RECOVERY_AFTER_S = 5.0

# EngineFactory(lane_name) -> a fresh engine for that lane
EngineFactory = Callable[[str], CompiledPipeline]


def canary_takes(seq: int, fraction: float) -> bool:
    """The DETERMINISTIC canary decision for request number ``seq``
    (0-based): True exactly when the integer part of ``seq·fraction``
    advances, i.e. of any n consecutive requests ``floor(n·fraction)``
    (±1) are canaried — evenly spread, no RNG, reproducible. The
    lifecycle's ``CanaryRouter`` drives ``submit()`` with this; it is
    a module function so the policy tests can pin its arithmetic
    without a pool."""
    if fraction <= 0.0:
        return False
    if fraction >= 1.0:
        return True
    return int((seq + 1) * fraction) > int(seq * fraction)


class Lane:
    """One replica: a private engine behind a private micro-batcher,
    plus the load/health accounting the router reads."""

    def __init__(
        self,
        engine: CompiledPipeline,
        index: int,
        max_delay_ms: float = 5.0,
        capacity: Optional[int] = None,
        pipeline_depth: int = 0,
        host_featurize=None,
    ):
        self.index = index
        self.batcher = MicroBatcher(
            engine,
            max_delay_ms=max_delay_ms,
            pipeline_depth=pipeline_depth,
            host_featurize=host_featurize,
        )
        self._capacity_pinned = int(capacity) if capacity else None
        self._lock = threading.Lock()
        self._inflight = 0  # guarded-by: _lock
        self._consecutive_failures = 0  # guarded-by: _lock
        self._last_failure_t = 0.0  # guarded-by: _lock

    @property
    def capacity(self) -> int:
        """How many unresolved requests this lane will hold before the
        admission router stops feeding it: two full windows keeps the
        batcher's next window filling while one executes — plus one
        window per pipeline stage-depth when the lane is a staged
        pipeline, so the prep/upload/compute stages all have a window
        to chew on. Unless pinned it tracks the CURRENT engine's window
        size, so a rebucket to larger buckets also widens the lane (a
        frozen bound would cap throughput at the old bucket's scale)."""
        if self._capacity_pinned is not None:
            return self._capacity_pinned
        return (
            (2 + self.batcher.pipeline_depth) * self.batcher.max_batch
        )

    @property
    def engine(self) -> CompiledPipeline:
        return self.batcher.engine

    @property
    def load(self) -> int:
        with self._lock:
            return self._inflight

    @property
    def free(self) -> int:
        with self._lock:
            return max(0, self.capacity - self._inflight)

    @property
    def healthy(self) -> bool:
        with self._lock:
            if self._consecutive_failures < UNHEALTHY_AFTER:
                return True
            # half-open: after the cool-down the lane gets probe traffic
            # again; one success fully restores it
            return (
                time.perf_counter() - self._last_failure_t
                > RECOVERY_AFTER_S
            )

    def submit(
        self, example: Any, parent_span_id: Optional[int] = None
    ) -> Future:
        with self._lock:
            self._inflight += 1
        # chaos point: an armed gateway.lane.kill (typically matched to
        # one lane index) fails requests routed here mid-flight; the
        # pool's retry-to-another-lane + success-corroborated health
        # charging must absorb it exactly like a real lane fault. The
        # raise sits AFTER the inflight increment so the router's
        # release() stays balanced. Unarmed: the armed() gate is one
        # attribute read, and the ctx dict is never even built.
        if faults.armed() and faults.fire(
            "gateway.lane.kill", {"lane": self.index}
        ) is not None:
            raise faults.FaultInjected("gateway.lane.kill", lane=self.index)
        return self.batcher.submit(example, parent_span_id=parent_span_id)

    def release(self) -> None:
        """One request left this lane (resolved either way) — load
        accounting only; health attribution is separate."""
        with self._lock:
            self._inflight = max(0, self._inflight - 1)

    def mark_ok(self) -> None:
        with self._lock:
            self._consecutive_failures = 0

    def mark_failed(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            self._last_failure_t = time.perf_counter()

    def close(self, timeout: Optional[float] = 10.0) -> None:
        self.batcher.close(timeout=timeout)


class EnginePool:
    """N shared-nothing lanes with least-loaded routing, health
    tracking, retry-on-lane-failure, and atomic engine swap."""

    def __init__(
        self,
        engine_factory: EngineFactory,
        n_lanes: int = 2,
        *,
        name: str = "gateway",
        max_delay_ms: float = 5.0,
        lane_capacity: Optional[int] = None,
        max_retries: int = 1,
        metrics=None,  # GatewayMetrics; duck-typed so tests can stub
        pipeline_depth: int = 0,
        host_featurize=None,
    ):
        if n_lanes < 1:
            raise ValueError(f"need at least one lane, got {n_lanes}")
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {max_retries}")
        self.max_retries = max_retries
        self.name = name
        self.metrics = metrics
        self._factory = engine_factory  # guarded-by: _lock
        self._max_delay_ms = max_delay_ms
        self._lane_capacity = lane_capacity
        self._lock = threading.Lock()
        self._closed = False  # guarded-by: _lock
        # lifecycle hooks (duck-typed; see lifecycle/routes.py): a
        # mirror sees a COPY of every submit off the response path, a
        # canary serves a deterministic fraction ON it. Plain attribute
        # writes — submit() reads each once, so the disarmed cost is
        # two attribute reads and None-checks per request
        self._mirror = None
        self._canary = None
        self._free_listeners: List[Callable[[], None]] = []
        self.lanes: List[Lane] = [
            Lane(
                engine_factory(self.lane_name(i)),
                i,
                max_delay_ms=max_delay_ms,
                capacity=lane_capacity,
                pipeline_depth=pipeline_depth,
                host_featurize=host_featurize,
            )
            for i in range(n_lanes)
        ]

    def lane_name(self, index: int) -> str:
        return f"{self.name}-lane{index}"

    # -- capacity signals (the admission router's pacing inputs) -----------

    def add_free_listener(self, fn: Callable[[], None]) -> None:
        """``fn`` fires (from a completion callback thread) whenever a
        lane slot frees — the admission router waits on this instead of
        polling."""
        self._free_listeners.append(fn)

    def _notify_free(self) -> None:
        for fn in self._free_listeners:
            try:
                fn()
            except Exception:
                logger.exception("pool free-listener failed")

    def free_capacity(self) -> int:
        return sum(l.free for l in self.lanes if l.healthy)

    def total_load(self) -> int:
        return sum(l.load for l in self.lanes)

    def healthy_lanes(self) -> int:
        return sum(1 for l in self.lanes if l.healthy)

    def status(self) -> dict:
        """One inspection snapshot per pool — what ``/planz`` reports
        as a model's ACTUAL placement (lane count, the lanes' current
        bucket list, health/load) next to the optimizer's plan."""
        return {
            "lanes": len(self.lanes),
            "healthy_lanes": self.healthy_lanes(),
            "buckets": list(self.lanes[0].engine.buckets),
            "free_capacity": self.free_capacity(),
            "total_load": self.total_load(),
        }

    # -- routing -----------------------------------------------------------

    def set_mirror(self, mirror) -> None:
        """Install (or clear, with None) the shadow mirror — every
        subsequent ``submit()`` also hands the example + primary
        future to ``mirror.observe`` off the response path."""
        self._mirror = mirror

    def set_canary(self, canary) -> None:
        """Install (or clear, with None) the canary router — it takes
        a deterministic fraction of subsequent ``submit()``s onto the
        candidate engine, falling back to the lanes on failure."""
        self._canary = canary

    def pick(self, exclude: Sequence[Lane] = ()) -> Optional[Lane]:
        """The routing decision ``submit()`` uses, public: the
        least-loaded healthy lane (unhealthy lanes only when nothing
        else is left). The canary fraction rides ON TOP of this — a
        canaried request bypasses the lanes entirely, everything else
        lands here."""
        return self._pick(exclude)

    def _pick(self, exclude: Sequence[Lane]) -> Optional[Lane]:
        candidates = [
            l for l in self.lanes if l.healthy and l not in exclude
        ]
        if not candidates:
            # availability over purity: an unhealthy lane beats shedding
            # when it is the only lane left (and gives it probe traffic)
            candidates = [l for l in self.lanes if l not in exclude]
        if not candidates:
            return None
        return min(candidates, key=lambda l: l.load)

    def submit(
        self, example: Any, parent_span_id: Optional[int] = None
    ) -> Future:
        """Route one example to the least-loaded healthy lane. The
        returned future resolves with the example's pipeline output; on
        a lane failure the request is retried once on a different lane
        before the error propagates."""
        if self._closed:
            raise RuntimeError("EnginePool is closed")
        out: Future = Future()
        canary = self._canary
        if canary is not None and canary.takes():
            # a deterministic fraction serves from the candidate
            # engine; the router falls back to the incumbent lanes on
            # any candidate failure, so callers never see one
            canary.route(
                example, parent_span_id, out,
                lambda: self._submit_once(
                    example, parent_span_id, out, tried=[]
                ),
            )
        else:
            self._submit_once(example, parent_span_id, out, tried=[])
        mirror = self._mirror
        if mirror is not None:
            # off the response path: the mirror copies the example to
            # the candidate and diffs outputs in completion callbacks;
            # it must never raise (and ShadowMirror.observe doesn't),
            # and `out` is already on its way either way
            mirror.observe(example, out)
        return out

    def _submit_once(
        self,
        example: Any,
        parent_span_id: Optional[int],
        out: Future,
        tried: List[Lane],
    ) -> None:
        lane = self._pick(exclude=tried)
        if lane is None:
            out.set_exception(
                RuntimeError(f"no lane available (tried {len(tried)})")
            )
            return
        tried.append(lane)
        # which lane served this request (the LAST one tried wins on a
        # retry) — the admission layer copies it onto the caller-facing
        # future for the request log and flight-recorder attrs
        out.lane_index = lane.index
        try:
            fut = lane.submit(example, parent_span_id=parent_span_id)
        except Exception as e:
            # a submit-time raise (closed batcher mid-drain, or an
            # example whose spec can't even be computed) gets the same
            # treatment as a dispatch failure: retry elsewhere, and NO
            # unilateral health charge — only the success-corroboration
            # path in done() may bench a lane, else malformed requests
            # could bench the pool
            lane.release()
            retriable = [l for l in self.lanes if l not in tried]
            if (
                retriable
                and len(tried) <= self.max_retries
                and not self._closed
            ):
                if self.metrics is not None:
                    self.metrics.record_retry()
                self._submit_once(example, parent_span_id, out, tried)
            else:
                try:
                    out.set_exception(e)
                except Exception:
                    pass  # caller cancelled concurrently
            return

        def done(f: Future) -> None:
            err = f.exception()
            lane.release()
            self._notify_free()
            if err is None:
                # health attribution happens only on success: THIS lane
                # is fine, and any lane that failed this same request
                # earlier failed where another succeeded — a
                # lane-specific fault, safe to count against it
                lane.mark_ok()
                for failed in tried[:-1]:
                    failed.mark_failed()
                if not out.cancelled():
                    out.set_result(f.result())
                return
            # retry on a DIFFERENT lane at most max_retries times
            # (default once): transient lane failures heal invisibly;
            # deterministic request errors reproduce on the retry lane
            # and propagate instead of touring every lane of a big pool
            retriable = [
                l for l in self.lanes if l not in tried
            ]
            if (
                retriable
                and len(tried) <= self.max_retries
                and not self._closed
            ):
                if self.metrics is not None:
                    self.metrics.record_retry()
                logger.warning(
                    "lane %d failed a request (%s); retrying on "
                    "another lane", lane.index, err,
                )
                self._submit_once(example, parent_span_id, out, tried)
            else:
                # terminal failure: the error reproduced on every lane
                # we tried (or no other lane exists) — that signature is
                # a request-caused error, so NO lane's health is dinged:
                # a trickle of malformed requests must never bench the
                # pool and starve well-formed traffic
                try:
                    out.set_exception(err)
                except Exception:
                    pass  # caller cancelled while we were failing

        fut.add_done_callback(done)

    # -- lifecycle primitives ----------------------------------------------

    def swap(
        self,
        engine_factory: Optional[EngineFactory] = None,
        warmup_example: Any = None,
        engines: Optional[Sequence[CompiledPipeline]] = None,
    ) -> List[CompiledPipeline]:
        """Replace every lane's engine atomically-per-lane: build (and
        optionally warm) ALL replacements first — a failure there aborts
        the swap with the old engines untouched — then re-point each
        lane's batcher. Returns the displaced engines (callers normally
        drop them; in-flight windows finish on them regardless).

        ``engines``: PREBUILT (and already-warmed) replacements, one
        per lane in lane order — the Gateway warm-pool path builds the
        next generation outside this lock (on a background builder
        thread, from the AOT executable store when configured), so the
        work under the lock here is just the atomic re-point.

        Engines are rebuilt under their lane's original name, so the
        ServingMetrics label-transfer rule keeps one Prometheus series
        per lane across any number of swaps."""
        factory = engine_factory or self._factory
        if engines is not None and len(engines) != len(self.lanes):
            raise ValueError(
                f"need one prebuilt engine per lane "
                f"({len(self.lanes)}), got {len(engines)}"
            )
        if self._closed:
            raise RuntimeError("EnginePool is closed")
        if engines is not None:
            replacements = list(engines)
        else:
            # build + warm OUTSIDE the pool lock: the generation build
            # is seconds of XLA compile (milliseconds off the AOT
            # store), and holding the lock for it would stall close()
            # and every other lifecycle call behind one swap. The lock
            # below covers only the atomic re-point — the same
            # work-split the Gateway warm pool uses. Swap-vs-swap
            # serialization is the caller's job (the Gateway holds its
            # _swap_lock); racing bare-pool swaps would build two
            # generations and rotate them in arrival order.
            replacements = self.build_replacements(
                factory, warmup_example=warmup_example
            )
        with self._lock:
            if self._closed:
                raise RuntimeError("EnginePool is closed")
            old = [
                lane.batcher.swap_engine(eng)
                for lane, eng in zip(self.lanes, replacements)
            ]
            self._factory = factory
        if self.metrics is not None:
            self.metrics.record_swap()
        logger.info(
            "pool %s swapped %d lane engine(s); buckets now %s",
            self.name, len(old), replacements[0].buckets,
        )
        return old

    def build_replacements(
        self,
        engine_factory: Optional[EngineFactory] = None,
        warmup_example: Any = None,
    ) -> List[CompiledPipeline]:
        """Build (and optionally warm) one replacement engine per lane
        under the lanes' names — the ONE generation-build loop, shared
        by ``swap()``'s build-inline path and the Gateway warm pool
        (which runs it outside this pool's lock and hands the result
        back via ``swap(engines=...)``)."""
        factory = engine_factory or self._factory
        replacements = []
        for lane in self.lanes:
            eng = factory(self.lane_name(lane.index))
            if warmup_example is not None:
                eng.warmup(example=warmup_example)
            replacements.append(eng)
        return replacements

    def warmup(self, example: Any) -> None:
        for lane in self.lanes:
            lane.engine.warmup(example=example)

    def close(self, timeout: Optional[float] = 10.0) -> None:
        """Stop accepting, then flush every lane's batcher (pending
        windows dispatch and their futures resolve)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for lane in self.lanes:
            lane.close(timeout=timeout)

    def __enter__(self) -> "EnginePool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
