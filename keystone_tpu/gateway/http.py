"""HTTP inference frontend: the network face of the gateway.

A stdlib ``http.server`` on a background daemon thread, following the
``observability/admin.py`` server pattern (nothing to install, ephemeral
``port=0`` for tests/smoke, daemon threads per request). Routes:

- ``POST /predict`` — body ``{"instances": [<example>, ...]}`` (each
  instance one example WITHOUT the batch axis; numbers nest as JSON
  arrays), optional ``"deadline_ms"``. Every instance is admitted
  individually, so concurrent clients coalesce in the micro-batchers.
  Under ``--device-featurize`` (``input_dtype=uint8``) instances are
  RAW uint8 images — the staging path carries raw bytes and the fused
  featurize∘model bucket program does the rest on device.
  Responds ``{"predictions": [...]}``; typed errors map to status
  codes: 429 shed (``Overloaded``: queue_full/deadline), 504 expired,
  503 draining/closed, 400 malformed, 500 engine error. An inbound
  W3C ``traceparent`` header (the fleet router sends one per forward)
  is ADOPTED: every instance's admit → coalesce → dispatch span
  chain, the latency exemplars, and any flight-recorder capture ride
  the caller's trace id, and every response — success AND typed
  shed — echoes it as ``X-Keystone-Trace`` (with tracing on and no
  inbound context, this process roots the trace itself).
- ``POST /predict/<model>`` — the model-zoo route (``--zoo``): same
  body/contract, served by the NAMED model's own gateway unit with
  that model's input dtype; bare ``/predict`` keeps serving the zoo's
  default model, so single-model clients survive the upgrade. An id
  the registry doesn't know gets a TYPED 404 —
  ``{"error": "unknown_model", "model": ..., "registered": [...]}`` —
  instead of prose (the fleet router forwards the path and relays
  this body verbatim). Without ``--zoo`` the route 404s the same way
  with an empty ``registered`` list.
- ``GET /planz`` — zoo mode only: the applied ``PlacementPlan`` (or
  null when serving on spec flags) next to every model's ACTUAL shape
  (resident/cold, lanes, buckets, shared-prefix membership) — the
  plan-vs-actual audit surface of ``--optimize``.
- ``GET /attributionz`` — zoo mode only: the per-model device-cost
  ledger (``observability/attribution.py``): device-seconds share,
  modeled-FLOP share, seconds-per-GFLOP, goodput fraction, staging
  bytes, and a top-k table — shared-prefix (CSE) windows fair-split
  so per-model totals sum exactly to engine totals.
- ``GET /driftz`` — zoo mode only: live-vs-plan workload drift
  (``observability/drift.py``): per-model PSI of the windowed live
  request-size histogram against the applied plan's assumed one,
  plus — once any model trips the threshold — a RECOMMENDATION-ONLY
  re-plan diff (``plan_placement`` re-run on live profiles; applying
  it stays an operator decision). Each POST /predict observes its
  instance count as one live size sample.
- ``GET /readyz`` — 200 while the gateway admits, 503 once draining.
  READINESS, not liveness: the admin endpoint's ``/healthz`` answers
  "is the process up", this answers "should the load balancer route
  here" — a draining gateway is alive but not ready. With SLOs
  declared, an active burn/pressure state is appended to the body
  (still 200: burning means "send less", not "stop sending"). Every
  response carries an ``X-Keystone-Load`` header (queued + in-lane
  requests) — the fleet router's probes read this replica's routing
  load from the same request its health comes from. A convenience
  ``GET /healthz`` is also served for single-port deployments.
- ``GET /metrics`` — Prometheus exposition of the (global) registry,
  so a gateway-only deployment is scrapeable without the admin server
  (latency-histogram buckets carry ``trace_id`` exemplars).
- ``GET /slz`` / ``GET /debugz`` / ``GET /tracez`` — the SLO
  burn-rate, flight-recorder, and recent-span surfaces, mirrored from
  the admin endpoint for single-port deployments (``/tracez`` shows
  the per-window ``microbatch.coalesce`` → ``pipeline.host_prep`` /
  ``.upload`` / ``.compute`` / ``.deliver`` stage chains when the
  lanes run pipelined and tracing is on).
- ``GET /profilez?seconds=N`` — arm a ``jax.profiler`` trace around
  the next N seconds of live traffic and list the capture directory
  (Perfetto/XProf); 409 while another capture runs — mirrored from the
  admin endpoint (``observability/profilez.py``) so a gateway-only
  deployment can still grab a device trace. The server also runs the
  device-memory sampler, so ``/metrics`` here carries the
  ``keystone_device_memory_bytes`` and ``keystone_device_info``
  families without an admin port.
- ``POST /swap`` — force one lifecycle iteration
  (``Gateway.rebucket(force=True)``); returns the active bucket set.
  The smoke script's forced-swap drill.
- ``POST /drain`` — begin graceful shutdown in the background;
  ``/readyz`` flips 503 immediately, admitted requests resolve.
- ``GET /chaosz`` / ``POST /chaosz`` — the fault-injection plane's
  admin surface (``loadgen/faults.py``): GET lists the fault-point
  catalog, armed specs, and fire counts; POST ``{"arm": {"point":
  ..., "count": ..., "delay_ms": ..., "for_s": ..., "match": {...}}}``
  arms a point in THIS process (400 for a point outside the catalog),
  ``{"disarm": "<point>"}`` / ``{"disarm": "*"}`` clears. This is how
  the load generator injects faults into a live gateway from outside.

With ``--request-log`` (or ``GatewayServer(request_log=True)``) every
``/predict`` instance also emits one structured JSON line — ``{"ts",
"status", "latency_ms", "lane", "trace_id", "n_rows", "shape",
"deadline_ms"}`` — so a flight-recorder trace id found at ``/debugz``
is greppable straight from the process log, and the line carries
enough to RECONSTRUCT the request: ``loadgen/trace.py`` parses these
records back into a replayable trace (``n_rows`` = instances in the
originating POST; old-format lines without the replay fields still
parse as single-instance events). Lines go to stdout by default;
``--request-log FILE`` (or ``GatewayServer(request_log="path")``)
appends them line-buffered to a JSONL file instead, so record/replay
needs no process-output scraping.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from keystone_tpu.gateway.admission import Overloaded
from keystone_tpu.gateway.lifecycle import Gateway
from keystone_tpu.loadgen import faults
from keystone_tpu.observability import device as device_obs
from keystone_tpu.observability import flight as flight_mod
from keystone_tpu.observability import profilez as profilez_mod
from keystone_tpu.observability import prometheus
from keystone_tpu.observability import slo as slo_mod
from keystone_tpu.observability.httpd import (
    BackgroundServer,
    JsonHandler,
    RequestLogWriter,
    next_post_seq,
)
from keystone_tpu.observability.registry import get_global_registry
from keystone_tpu.observability.tracing import (
    TRACEPARENT_HEADER,
    TRACE_RESPONSE_HEADER,
    get_tracer,
    new_trace_id,
    parse_traceparent,
)

logger = logging.getLogger(__name__)

# generous server-side ceiling for waiting on one prediction; requests
# with their own deadline wait deadline + slack instead
RESULT_TIMEOUT_S = 60.0


def _status_for(err: Overloaded) -> int:
    if err.reason == "closed":
        return 503
    if err.reason == "expired":
        return 504
    return 429


class _Handler(JsonHandler):
    def _send(self, code, body, content_type, headers=None) -> None:
        # every response of a traced /predict (success, typed shed,
        # error) carries the trace id — the client's forensic handle
        # into /debugz?trace_id= on whichever process served it
        tid = getattr(self, "_trace_id", None)
        if tid:
            headers = {**(headers or {}), TRACE_RESPONSE_HEADER: tid}
        super()._send(code, body, content_type, headers=headers)

    def _send_error_json(self, code: int, error: str, **extra) -> None:
        self._send_json({"error": error, **extra}, code=code)

    @property
    def zoo(self):
        return self.server.zoo  # type: ignore[attr-defined]

    @property
    def lifecycle(self):
        """The LifecycleManager, if this frontend runs one — set
        directly (``--refit``) or attached to the zoo
        (``ModelZoo.attach_lifecycle``)."""
        mgr = self.server.lifecycle  # type: ignore[attr-defined]
        if mgr is None and self.zoo is not None:
            mgr = getattr(self.zoo, "lifecycle", None)
        return mgr

    @property
    def gateway(self) -> Gateway:
        gw = self.server.gateway  # type: ignore[attr-defined]
        if gw is None:
            # zoo mode: single-gateway routes (/swap's non-zoo shape,
            # legacy callers) act on the DEFAULT model's unit
            zoo = self.zoo
            return zoo.gateway_for(zoo.registry.default_id)
        return gw

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler API)
        url = urlparse(self.path)
        path = url.path
        self._trace_id = None  # per-request (keep-alive safety)
        try:
            if path == "/readyz" and self.zoo is not None:
                # zoo readiness: every RESIDENT unit admitting; load
                # is the sum across units (cold models contribute 0 —
                # they hold no queue to be loaded on)
                zoo = self.zoo
                load_headers = {
                    "X-Keystone-Load": str(zoo.total_load())
                }
                if zoo.ready:
                    self._send_text(200, "ok\n", headers=load_headers)
                else:
                    self._send_text(
                        503, "draining\n", headers=load_headers
                    )
            elif path == "/readyz":
                # the load-report header: queued + in-lane requests,
                # so the fleet router's probe reads this replica's
                # routing load without a full /metrics scrape
                load_headers = {
                    "X-Keystone-Load": str(
                        self.gateway.admission.queue_depth
                        + self.gateway.pool.total_load()
                    )
                }
                if self.gateway.ready:
                    status = self.gateway.slo_status()
                    if status is not None and (
                        status["pressure"] > 0 or status["breaching"]
                    ):
                        # burning is visible here but still 200: the
                        # LB should keep routing, admission itself is
                        # doing the early shedding
                        self._send_text(
                            200,
                            "ok (slo burning: "
                            f"pressure={status['pressure']:.2f} "
                            f"fast={status['burn_rate'].get('fast')})\n",
                            headers=load_headers,
                        )
                    else:
                        self._send_text(
                            200, "ok\n", headers=load_headers
                        )
                else:
                    self._send_text(
                        503, "draining\n", headers=load_headers
                    )
            elif path == "/healthz":
                self._send_text(200, "ok\n")
            elif path == "/metrics":
                registry = self.server.registry  # type: ignore[attr-defined]
                body, ctype = prometheus.negotiate_render(
                    registry.collect(), self.headers.get("Accept")
                )
                self._send(200, body.encode("utf-8"), ctype)
            elif path == "/planz":
                if self.zoo is None:
                    self._send_error_json(
                        404, "no_zoo",
                        detail="started without --zoo; /planz reports "
                               "the model-zoo placement plan",
                    )
                else:
                    self._send_json(self.zoo.planz(), indent=1)
            elif path == "/attributionz":
                if self.zoo is None:
                    self._send_error_json(
                        404, "no_zoo",
                        detail="started without --zoo; /attributionz "
                               "reports the per-model device-cost "
                               "ledger",
                    )
                else:
                    self._send_json(self.zoo.attributionz(), indent=1)
            elif path == "/driftz":
                if self.zoo is None:
                    self._send_error_json(
                        404, "no_zoo",
                        detail="started without --zoo; /driftz reports "
                               "live-vs-plan workload drift and the "
                               "re-plan recommendation",
                    )
                else:
                    self._send_json(self.zoo.driftz(), indent=1)
            elif path == "/slz":
                self._send_json(slo_mod.slz_status(), indent=1)
            elif path == "/debugz":
                q = parse_qs(url.query)
                code, doc = flight_mod.debugz_document(
                    q.get("trace_id", [None])[0],
                    q.get("format", [""])[0],
                )
                self._send_json(doc, code=code, indent=1)
            elif path == "/profilez":
                q = parse_qs(url.query)
                code, doc = profilez_mod.profilez_document(
                    q.get("seconds", [None])[0]
                )
                self._send_json(doc, code=code, indent=1)
            elif path == "/chaosz":
                if not self.server.chaos_routes:  # type: ignore[attr-defined]
                    self._send_error_json(
                        404, "chaos_routes_disabled",
                        detail="started with --no-chaosz",
                    )
                else:
                    self._send_json(
                        faults.get_injector().status(), indent=1
                    )
            elif path == "/lifecyclez":
                if self.lifecycle is None:
                    self._send_error_json(
                        404, "no_lifecycle",
                        detail="started without --refit; /lifecyclez "
                               "reports the online-lifecycle state "
                               "machine per model",
                    )
                else:
                    self._send_json(self.lifecycle.status(), indent=1)
            elif path == "/tracez":
                from keystone_tpu.observability.tracing import (
                    tracez_document,
                )

                q = parse_qs(url.query)
                self._send_json(
                    tracez_document(
                        get_tracer(),
                        q.get("format", [""])[0],
                        q["n"][0] if "n" in q else None,
                    ),
                    indent=1,
                )
            else:
                self._send_text(
                    404,
                    "not found; try /predict /predict/<model> /planz "
                    "/attributionz /driftz /readyz /healthz /metrics "
                    "/slz /debugz /tracez /profilez /chaosz "
                    "/lifecyclez\n",
                )
        except Exception as e:
            logger.exception("gateway GET error for %s", self.path)
            self._send_error_json(500, "internal", detail=str(e))

    def _log_request(
        self,
        status: int,
        latency_s: float,
        lane: Optional[int] = None,
        trace_id: Optional[str] = None,
        error: Optional[str] = None,
        n_rows: Optional[int] = None,
        shape: Optional[tuple] = None,
        deadline_ms: Optional[float] = None,
    ) -> None:
        """One structured JSON line per /predict instance
        (``--request-log``): trace ids surfaced at /debugz are
        greppable straight from the process log, and the
        ``n_rows``/``shape``/``deadline_ms`` fields make the record
        REPLAYABLE (``loadgen/trace.py`` reconstructs the request
        from them; pre-loadgen readers can ignore the extra keys)."""
        meta = getattr(self, "_log_meta", None) or {}
        line = {
            # arrival time (see do_POST), so replay preserves the
            # recorded arrival pattern rather than completion order
            "ts": round(getattr(self, "_t_wall", None) or time.time(), 6),
            "path": "/predict",
            "status": status,
            "latency_ms": round(latency_s * 1e3, 3),
            "lane": lane,
            "trace_id": trace_id,
            "n_rows": n_rows if n_rows is not None else meta.get("n_rows"),
            "shape": (
                list(shape) if shape is not None else meta.get("shape")
            ),
            "deadline_ms": (
                deadline_ms if deadline_ms is not None
                else meta.get("deadline_ms")
            ),
            "post_seq": meta.get("post_seq"),
            # zoo mode: which named model served the instance (None on
            # the bare single-model route; replay targets the same id)
            "model": meta.get("model"),
        }
        if error is not None:
            line["error"] = error
        self.server.write_request_log(line)  # type: ignore[attr-defined]

    def do_POST(self) -> None:  # noqa: N802 (stdlib handler API)
        path = urlparse(self.path).path
        self._trace_id = None  # _predict adopts/mints; see _send
        self._t_post = time.perf_counter()
        # ARRIVAL wall time: request-log lines stamp this (not
        # log-emit time, which for success lines is after the whole
        # POST resolved) — the replayer treats ts as the arrival
        # clock, so completion-time stamps would distort the recorded
        # inter-arrival gaps by per-request latency
        self._t_wall = time.time()
        # request-log context for the error handlers below; _predict
        # fills it once the body parses
        self._log_meta = {}
        try:
            if path == "/predict" or path.startswith("/predict/"):
                model_id = path[len("/predict/"):] if (
                    path.startswith("/predict/")
                ) else None
                self._predict(model_id or None)
            elif path == "/chaosz":
                self._chaosz()
            elif path == "/feedback" or path.startswith("/feedback/"):
                model_id = path[len("/feedback/"):] if (
                    path.startswith("/feedback/")
                ) else None
                self._feedback(model_id or None)
            elif path == "/lifecyclez":
                self._lifecyclez_post()
            elif path == "/swap":
                if self.zoo is not None:
                    self._send_json(
                        {"swapped": self.zoo.rebucket(force=True)}
                    )
                else:
                    swapped = self.gateway.rebucket(force=True)
                    self._send_json(
                        {
                            "swapped": swapped,
                            "buckets": list(self.gateway.buckets),
                        }
                    )
            elif path == "/drain":
                target = (
                    self.zoo.close if self.zoo is not None
                    else self.gateway.close
                )
                threading.Thread(
                    target=target,
                    name="keystone-gateway-drain",
                    daemon=True,
                ).start()
                self._send_json({"draining": True})
            else:
                self._send_text(
                    404,
                    "not found; try /predict /predict/<model> /swap "
                    "/drain /chaosz /feedback /lifecyclez\n",
                )
        except Overloaded as e:
            code = _status_for(e)
            if path == "/predict" and self.server.request_log:  # type: ignore[attr-defined]
                self._log_request(
                    code, time.perf_counter() - self._t_post,
                    error=e.reason,
                )
            self._send_error_json(
                code, "overloaded", reason=e.reason,
                detail=str(e),
            )
        except Exception as e:
            logger.exception("gateway POST error for %s", self.path)
            if path == "/predict" and self.server.request_log:  # type: ignore[attr-defined]
                self._log_request(
                    500, time.perf_counter() - self._t_post,
                    error=str(e),
                )
            self._send_error_json(500, "internal", detail=str(e))

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0) or 0)
        return self.rfile.read(length) if length else b""

    def _chaosz(self) -> None:
        """Arm/disarm fault points in this process (the load
        generator's remote chaos control; see loadgen/faults.py)."""
        if not self.server.chaos_routes:  # type: ignore[attr-defined]
            self._send_error_json(
                404, "chaos_routes_disabled",
                detail="started with --no-chaosz",
            )
            return
        injector = faults.get_injector()
        try:
            doc = json.loads(self._read_body() or b"{}")
        except ValueError as e:
            self._send_error_json(400, "bad_request", detail=str(e))
            return
        if "arm" in doc:
            spec = doc["arm"]
            if not isinstance(spec, dict) or "point" not in spec:
                self._send_error_json(
                    400, "bad_request",
                    detail='arm wants {"point": ..., [count/delay_ms/'
                           'for_s/match]}',
                )
                return
            spec = dict(spec)
            point = spec.pop("point")
            if point not in faults.FAULT_POINTS:
                self._send_error_json(
                    400, "unknown_fault_point", point=point,
                    known=sorted(faults.FAULT_POINTS),
                )
                return
            try:
                injector.arm(point, **spec)
            except (TypeError, ValueError) as e:
                self._send_error_json(400, "bad_request", detail=str(e))
                return
        elif "disarm" in doc:
            point = doc["disarm"]
            if point == "*":
                injector.disarm_all()
            else:
                injector.disarm(point)
        else:
            self._send_error_json(
                400, "bad_request",
                detail='want {"arm": {...}} or {"disarm": "<point>|*"}',
            )
            return
        self._send_json(injector.status(), indent=1)

    def _feedback(self, model_id: Optional[str] = None) -> None:
        """Queue one labeled batch for the streaming refit. Body:
        ``{"instances": [[...], ...], "labels": [[...], ...]}``. The
        accumulation itself happens at policy-tick time, off this
        request path — the handler only validates shapes and appends
        to the controller's buffer."""
        mgr = self.lifecycle
        if mgr is None:
            self._send_error_json(
                404, "no_lifecycle",
                detail="started without --refit; /feedback feeds the "
                       "streaming-refit accumulator",
            )
            return
        controller = mgr.get(model_id)
        if controller is None:
            self._send_error_json(
                404, "unknown_lifecycle_model", model=model_id,
                known=mgr.models(),
            )
            return
        try:
            doc = json.loads(self._read_body() or b"{}")
            instances = doc["instances"]
            labels = doc["labels"]
        except (ValueError, KeyError, TypeError) as e:
            self._send_error_json(
                400, "bad_request",
                detail='want {"instances": [[...]], "labels": '
                       f'[[...]]}} ({e})',
            )
            return
        try:
            n = controller.add_feedback(instances, labels)
        except (ValueError, RuntimeError) as e:
            self._send_error_json(400, "bad_request", detail=str(e))
            return
        self._send_json({"queued": n, "model": controller.name})

    def _lifecyclez_post(self) -> None:
        """Operator controls (``serve-lifecycle``): ``{"tick": true}``
        forces one policy tick on every controller; ``{"rollback":
        true[, "model": m]}`` forces a rollback on one controller."""
        mgr = self.lifecycle
        if mgr is None:
            self._send_error_json(
                404, "no_lifecycle",
                detail="started without --refit",
            )
            return
        try:
            doc = json.loads(self._read_body() or b"{}")
        except ValueError as e:
            self._send_error_json(400, "bad_request", detail=str(e))
            return
        if doc.get("tick"):
            self._send_json({"ticked": mgr.tick_all()}, indent=1)
        elif doc.get("rollback"):
            controller = mgr.get(doc.get("model"))
            if controller is None:
                self._send_error_json(
                    404, "unknown_lifecycle_model",
                    model=doc.get("model"), known=mgr.models(),
                )
                return
            self._send_json(
                {"rolled_back": controller.force_rollback("manual")},
                indent=1,
            )
        else:
            self._send_error_json(
                400, "bad_request",
                detail='want {"tick": true} or {"rollback": true'
                       '[, "model": m]}',
            )

    def _predict(self, model_id: Optional[str] = None) -> None:
        # W3C trace adoption FIRST, before the body can 400 or
        # admission can shed: the router (or any tracing caller) sent
        # a `traceparent`, and EVERY response — success, typed shed,
        # malformed body — must echo the one trace id the fleet knows
        # this request by. With no inbound context and tracing on,
        # this process roots the trace itself (single-gateway mode).
        ctx = parse_traceparent(self.headers.get(TRACEPARENT_HEADER))
        if ctx is not None:
            self._trace_id = ctx.trace_id
        elif get_tracer().enabled:
            self._trace_id = new_trace_id()
        # model resolution before the body parse: an unknown id is a
        # typed 404 regardless of payload shape, and the error carries
        # the registered ids so the client can correct itself
        zoo = self.zoo
        if zoo is not None:
            from keystone_tpu.zoo.registry import UnknownModel

            try:
                model_id, spec = zoo.resolve(model_id)
            except UnknownModel as e:
                self._send_error_json(
                    404, "unknown_model", model=e.model_id,
                    registered=list(e.registered),
                )
                return
            dtype = np.dtype(spec.input_dtype)

            def submit(ex, **kw):
                return zoo.predict(ex, model_id, **kw)

        elif model_id is not None:
            # single-model deployment: no named routes exist at all
            self._send_error_json(
                404, "unknown_model", model=model_id, registered=[],
                detail="single-model deployment (started without "
                       "--zoo); POST bare /predict",
            )
            return
        else:
            dtype = self.server.input_dtype  # type: ignore[attr-defined]
            submit = self.gateway.predict
        try:
            doc = json.loads(self._read_body() or b"{}")
            instances = doc["instances"]
            if not isinstance(instances, list) or not instances:
                raise ValueError("instances must be a non-empty list")
        except (ValueError, KeyError, TypeError) as e:
            self._send_error_json(400, "bad_request", detail=str(e))
            return
        deadline_ms = doc.get("deadline_ms")
        if deadline_ms is not None and (
            isinstance(deadline_ms, bool)
            or not isinstance(deadline_ms, (int, float))
            or deadline_ms <= 0
        ):
            self._send_error_json(
                400, "bad_request",
                detail=f"deadline_ms must be a positive number, "
                       f"got {deadline_ms!r}",
            )
            return
        try:
            # OverflowError: an out-of-range integer against a narrow
            # dtype (a 256 pixel under --device-featurize's uint8) is
            # a malformed REQUEST — 400, not a 500 + stack trace
            examples = [np.asarray(inst, dtype=dtype) for inst in instances]
        except (ValueError, TypeError, OverflowError) as e:
            self._send_error_json(400, "bad_request", detail=str(e))
            return
        # replay context for every log line this POST emits (including
        # the typed-shed/error lines in do_POST's handlers): what the
        # request WAS, so loadgen can reissue it
        self._log_meta = {
            "n_rows": len(examples),
            "shape": list(examples[0].shape),
            "deadline_ms": deadline_ms,
            "post_seq": next_post_seq(),
            "model": model_id,
        }
        if zoo is not None:
            # one drift observation per POST: the request's SIZE is its
            # instance count — the same unit the placement planner's
            # expected-size histograms are drawn in, so live-vs-plan
            # PSI (observability/drift.py) compares like with like
            zoo.observe_request(model_id, len(examples))
        # admit every instance BEFORE waiting on any: concurrent
        # instances coalesce into shared micro-batch windows. Every
        # instance of one POST shares the POST's trace id — the span
        # trees of sibling instances interleave under one trace, which
        # is what the router's cross-process stitch joins on.
        futures = []
        try:
            for ex in examples:
                futures.append(
                    submit(
                        ex,
                        deadline_ms=deadline_ms,
                        trace_id=self._trace_id,
                    )
                )
        except Overloaded:
            # partial admission on a shed response: cancel what was
            # already admitted so the engines don't burn overload-time
            # cycles computing results this 429 discards
            for f in futures:
                f.cancel()
            raise  # -> do_POST's typed handler
        timeout = (
            deadline_ms / 1e3 + 5.0
            if deadline_ms is not None
            else RESULT_TIMEOUT_S
        )
        try:
            preds = [np.asarray(f.result(timeout=timeout)) for f in futures]
        except Overloaded:
            # one instance shed/expired -> whole response is an error:
            # cancel the siblings so engines don't compute answers this
            # response discards (same reason as the admission path above)
            for f in futures:
                f.cancel()
            raise
        except Exception as e:
            for f in futures:
                f.cancel()
            if self.server.request_log:  # type: ignore[attr-defined]
                self._log_request(
                    500, time.perf_counter() - self._t_post,
                    error=str(e),
                )
            self._send_error_json(500, "prediction_failed", detail=str(e))
            return
        if self.server.request_log:  # type: ignore[attr-defined]
            whole_post_s = time.perf_counter() - self._t_post
            for ex, f in zip(examples, futures):
                # per-request latency as the admission layer measured
                # it (rides the future) — iterating result() above
                # would charge every instance the wait on instance 0
                self._log_request(
                    200,
                    getattr(f, "latency_s", None) or whole_post_s,
                    lane=getattr(f, "lane_index", None),
                    trace_id=getattr(f, "trace_id", None),
                    n_rows=len(examples),
                    shape=ex.shape,
                    deadline_ms=deadline_ms,
                )
        self._send_json({"predictions": [p.tolist() for p in preds]})


class GatewayServer(BackgroundServer, device_obs.MemorySamplerHost):
    """The inference frontend over one ``Gateway``. ``start()`` binds
    and serves on a daemon thread; ``stop()`` shuts the listener down
    (the gateway itself drains via ``Gateway.close``/``/drain``)."""

    handler_cls = _Handler
    thread_name = "keystone-gateway-http"

    def __init__(
        self,
        gateway: Optional[Gateway] = None,
        port: int = 0,
        host: str = "127.0.0.1",
        registry=None,
        input_dtype: Any = np.float32,
        request_log: Any = False,
        chaos_routes: bool = True,
        zoo=None,
        lifecycle=None,
    ):
        """``request_log``: falsy = off; True = one JSON line per
        /predict instance on stdout; a path string = append the lines
        to that JSONL file, line-buffered (the loadgen record/replay
        path — no process-output scraping). ``chaos_routes=False``
        removes the /chaosz fault-injection surface from this
        frontend (a production deployment that is not a chaos
        experiment shouldn't expose sabotage routes to anyone who
        can reach /predict). ``zoo`` (a ``ModelZoo``) replaces
        ``gateway``: /predict/<model> routes by id, bare /predict
        serves the default model with ITS input dtype (the
        ``input_dtype`` arg only applies to single-gateway mode), and
        /planz reports plan-vs-actual. ``lifecycle`` (a
        ``LifecycleManager``) turns on the online-lifecycle surface:
        ``POST /feedback[/<model>]`` queues labeled examples for the
        streaming refit, ``GET /lifecyclez`` reports every model's
        refit→shadow→canary state, ``POST /lifecyclez`` forces a
        policy tick or a rollback (``serve-lifecycle``)."""
        if (gateway is None) == (zoo is None):
            raise ValueError(
                "GatewayServer wants exactly one of gateway= or zoo="
            )
        super().__init__(port=port, host=host)
        self.gateway = gateway
        self.zoo = zoo
        self.lifecycle = lifecycle
        self.registry = (
            registry if registry is not None else get_global_registry()
        )
        self.input_dtype = np.dtype(input_dtype)
        # the line-at-a-time sink (stdout or JSONL file) now lives in
        # observability/httpd.py — shared with the fleet router so
        # both tiers log the same replayable schema
        self._request_log = RequestLogWriter(request_log)
        self.request_log = self._request_log.enabled
        self.chaos_routes = bool(chaos_routes)
        # single-port deployments scrape THIS port: carry the device
        # identity gauge and the memory sampler here too, same as the
        # admin endpoint (refcounted — one thread per registry even
        # when both servers run in one process)
        device_obs.register_device_metrics(self.registry)

    def _configure(self, httpd) -> None:
        httpd.gateway = self.gateway
        httpd.zoo = self.zoo
        httpd.lifecycle = self.lifecycle
        httpd.registry = self.registry
        httpd.input_dtype = self.input_dtype
        httpd.request_log = self.request_log
        httpd.chaos_routes = self.chaos_routes
        httpd.write_request_log = self.write_request_log

    def write_request_log(self, line: dict) -> None:
        """One record to the request log (stdout or the file)."""
        self._request_log.write(line)

    def start(self) -> "GatewayServer":
        super().start()
        self._start_memory_sampler()
        return self

    def stop(self) -> None:
        self._stop_memory_sampler()
        super().stop()
        self._request_log.close()


def register_with_router(
    router_url: str,
    own_url: str,
    attempts: int = 30,
    interval_s: float = 1.0,
    cancel: Optional[threading.Event] = None,
    models=None,
) -> bool:
    """POST this gateway's base URL to a fleet router's ``/registerz``
    (``serve-gateway --register``). Retries: replicas and their router
    launch concurrently, so the router may not be listening yet — the
    registration is idempotent per URL, a later success is as good as
    a first one. ``cancel`` stops the retry loop: the DRAIN path sets
    it before deregistering, or a straggling retry could re-register
    a replica that is already exiting — recreating exactly the
    lingering-roster-entry gap deregistration closes. ``models``
    advertises the zoo model ids this replica serves (zoo mode) so
    the router can route ``/predict/<model>`` to it."""
    from keystone_tpu.fleet.client import REGISTER_ROUTE, post_roster

    for attempt in range(attempts):
        if cancel is not None and cancel.is_set():
            return False
        try:
            post_roster(
                router_url, REGISTER_ROUTE, own_url, timeout_s=10,
                models=models,
            )
            logger.info(
                "registered %s with router %s", own_url, router_url
            )
            return True
        except Exception as e:
            if attempt == attempts - 1:
                logger.warning(
                    "could not register with router %s after %d "
                    "attempts: %s", router_url, attempts, e,
                )
            if cancel is not None:
                if cancel.wait(interval_s):
                    return False
            else:
                time.sleep(interval_s)
    return False


def deregister_from_router(router_url: str, own_url: str) -> bool:
    """POST this gateway's base URL to a fleet router's
    ``/deregisterz`` — the exit half of ``register_with_router``,
    called once the drain has finished so a retired replica leaves
    the roster instead of lingering until probes fail it. ONE short
    attempt (``fleet/client.try_deregister``): a dead router must not
    stall a process exit, unlike startup registration which is
    allowed to wait for a router still binding."""
    from keystone_tpu.fleet.client import try_deregister

    return try_deregister(router_url, own_url, timeout_s=3.0)


def main(argv=None) -> int:
    """``python -m keystone_tpu serve-gateway [--gateway-port N] ...`` —
    stand up the full request plane over the serve-bench pipeline (the
    demo/smoke entry; real deployments construct ``Gateway`` over their
    own fitted pipeline)."""
    import argparse
    import time

    import jax.numpy as jnp

    from keystone_tpu.parallel.runtime import setup_compilation_cache
    from keystone_tpu.serving.bench import build_pipeline

    ap = argparse.ArgumentParser(
        prog="keystone_tpu serve-gateway", description=__doc__
    )
    ap.add_argument("--gateway-port", "--port", dest="port", type=int,
                    default=0, help="bind port (0 = ephemeral)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--buckets", default="8,32,128")
    ap.add_argument("--lanes", type=int, default=2)
    ap.add_argument("--max-pending", type=int, default=1024)
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--pipeline-depth", type=int, default=2,
                    help="stage-queue depth of each lane's staged "
                    "pipeline (host-prep/upload/compute/deliver "
                    "overlap across windows); 0 = serial dispatch")
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="default per-request deadline")
    ap.add_argument("--rebucket-interval", type=float, default=None,
                    help="seconds between autoscale/rebucket sweeps")
    ap.add_argument("--slo-latency-ms", type=float, default=None,
                    help="declare + enforce a latency SLO at this "
                    "threshold: burn-rate gauges + /slz, admission "
                    "tightening under sustained fast-window burn, and "
                    "tail-sampled forensics at /debugz (enables span "
                    "tracing)")
    ap.add_argument("--trace", action="store_true",
                    help="enable span tracing without declaring an "
                    "SLO: /tracez fills, inbound W3C traceparent "
                    "headers are adopted, and every /predict response "
                    "carries X-Keystone-Trace — what a replica behind "
                    "a tracing serve-router needs for cross-process "
                    "stitching")
    ap.add_argument("--slo-target", type=float, default=0.99,
                    help="fraction of requests that must make the "
                    "latency threshold")
    ap.add_argument("--flight-capacity", type=int, default=64,
                    help="forensic ring size (requests)")
    ap.add_argument("--request-log", nargs="?", const=True,
                    default=False, metavar="FILE",
                    help="one structured JSON line per /predict "
                    "instance (status, latency_ms, lane, trace_id, "
                    "plus the n_rows/shape/deadline_ms replay fields "
                    "loadgen consumes). Bare flag: stdout; with FILE: "
                    "append line-buffered JSONL there (record/replay "
                    "without scraping process output)")
    ap.add_argument("--no-chaosz", action="store_true",
                    help="disable the /chaosz fault-injection routes "
                    "on this frontend (for serving deployments that "
                    "are not chaos experiments; faults stay armable "
                    "in-process via code/env)")
    ap.add_argument("--register", action="append", default=[],
                    metavar="ROUTER_URL",
                    help="self-register this replica with a fleet "
                    "router (POST {url} to ROUTER_URL/registerz, "
                    "retried in the background; repeatable). The "
                    "router probes /readyz and scrapes /metrics from "
                    "then on — see keystone_tpu/fleet/")
    ap.add_argument("--advertise-url", default=None, metavar="URL",
                    help="the base URL to register (and for the "
                    "router to reach this replica at). Required for "
                    "real cross-host serving with --host 0.0.0.0: "
                    "the default advertises the BIND address, and "
                    "http://0.0.0.0:PORT means 'myself' to the "
                    "router, not to this replica")
    ap.add_argument("--zoo", default=None, metavar="SPEC.json",
                    help="serve a MODEL ZOO instead of one model: a "
                    "JSON spec of named models (see "
                    "keystone_tpu/zoo/registry.py for the format). "
                    "POST /predict/<model> routes by id, bare "
                    "/predict serves the spec's default model, GET "
                    "/planz reports plan-vs-actual. Each model gets "
                    "its own gateway lanes, metrics under its own "
                    "name, and an AOT store namespace; co-hosted "
                    "models with IDENTICAL featurize chains share one "
                    "engine that computes the prefix once per window "
                    "(cross-model CSE). Ignores the single-model "
                    "flags (--d/--hidden/--depth/--device-featurize/"
                    "--shard-model/--buckets/--lanes)")
    ap.add_argument("--optimize", action="store_true",
                    help="with --zoo: run the placement optimizer "
                    "(zoo/optimizer.py) over the spec's expected-size "
                    "histograms, measured param bytes, and the "
                    "per-chip HBM budget, and host each model with "
                    "the PLANNED buckets/lanes/sharding instead of "
                    "its spec flags; /planz shows the plan next to "
                    "the actual pool shapes")
    ap.add_argument("--max-resident", type=int, default=None,
                    metavar="N",
                    help="with --zoo: cap how many models hold "
                    "compiled engines at once; over the cap the "
                    "least-recently-used unpinned model is evicted "
                    "(drains in the background) and pages back in on "
                    "its next request (default: all models resident)")
    ap.add_argument("--refit", action="store_true",
                    help="run the ONLINE MODEL LIFECYCLE over the "
                    "demo model: POST /feedback streams labeled "
                    "examples into an incremental normal-equations "
                    "refit of the model's head; each solved candidate "
                    "walks shadow -> canary -> promoted (atomic "
                    "engine swap) or auto-rolls back on the accuracy/"
                    "SLO gates. GET /lifecyclez reports the state "
                    "machine; serve-lifecycle drives it remotely. "
                    "Single-model mode only (not --zoo/"
                    "--device-featurize)")
    ap.add_argument("--refit-interval-s", type=float, default=2.0,
                    metavar="S",
                    help="with --refit: background policy-tick "
                    "period; 0 disables the thread (tick via POST "
                    "/lifecyclez, e.g. serve-lifecycle tick)")
    ap.add_argument("--refit-min-samples", type=int, default=256,
                    metavar="N",
                    help="with --refit: fresh feedback rows required "
                    "before a new candidate is solved")
    ap.add_argument("--canary-fraction", type=float, default=0.25,
                    metavar="F",
                    help="with --refit: deterministic fraction of "
                    "live requests the canary stage routes to the "
                    "candidate")
    ap.add_argument("--d", type=int, default=256)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--depth", type=int, default=4)
    ap.add_argument("--device-featurize", nargs="?", const="demo",
                    choices=("demo", "flagship"), default=None,
                    metavar="CHAIN",
                    help="serve RAW uint8 images instead of f32 "
                    "feature vectors: a pure-JAX image featurize "
                    "chain (serving/featurize.py) is fused in front "
                    "of the model inside every bucket program, so "
                    "/predict instances are (--img, --img, 3) uint8 "
                    "arrays, the wire/staging path carries fewer "
                    "bytes, and cast + featurize + predict ride one "
                    "compiled dispatch (--d is derived from the "
                    "featurize output and ignored). CHAIN picks the "
                    "chain: 'demo' (bare flag; the dense-conv stack, "
                    "default --img 16) or 'flagship' (the branched "
                    "SIFT+LCS -> PCA -> GMM Fisher Vector DAG with "
                    "Pallas hot loops, default --img 64)")
    ap.add_argument("--img", type=int, default=None,
                    help="raw image edge length under "
                    "--device-featurize (default: 16 for the demo "
                    "chain, 64 for flagship)")
    ap.add_argument("--shard-model", action="store_true",
                    help="mesh-shard the MODEL over the local devices "
                    "(serving/sharding.py): the process mesh is pinned "
                    "to (data=1, model=<all devices>), the default "
                    "partition rules split every weight matrix over "
                    "the model axis, and each lane engine's bucket "
                    "programs run GSPMD-partitioned with the params as "
                    "sharded arguments — models bigger than one chip's "
                    "HBM serve on the mesh. Typically combined with "
                    "--lanes 1 (each lane places its own param copy)")
    ap.add_argument("--mesh-model", type=int, default=None,
                    metavar="N",
                    help="model-axis size under --shard-model "
                    "(default: all local devices); remaining devices "
                    "go to the data axis")
    ap.add_argument("--no-cache", action="store_true",
                    help="run with NO persistence: skips both the "
                    "persistent XLA compile cache and the AOT "
                    "serialized-executable store, so every bucket "
                    "pays a real trace + compile")
    ap.add_argument("--aot-cache", default=None, metavar="DIR",
                    help="AOT executable store dir (default: "
                    "$KEYSTONE_AOT_CACHE, then "
                    "~/.cache/keystone_tpu/aot); pre-populate with "
                    "serve-aot-build for a zero-compile cold start. "
                    "Ignored under --no-cache")
    args = ap.parse_args(argv)
    if not args.no_cache:
        setup_compilation_cache()
        from keystone_tpu.parallel.runtime import setup_aot_cache

        setup_aot_cache(args.aot_cache)

    if args.slo_latency_ms is not None or args.trace:
        # the forensic chain (exemplars, flight records, burn gauges)
        # keys off trace ids, so SLO mode implies tracing; --trace
        # turns the span plane on without an SLO (fleet stitching)
        from keystone_tpu.observability import enable_tracing

        enable_tracing()

    if args.refit and (args.zoo or args.device_featurize):
        print(
            "--refit wants the plain demo model (not --zoo / "
            "--device-featurize)",
            flush=True,
        )
        return 2
    featurize = None
    input_dtype = np.float32
    zoo = None
    gateway = None
    refit_base = refit_head = None
    if args.zoo:
        from keystone_tpu.zoo import ModelZoo, load_zoo_spec

        model_registry = load_zoo_spec(args.zoo)
        zoo = ModelZoo(model_registry, max_resident=args.max_resident)
        if args.optimize:
            import jax

            from keystone_tpu.observability.device import (
                chip_hbm_bytes,
            )
            from keystone_tpu.zoo.optimizer import (
                ChipBudget,
                plan_placement,
            )

            # plan BEFORE hosting: profiles(build=True) materializes
            # params (cheap, host memory) so params_nbytes is measured
            # not guessed; hosting then happens under the plan.
            # apply_plan (not a bare assignment) also pins each
            # profile's histogram as the drift-detector baseline and
            # keeps the budget for /driftz re-plan audits
            profiles = zoo.profiles(build=True)
            budget = ChipBudget(
                hbm_bytes=chip_hbm_bytes(),
                n_chips=len(jax.devices()),
            )
            zoo.apply_plan(
                plan_placement(profiles, budget),
                budget=budget,
                profiles=profiles,
            )
            print(
                json.dumps({"plan": zoo.plan.to_dict()}), flush=True
            )
        if args.max_resident is None:
            # everything resident up-front: one host() call, so CSE
            # groups form across the whole spec
            zoo.host()
        else:
            # capped: warm the pinned set + the default model now,
            # the rest page in on first request
            want = [s.model_id for s in model_registry if s.pinned]
            if model_registry.default_id not in want:
                want.append(model_registry.default_id)
            zoo.host(want)
    elif args.device_featurize:
        from keystone_tpu.serving.featurize import (
            build_featurize_pipeline,
            build_flagship_featurize_pipeline,
        )

        if args.device_featurize == "flagship":
            args.img = args.img if args.img is not None else 64
            featurize, feat_d = build_flagship_featurize_pipeline(
                img=args.img
            )
        else:
            args.img = args.img if args.img is not None else 16
            featurize, feat_d = build_featurize_pipeline(img=args.img)
        args.d = feat_d  # the model consumes the featurize output
        warmup_example = jnp.zeros((args.img, args.img, 3), jnp.uint8)
        input_dtype = np.uint8
    if zoo is None:
        if args.refit:
            # the SAME model build_pipeline serves (identical rng
            # draws → bitwise-equal outputs), split at the last layer
            # so the lifecycle can refit the head in closed form and
            # rebuild candidates as base.and_then(affine_head(W, b))
            from keystone_tpu.serving.bench import (
                affine_head,
                build_split_pipeline,
            )

            refit_base, head_w, head_b = build_split_pipeline(
                d=args.d, hidden=args.hidden, depth=args.depth
            )
            refit_head = affine_head
            fitted = refit_base.and_then(affine_head(head_w, head_b))
        else:
            fitted = build_pipeline(
                d=args.d, hidden=args.hidden, depth=args.depth
            )
        if not args.device_featurize:
            warmup_example = jnp.zeros((args.d,), jnp.float32)
        if args.shard_model:
            # pin the process mesh so EVERY engine generation (initial
            # build, rebuckets, warm-pool swaps) places over the same
            # (data, model) topology
            import jax

            from keystone_tpu.parallel import mesh as mesh_lib

            n_model = args.mesh_model or len(jax.devices())
            mesh_lib.set_mesh(mesh_lib.make_mesh(n_model=n_model))
        gateway = Gateway(
            fitted,
            buckets=tuple(int(b) for b in args.buckets.split(",")),
            n_lanes=args.lanes,
            max_delay_ms=args.max_delay_ms,
            pipeline_depth=args.pipeline_depth,
            device_featurize=featurize,
            param_sharding=True if args.shard_model else None,
            warmup_example=warmup_example,
            max_pending=args.max_pending,
            default_deadline_ms=args.deadline_ms,
            maintenance_interval_s=args.rebucket_interval,
            slo_latency_s=(
                args.slo_latency_ms / 1e3
                if args.slo_latency_ms is not None else None
            ),
            slo_target=args.slo_target,
            flight_capacity=args.flight_capacity,
        )
        gateway.install_signal_handlers()
    else:
        # zoo mode: SIGTERM/SIGINT drain the whole zoo (every unit
        # concurrently) instead of one gateway
        import signal

        def _drain(signum, frame):
            threading.Thread(
                target=zoo.close,
                name="keystone-zoo-drain",
                daemon=True,
            ).start()

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, _drain)
            except ValueError:
                pass  # not the main thread (embedded use)
    # chaos experiments can pre-arm fault points from the environment
    # (KEYSTONE_FAULTS="point=k:v,... ..."); absent env is a no-op.
    # This must run AFTER the Gateway exists: trigger points
    # (gateway.swap.force) disarm immediately when nothing has
    # registered for them, so arming before construction would be a
    # silent no-op.
    faults.arm_from_env()
    lifecycle = None
    if args.refit:
        from keystone_tpu.lifecycle import LifecycleManager
        from keystone_tpu.lifecycle.controller import (
            LifecycleController,
        )

        lifecycle = LifecycleManager()
        lifecycle.add(
            LifecycleController(
                gateway,
                base=refit_base,
                head_builder=refit_head,
                feature_dim=args.hidden,
                out_dim=args.d,
                name="default",
                canary_fraction=args.canary_fraction,
                min_refit_samples=args.refit_min_samples,
                interval_s=args.refit_interval_s or None,
                aot_namespace="lifecycle/default",
            ),
            default=True,
        )
    server = GatewayServer(
        gateway, port=args.port, host=args.host,
        input_dtype=input_dtype,
        request_log=args.request_log,
        chaos_routes=not args.no_chaosz,
        zoo=zoo,
        lifecycle=lifecycle,
    ).start()
    # the machine-parseable bound-address line FIRST: with --port 0
    # (ephemeral — no port races) smoke scripts and the fleet drills
    # read the actual address off this one JSON line instead of
    # scraping the human summary below
    print(
        json.dumps(
            {
                "listening": server.url().rstrip("/"),
                "role": "gateway",
                **(
                    {"models": list(zoo.registry.ids())}
                    if zoo is not None else {}
                ),
            }
        ),
        flush=True,
    )
    zoo_routes = (
        "POST /predict/<model>, GET /planz, GET /attributionz, "
        "GET /driftz, " if zoo is not None else ""
    )
    lifecycle_routes = (
        "POST /feedback, GET|POST /lifecyclez, "
        if lifecycle is not None else ""
    )
    print(
        f"gateway: {server.url()} (POST /predict, {zoo_routes}"
        f"{lifecycle_routes}"
        "GET /readyz, GET /metrics, GET /slz, GET /debugz, "
        "GET /profilez, POST /swap, POST /drain, GET|POST /chaosz)",
        flush=True,
    )
    advertised = args.advertise_url or server.url()
    # set on drain, BEFORE deregistering: a registration retry that
    # outlives the drain must not re-add this replica to the roster
    cancel_registration = threading.Event()
    for router_url in args.register:
        # background: registration retries must not delay serving.
        # Zoo mode advertises the registry's model ids so the router
        # can route /predict/<model> here.
        threading.Thread(
            target=register_with_router,
            args=(router_url, advertised),
            kwargs={
                "cancel": cancel_registration,
                "models": (
                    list(zoo.registry.ids()) if zoo is not None
                    else None
                ),
            },
            name="keystone-gateway-register",
            daemon=True,
        ).start()
    plane = zoo if zoo is not None else gateway
    try:
        while plane.ready:
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    # the graceful-exit protocol, in order: stop any registration
    # retries (a straggler would re-register a dying replica), finish
    # the drain (stop admitting, resolve in-flight windows), THEN
    # deregister from every router this replica joined — the roster
    # entry outliving the drain is harmless (the router fails over on
    # 503-closed), the reverse order would drop the roster entry
    # while work is still in flight behind it
    cancel_registration.set()
    if lifecycle is not None:
        # stop the refit/tick plane BEFORE draining the gateway:
        # a tick mid-drain would race swap_model against close()
        lifecycle.close()
    plane.close()
    for router_url in args.register:
        deregister_from_router(router_url, advertised)
    server.stop()
    return 0
