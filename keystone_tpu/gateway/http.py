"""HTTP inference frontend: the network face of the gateway.

A stdlib ``http.server`` on a background daemon thread, following the
``observability/admin.py`` server pattern (nothing to install, ephemeral
``port=0`` for tests/smoke, daemon threads per request). Routes:

- ``POST /predict`` — body ``{"instances": [<example>, ...]}`` (each
  instance one example WITHOUT the batch axis; numbers nest as JSON
  arrays), optional ``"deadline_ms"``. Every instance is admitted
  individually, so concurrent clients coalesce in the micro-batchers.
  Responds ``{"predictions": [...]}``; typed errors map to status
  codes: 429 shed (``Overloaded``: queue_full/deadline), 504 expired,
  503 draining/closed, 400 malformed, 500 engine error.
- ``GET /readyz`` — 200 while the gateway admits, 503 once draining.
  READINESS, not liveness: the admin endpoint's ``/healthz`` answers
  "is the process up", this answers "should the load balancer route
  here" — a draining gateway is alive but not ready. With SLOs
  declared, an active burn/pressure state is appended to the body
  (still 200: burning means "send less", not "stop sending"). A
  convenience ``GET /healthz`` is also served for single-port
  deployments.
- ``GET /metrics`` — Prometheus exposition of the (global) registry,
  so a gateway-only deployment is scrapeable without the admin server
  (latency-histogram buckets carry ``trace_id`` exemplars).
- ``GET /slz`` / ``GET /debugz`` / ``GET /tracez`` — the SLO
  burn-rate, flight-recorder, and recent-span surfaces, mirrored from
  the admin endpoint for single-port deployments (``/tracez`` shows
  the per-window ``microbatch.coalesce`` → ``pipeline.host_prep`` /
  ``.upload`` / ``.compute`` / ``.deliver`` stage chains when the
  lanes run pipelined and tracing is on).
- ``GET /profilez?seconds=N`` — arm a ``jax.profiler`` trace around
  the next N seconds of live traffic and list the capture directory
  (Perfetto/XProf); 409 while another capture runs — mirrored from the
  admin endpoint (``observability/profilez.py``) so a gateway-only
  deployment can still grab a device trace. The server also runs the
  device-memory sampler, so ``/metrics`` here carries the
  ``keystone_device_memory_bytes`` and ``keystone_device_info``
  families without an admin port.
- ``POST /swap`` — force one lifecycle iteration
  (``Gateway.rebucket(force=True)``); returns the active bucket set.
  The smoke script's forced-swap drill.
- ``POST /drain`` — begin graceful shutdown in the background;
  ``/readyz`` flips 503 immediately, admitted requests resolve.

With ``--request-log`` (or ``GatewayServer(request_log=True)``) every
``/predict`` instance also emits one structured JSON line to stdout —
``{"ts", "status", "latency_ms", "lane", "trace_id"}`` — so a
flight-recorder trace id found at ``/debugz`` is greppable straight
from the process log.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from keystone_tpu.gateway.admission import Overloaded
from keystone_tpu.gateway.lifecycle import Gateway
from keystone_tpu.observability import device as device_obs
from keystone_tpu.observability import flight as flight_mod
from keystone_tpu.observability import profilez as profilez_mod
from keystone_tpu.observability import prometheus
from keystone_tpu.observability import slo as slo_mod
from keystone_tpu.observability.httpd import BackgroundServer, JsonHandler
from keystone_tpu.observability.registry import get_global_registry

logger = logging.getLogger(__name__)

# generous server-side ceiling for waiting on one prediction; requests
# with their own deadline wait deadline + slack instead
RESULT_TIMEOUT_S = 60.0


def _status_for(err: Overloaded) -> int:
    if err.reason == "closed":
        return 503
    if err.reason == "expired":
        return 504
    return 429


class _Handler(JsonHandler):
    def _send_error_json(self, code: int, error: str, **extra) -> None:
        self._send_json({"error": error, **extra}, code=code)

    @property
    def gateway(self) -> Gateway:
        return self.server.gateway  # type: ignore[attr-defined]

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler API)
        url = urlparse(self.path)
        path = url.path
        try:
            if path == "/readyz":
                if self.gateway.ready:
                    status = self.gateway.slo_status()
                    if status is not None and (
                        status["pressure"] > 0 or status["breaching"]
                    ):
                        # burning is visible here but still 200: the
                        # LB should keep routing, admission itself is
                        # doing the early shedding
                        self._send_text(
                            200,
                            "ok (slo burning: "
                            f"pressure={status['pressure']:.2f} "
                            f"fast={status['burn_rate'].get('fast')})\n",
                        )
                    else:
                        self._send_text(200, "ok\n")
                else:
                    self._send_text(503, "draining\n")
            elif path == "/healthz":
                self._send_text(200, "ok\n")
            elif path == "/metrics":
                registry = self.server.registry  # type: ignore[attr-defined]
                body, ctype = prometheus.negotiate_render(
                    registry.collect(), self.headers.get("Accept")
                )
                self._send(200, body.encode("utf-8"), ctype)
            elif path == "/slz":
                self._send_json(slo_mod.slz_status(), indent=1)
            elif path == "/debugz":
                q = parse_qs(url.query)
                code, doc = flight_mod.debugz_document(
                    q.get("trace_id", [None])[0],
                    q.get("format", [""])[0],
                )
                self._send_json(doc, code=code, indent=1)
            elif path == "/profilez":
                q = parse_qs(url.query)
                code, doc = profilez_mod.profilez_document(
                    q.get("seconds", [None])[0]
                )
                self._send_json(doc, code=code, indent=1)
            elif path == "/tracez":
                from keystone_tpu.observability.tracing import (
                    get_tracer,
                    tracez_document,
                )

                q = parse_qs(url.query)
                self._send_json(
                    tracez_document(
                        get_tracer(),
                        q.get("format", [""])[0],
                        q["n"][0] if "n" in q else None,
                    ),
                    indent=1,
                )
            else:
                self._send_text(
                    404,
                    "not found; try /predict /readyz /healthz /metrics "
                    "/slz /debugz /tracez /profilez\n",
                )
        except Exception as e:
            logger.exception("gateway GET error for %s", self.path)
            self._send_error_json(500, "internal", detail=str(e))

    def _log_request(
        self,
        status: int,
        latency_s: float,
        lane: Optional[int] = None,
        trace_id: Optional[str] = None,
        error: Optional[str] = None,
    ) -> None:
        """One structured JSON line per /predict instance on stdout
        (``--request-log``): trace ids surfaced at /debugz are
        greppable straight from the process log."""
        line = {
            "ts": round(time.time(), 6),
            "path": "/predict",
            "status": status,
            "latency_ms": round(latency_s * 1e3, 3),
            "lane": lane,
            "trace_id": trace_id,
        }
        if error is not None:
            line["error"] = error
        print(json.dumps(line), flush=True)

    def do_POST(self) -> None:  # noqa: N802 (stdlib handler API)
        path = urlparse(self.path).path
        self._t_post = time.perf_counter()
        try:
            if path == "/predict":
                self._predict()
            elif path == "/swap":
                swapped = self.gateway.rebucket(force=True)
                self._send_json(
                    {
                        "swapped": swapped,
                        "buckets": list(self.gateway.buckets),
                    }
                )
            elif path == "/drain":
                threading.Thread(
                    target=self.gateway.close,
                    name="keystone-gateway-drain",
                    daemon=True,
                ).start()
                self._send_json({"draining": True})
            else:
                self._send_text(404, "not found; try /predict /swap /drain\n")
        except Overloaded as e:
            code = _status_for(e)
            if path == "/predict" and self.server.request_log:  # type: ignore[attr-defined]
                self._log_request(
                    code, time.perf_counter() - self._t_post,
                    error=e.reason,
                )
            self._send_error_json(
                code, "overloaded", reason=e.reason,
                detail=str(e),
            )
        except Exception as e:
            logger.exception("gateway POST error for %s", self.path)
            if path == "/predict" and self.server.request_log:  # type: ignore[attr-defined]
                self._log_request(
                    500, time.perf_counter() - self._t_post,
                    error=str(e),
                )
            self._send_error_json(500, "internal", detail=str(e))

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0) or 0)
        return self.rfile.read(length) if length else b""

    def _predict(self) -> None:
        try:
            doc = json.loads(self._read_body() or b"{}")
            instances = doc["instances"]
            if not isinstance(instances, list) or not instances:
                raise ValueError("instances must be a non-empty list")
        except (ValueError, KeyError, TypeError) as e:
            self._send_error_json(400, "bad_request", detail=str(e))
            return
        deadline_ms = doc.get("deadline_ms")
        if deadline_ms is not None and (
            isinstance(deadline_ms, bool)
            or not isinstance(deadline_ms, (int, float))
            or deadline_ms <= 0
        ):
            self._send_error_json(
                400, "bad_request",
                detail=f"deadline_ms must be a positive number, "
                       f"got {deadline_ms!r}",
            )
            return
        dtype = self.server.input_dtype  # type: ignore[attr-defined]
        try:
            examples = [np.asarray(inst, dtype=dtype) for inst in instances]
        except (ValueError, TypeError) as e:
            self._send_error_json(400, "bad_request", detail=str(e))
            return
        # admit every instance BEFORE waiting on any: concurrent
        # instances coalesce into shared micro-batch windows
        futures = []
        try:
            for ex in examples:
                futures.append(
                    self.gateway.predict(ex, deadline_ms=deadline_ms)
                )
        except Overloaded:
            # partial admission on a shed response: cancel what was
            # already admitted so the engines don't burn overload-time
            # cycles computing results this 429 discards
            for f in futures:
                f.cancel()
            raise  # -> do_POST's typed handler
        timeout = (
            deadline_ms / 1e3 + 5.0
            if deadline_ms is not None
            else RESULT_TIMEOUT_S
        )
        try:
            preds = [np.asarray(f.result(timeout=timeout)) for f in futures]
        except Overloaded:
            # one instance shed/expired -> whole response is an error:
            # cancel the siblings so engines don't compute answers this
            # response discards (same reason as the admission path above)
            for f in futures:
                f.cancel()
            raise
        except Exception as e:
            for f in futures:
                f.cancel()
            if self.server.request_log:  # type: ignore[attr-defined]
                self._log_request(
                    500, time.perf_counter() - self._t_post,
                    error=str(e),
                )
            self._send_error_json(500, "prediction_failed", detail=str(e))
            return
        if self.server.request_log:  # type: ignore[attr-defined]
            whole_post_s = time.perf_counter() - self._t_post
            for f in futures:
                # per-request latency as the admission layer measured
                # it (rides the future) — iterating result() above
                # would charge every instance the wait on instance 0
                self._log_request(
                    200,
                    getattr(f, "latency_s", None) or whole_post_s,
                    lane=getattr(f, "lane_index", None),
                    trace_id=getattr(f, "trace_id", None),
                )
        self._send_json({"predictions": [p.tolist() for p in preds]})


class GatewayServer(BackgroundServer, device_obs.MemorySamplerHost):
    """The inference frontend over one ``Gateway``. ``start()`` binds
    and serves on a daemon thread; ``stop()`` shuts the listener down
    (the gateway itself drains via ``Gateway.close``/``/drain``)."""

    handler_cls = _Handler
    thread_name = "keystone-gateway-http"

    def __init__(
        self,
        gateway: Gateway,
        port: int = 0,
        host: str = "127.0.0.1",
        registry=None,
        input_dtype: Any = np.float32,
        request_log: bool = False,
    ):
        super().__init__(port=port, host=host)
        self.gateway = gateway
        self.registry = (
            registry if registry is not None else get_global_registry()
        )
        self.input_dtype = np.dtype(input_dtype)
        self.request_log = bool(request_log)
        # single-port deployments scrape THIS port: carry the device
        # identity gauge and the memory sampler here too, same as the
        # admin endpoint (refcounted — one thread per registry even
        # when both servers run in one process)
        device_obs.register_device_metrics(self.registry)

    def _configure(self, httpd) -> None:
        httpd.gateway = self.gateway
        httpd.registry = self.registry
        httpd.input_dtype = self.input_dtype
        httpd.request_log = self.request_log

    def start(self) -> "GatewayServer":
        super().start()
        self._start_memory_sampler()
        return self

    def stop(self) -> None:
        self._stop_memory_sampler()
        super().stop()


def main(argv=None) -> int:
    """``python -m keystone_tpu serve-gateway [--gateway-port N] ...`` —
    stand up the full request plane over the serve-bench pipeline (the
    demo/smoke entry; real deployments construct ``Gateway`` over their
    own fitted pipeline)."""
    import argparse
    import time

    import jax.numpy as jnp

    from keystone_tpu.parallel.runtime import setup_compilation_cache
    from keystone_tpu.serving.bench import build_pipeline

    ap = argparse.ArgumentParser(
        prog="keystone_tpu serve-gateway", description=__doc__
    )
    ap.add_argument("--gateway-port", "--port", dest="port", type=int,
                    default=0, help="bind port (0 = ephemeral)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--buckets", default="8,32,128")
    ap.add_argument("--lanes", type=int, default=2)
    ap.add_argument("--max-pending", type=int, default=1024)
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--pipeline-depth", type=int, default=2,
                    help="stage-queue depth of each lane's staged "
                    "pipeline (host-prep/upload/compute/deliver "
                    "overlap across windows); 0 = serial dispatch")
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="default per-request deadline")
    ap.add_argument("--rebucket-interval", type=float, default=None,
                    help="seconds between autoscale/rebucket sweeps")
    ap.add_argument("--slo-latency-ms", type=float, default=None,
                    help="declare + enforce a latency SLO at this "
                    "threshold: burn-rate gauges + /slz, admission "
                    "tightening under sustained fast-window burn, and "
                    "tail-sampled forensics at /debugz (enables span "
                    "tracing)")
    ap.add_argument("--slo-target", type=float, default=0.99,
                    help="fraction of requests that must make the "
                    "latency threshold")
    ap.add_argument("--flight-capacity", type=int, default=64,
                    help="forensic ring size (requests)")
    ap.add_argument("--request-log", action="store_true",
                    help="one structured JSON line per /predict "
                    "instance on stdout (status, latency_ms, lane, "
                    "trace_id)")
    ap.add_argument("--d", type=int, default=256)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--depth", type=int, default=4)
    ap.add_argument("--no-cache", action="store_true")
    args = ap.parse_args(argv)
    if not args.no_cache:
        setup_compilation_cache()

    if args.slo_latency_ms is not None:
        # the forensic chain (exemplars, flight records, burn gauges)
        # keys off trace ids, so SLO mode implies tracing
        from keystone_tpu.observability import enable_tracing

        enable_tracing()

    fitted = build_pipeline(d=args.d, hidden=args.hidden, depth=args.depth)
    gateway = Gateway(
        fitted,
        buckets=tuple(int(b) for b in args.buckets.split(",")),
        n_lanes=args.lanes,
        max_delay_ms=args.max_delay_ms,
        pipeline_depth=args.pipeline_depth,
        warmup_example=jnp.zeros((args.d,), jnp.float32),
        max_pending=args.max_pending,
        default_deadline_ms=args.deadline_ms,
        maintenance_interval_s=args.rebucket_interval,
        slo_latency_s=(
            args.slo_latency_ms / 1e3
            if args.slo_latency_ms is not None else None
        ),
        slo_target=args.slo_target,
        flight_capacity=args.flight_capacity,
    )
    gateway.install_signal_handlers()
    server = GatewayServer(
        gateway, port=args.port, host=args.host,
        request_log=args.request_log,
    ).start()
    print(
        f"gateway: {server.url()} (POST /predict, GET /readyz, "
        "GET /metrics, GET /slz, GET /debugz, GET /profilez, "
        "POST /swap, POST /drain)",
        flush=True,
    )
    try:
        while gateway.ready:
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    gateway.close()
    server.stop()
    return 0
