"""HTTP inference frontend: the network face of the gateway.

A stdlib ``http.server`` on a background daemon thread, following the
``observability/admin.py`` server pattern (nothing to install, ephemeral
``port=0`` for tests/smoke, daemon threads per request). Routes:

- ``POST /predict`` — body ``{"instances": [<example>, ...]}`` (each
  instance one example WITHOUT the batch axis; numbers nest as JSON
  arrays), optional ``"deadline_ms"``. Every instance is admitted
  individually, so concurrent clients coalesce in the micro-batchers.
  Responds ``{"predictions": [...]}``; typed errors map to status
  codes: 429 shed (``Overloaded``: queue_full/deadline), 504 expired,
  503 draining/closed, 400 malformed, 500 engine error.
- ``GET /readyz`` — 200 while the gateway admits, 503 once draining.
  READINESS, not liveness: the admin endpoint's ``/healthz`` answers
  "is the process up", this answers "should the load balancer route
  here" — a draining gateway is alive but not ready. A convenience
  ``GET /healthz`` is also served for single-port deployments.
- ``GET /metrics`` — Prometheus exposition of the (global) registry,
  so a gateway-only deployment is scrapeable without the admin server.
- ``POST /swap`` — force one lifecycle iteration
  (``Gateway.rebucket(force=True)``); returns the active bucket set.
  The smoke script's forced-swap drill.
- ``POST /drain`` — begin graceful shutdown in the background;
  ``/readyz`` flips 503 immediately, admitted requests resolve.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Any
from urllib.parse import urlparse

import numpy as np

from keystone_tpu.gateway.admission import Overloaded
from keystone_tpu.gateway.lifecycle import Gateway
from keystone_tpu.observability import prometheus
from keystone_tpu.observability.httpd import BackgroundServer, JsonHandler
from keystone_tpu.observability.registry import get_global_registry

logger = logging.getLogger(__name__)

# generous server-side ceiling for waiting on one prediction; requests
# with their own deadline wait deadline + slack instead
RESULT_TIMEOUT_S = 60.0


def _status_for(err: Overloaded) -> int:
    if err.reason == "closed":
        return 503
    if err.reason == "expired":
        return 504
    return 429


class _Handler(JsonHandler):
    def _send_error_json(self, code: int, error: str, **extra) -> None:
        self._send_json({"error": error, **extra}, code=code)

    @property
    def gateway(self) -> Gateway:
        return self.server.gateway  # type: ignore[attr-defined]

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler API)
        path = urlparse(self.path).path
        try:
            if path == "/readyz":
                if self.gateway.ready:
                    self._send_text(200, "ok\n")
                else:
                    self._send_text(503, "draining\n")
            elif path == "/healthz":
                self._send_text(200, "ok\n")
            elif path == "/metrics":
                registry = self.server.registry  # type: ignore[attr-defined]
                body = prometheus.render(registry.collect())
                self._send(
                    200, body.encode("utf-8"), prometheus.CONTENT_TYPE
                )
            else:
                self._send_text(
                    404,
                    "not found; try /predict /readyz /healthz /metrics\n",
                )
        except Exception as e:
            logger.exception("gateway GET error for %s", self.path)
            self._send_error_json(500, "internal", detail=str(e))

    def do_POST(self) -> None:  # noqa: N802 (stdlib handler API)
        path = urlparse(self.path).path
        try:
            if path == "/predict":
                self._predict()
            elif path == "/swap":
                swapped = self.gateway.rebucket(force=True)
                self._send_json(
                    {
                        "swapped": swapped,
                        "buckets": list(self.gateway.buckets),
                    }
                )
            elif path == "/drain":
                threading.Thread(
                    target=self.gateway.close,
                    name="keystone-gateway-drain",
                    daemon=True,
                ).start()
                self._send_json({"draining": True})
            else:
                self._send_text(404, "not found; try /predict /swap /drain\n")
        except Overloaded as e:
            self._send_error_json(
                _status_for(e), "overloaded", reason=e.reason,
                detail=str(e),
            )
        except Exception as e:
            logger.exception("gateway POST error for %s", self.path)
            self._send_error_json(500, "internal", detail=str(e))

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0) or 0)
        return self.rfile.read(length) if length else b""

    def _predict(self) -> None:
        try:
            doc = json.loads(self._read_body() or b"{}")
            instances = doc["instances"]
            if not isinstance(instances, list) or not instances:
                raise ValueError("instances must be a non-empty list")
        except (ValueError, KeyError, TypeError) as e:
            self._send_error_json(400, "bad_request", detail=str(e))
            return
        deadline_ms = doc.get("deadline_ms")
        if deadline_ms is not None and (
            isinstance(deadline_ms, bool)
            or not isinstance(deadline_ms, (int, float))
            or deadline_ms <= 0
        ):
            self._send_error_json(
                400, "bad_request",
                detail=f"deadline_ms must be a positive number, "
                       f"got {deadline_ms!r}",
            )
            return
        dtype = self.server.input_dtype  # type: ignore[attr-defined]
        try:
            examples = [np.asarray(inst, dtype=dtype) for inst in instances]
        except (ValueError, TypeError) as e:
            self._send_error_json(400, "bad_request", detail=str(e))
            return
        # admit every instance BEFORE waiting on any: concurrent
        # instances coalesce into shared micro-batch windows
        futures = []
        try:
            for ex in examples:
                futures.append(
                    self.gateway.predict(ex, deadline_ms=deadline_ms)
                )
        except Overloaded:
            # partial admission on a shed response: cancel what was
            # already admitted so the engines don't burn overload-time
            # cycles computing results this 429 discards
            for f in futures:
                f.cancel()
            raise  # -> do_POST's typed handler
        timeout = (
            deadline_ms / 1e3 + 5.0
            if deadline_ms is not None
            else RESULT_TIMEOUT_S
        )
        try:
            preds = [np.asarray(f.result(timeout=timeout)) for f in futures]
        except Overloaded:
            # one instance shed/expired -> whole response is an error:
            # cancel the siblings so engines don't compute answers this
            # response discards (same reason as the admission path above)
            for f in futures:
                f.cancel()
            raise
        except Exception as e:
            for f in futures:
                f.cancel()
            self._send_error_json(500, "prediction_failed", detail=str(e))
            return
        self._send_json({"predictions": [p.tolist() for p in preds]})


class GatewayServer(BackgroundServer):
    """The inference frontend over one ``Gateway``. ``start()`` binds
    and serves on a daemon thread; ``stop()`` shuts the listener down
    (the gateway itself drains via ``Gateway.close``/``/drain``)."""

    handler_cls = _Handler
    thread_name = "keystone-gateway-http"

    def __init__(
        self,
        gateway: Gateway,
        port: int = 0,
        host: str = "127.0.0.1",
        registry=None,
        input_dtype: Any = np.float32,
    ):
        super().__init__(port=port, host=host)
        self.gateway = gateway
        self.registry = (
            registry if registry is not None else get_global_registry()
        )
        self.input_dtype = np.dtype(input_dtype)

    def _configure(self, httpd) -> None:
        httpd.gateway = self.gateway
        httpd.registry = self.registry
        httpd.input_dtype = self.input_dtype


def main(argv=None) -> int:
    """``python -m keystone_tpu serve-gateway [--gateway-port N] ...`` —
    stand up the full request plane over the serve-bench pipeline (the
    demo/smoke entry; real deployments construct ``Gateway`` over their
    own fitted pipeline)."""
    import argparse
    import time

    import jax.numpy as jnp

    from keystone_tpu.parallel.runtime import setup_compilation_cache
    from keystone_tpu.serving.bench import build_pipeline

    ap = argparse.ArgumentParser(
        prog="keystone_tpu serve-gateway", description=__doc__
    )
    ap.add_argument("--gateway-port", "--port", dest="port", type=int,
                    default=0, help="bind port (0 = ephemeral)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--buckets", default="8,32,128")
    ap.add_argument("--lanes", type=int, default=2)
    ap.add_argument("--max-pending", type=int, default=1024)
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="default per-request deadline")
    ap.add_argument("--rebucket-interval", type=float, default=None,
                    help="seconds between autoscale/rebucket sweeps")
    ap.add_argument("--d", type=int, default=256)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--depth", type=int, default=4)
    ap.add_argument("--no-cache", action="store_true")
    args = ap.parse_args(argv)
    if not args.no_cache:
        setup_compilation_cache()

    fitted = build_pipeline(d=args.d, hidden=args.hidden, depth=args.depth)
    gateway = Gateway(
        fitted,
        buckets=tuple(int(b) for b in args.buckets.split(",")),
        n_lanes=args.lanes,
        max_delay_ms=args.max_delay_ms,
        warmup_example=jnp.zeros((args.d,), jnp.float32),
        max_pending=args.max_pending,
        default_deadline_ms=args.deadline_ms,
        maintenance_interval_s=args.rebucket_interval,
    )
    gateway.install_signal_handlers()
    server = GatewayServer(gateway, port=args.port, host=args.host).start()
    print(
        f"gateway: {server.url()} (POST /predict, GET /readyz, "
        "GET /metrics, POST /swap, POST /drain)",
        flush=True,
    )
    try:
        while gateway.ready:
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    gateway.close()
    server.stop()
    return 0
