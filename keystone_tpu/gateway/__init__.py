"""Request gateway: the serving plane's front door.

PR 1 built the per-engine fast path (``CompiledPipeline`` +
``MicroBatcher``) and PR 2 made it observable; this package owns engine
*lifecycle* and the request plane in front of it:

- ``AdmissionController`` (admission.py): bounded queue, per-request
  deadline propagation, and load shedding with a typed ``Overloaded``
  error — beyond-capacity traffic is rejected immediately instead of
  collapsing latency for everyone.
- ``EnginePool`` (pool.py): N shared-nothing replica lanes (one
  micro-batcher + engine pair each), least-loaded routing, per-lane
  health with half-open recovery, and retry-to-another-lane on lane
  failure.
- ``Gateway`` (lifecycle.py): build + warm lanes, the live autoscale
  loop (observed size histogram -> ``suggest_buckets`` -> warm
  replacement -> atomic swap -> drain), graceful shutdown on
  ``close()``/SIGTERM.
- ``GatewayServer`` (http.py): stdlib HTTP frontend — ``POST
  /predict``, ``GET /readyz`` (readiness, distinct from the admin
  plane's ``/healthz`` liveness; carries the ``X-Keystone-Load``
  header the fleet router's probes read), ``GET /metrics``,
  ``POST /swap``, ``POST /drain``, and ``--register`` to self-join a
  ``keystone_tpu/fleet`` router's replica set.

Everything publishes through the PR 2 observability plane:
``keystone_gateway_shed_total``, ``keystone_gateway_retries_total``,
``keystone_gateway_engine_swaps_total``, native-histogram queue-wait
and request-latency series, and ``gateway.admit`` spans parenting the
``microbatch.coalesce`` -> ``serving.dispatch`` chain.
"""

from keystone_tpu.gateway.admission import AdmissionController, Overloaded
from keystone_tpu.gateway.http import GatewayServer
from keystone_tpu.gateway.lifecycle import Gateway
from keystone_tpu.gateway.metrics import GatewayMetrics
from keystone_tpu.gateway.pool import EnginePool, Lane

__all__ = [
    "AdmissionController",
    "EnginePool",
    "Gateway",
    "GatewayMetrics",
    "GatewayServer",
    "Lane",
    "Overloaded",
]
