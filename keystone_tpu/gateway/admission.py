"""Admission control: the gateway's front door.

Every request passes one policy gate BEFORE it can touch an engine:

- **bounded queue** — at most ``max_pending`` admitted-but-unrouted
  requests; the router hands them to pool lanes only as lane capacity
  frees, so backpressure is explicit instead of an unbounded pile-up
  inside the micro-batchers;
- **load shedding** — a request is rejected IMMEDIATELY with a typed
  ``Overloaded`` error when the queue is full or when the estimated
  wait (pending work over the measured completion rate) already exceeds
  the request's deadline. Shedding the request that cannot make its
  deadline anyway keeps latency flat for the requests that can — the
  alternative is every request's latency collapsing together;
- **deadline propagation** — the deadline travels with the request: if
  it expires while queued (load arrived after admission), the router
  sheds it at hand-off time instead of wasting engine cycles on an
  answer nobody is waiting for;
- **SLO pressure** — the gateway's burn-rate watchdog can *tighten*
  admission (``set_pressure``): while the fast-window burn says the
  latency budget is being torched, the effective queue bound shrinks
  and arrivals beyond it shed with reason ``slo_pressure`` — shedding
  *early*, before the queue saturates, is what arrests the burn.

Instrumented via ``GatewayMetrics``: ``keystone_gateway_shed_total``
by reason, queue-depth/inflight gauges, and the queue-wait native
histogram. Each admission opens a ``gateway.admit`` span whose id and
trace id ride with the request so the micro-batcher's
``microbatch.coalesce`` span — on another thread — parents under it,
completing the admit → coalesce → dispatch chain in ``/tracez``; the
trace id also lands on the latency histogram as an OpenMetrics
exemplar and keys the flight recorder's tail-sampled forensics.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time
from concurrent.futures import Future
from typing import Any, Deque, Optional

from keystone_tpu.gateway.metrics import GatewayMetrics
from keystone_tpu.observability.flight import FlightRecorder
from keystone_tpu.observability.tracing import get_tracer

logger = logging.getLogger(__name__)

# completion-rate estimator: window and the minimum evidence before the
# estimated-wait shed rule activates (a cold gateway never deadline-sheds)
RATE_WINDOW_S = 10.0
MIN_RATE_SAMPLES = 8


class Overloaded(RuntimeError):
    """Typed shed/reject error. ``reason`` is one of:

    - ``queue_full``   — the bounded admission queue is at capacity;
    - ``slo_pressure`` — the SLO burn watchdog tightened admission and
      the queue is past the TIGHTENED bound (early shed);
    - ``deadline``     — estimated wait exceeds the request's deadline;
    - ``expired``      — the deadline passed while the request queued;
    - ``closed``       — the gateway is draining and admits nothing.

    HTTP maps these to 429 (shed), 504 (expired), 503 (closed)."""

    def __init__(
        self,
        reason: str,
        queue_depth: Optional[int] = None,
        est_wait_s: Optional[float] = None,
        deadline_s: Optional[float] = None,
    ):
        self.reason = reason
        self.queue_depth = queue_depth
        self.est_wait_s = est_wait_s
        self.deadline_s = deadline_s
        parts = [f"overloaded ({reason})"]
        if queue_depth is not None:
            parts.append(f"queue_depth={queue_depth}")
        if est_wait_s is not None:
            parts.append(f"est_wait={est_wait_s * 1e3:.1f}ms")
        if deadline_s is not None:
            parts.append(f"deadline={deadline_s * 1e3:.1f}ms")
        super().__init__(" ".join(parts))


def _fail(fut: Future, err: BaseException) -> None:
    """Resolve ``fut`` with ``err``, tolerating a caller cancelling in
    the same instant (InvalidStateError) — the caller stopped waiting,
    nobody needs the error."""
    try:
        fut.set_exception(err)
    except Exception:
        pass


@dataclasses.dataclass
class _Request:
    example: Any
    future: Future
    t_admit: float
    deadline_t: Optional[float]  # absolute perf_counter deadline
    parent_span_id: Optional[int]
    trace_id: Optional[str] = None


class AdmissionController:
    """Bounded-queue admission in front of an ``EnginePool`` (anything
    with ``submit``/``free_capacity``/``total_load``/
    ``add_free_listener`` — tests stub it)."""

    def __init__(
        self,
        pool,
        max_pending: int = 1024,
        default_deadline_ms: Optional[float] = None,
        metrics: Optional[GatewayMetrics] = None,
        name: str = "gateway",
        flight: Optional[FlightRecorder] = None,
        forensic_threshold_s: Optional[float] = None,
    ):
        if max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        self.pool = pool
        self.name = name
        self.max_pending = max_pending
        self.default_deadline_ms = default_deadline_ms
        # SLO-watchdog admission tightening: pressure in [0, 1] shrinks
        # the effective queue bound (0 = none; see set_pressure)
        self._pressure = 0.0
        # tail-sampling forensics: when wired, every finished request's
        # verdict goes through the flight recorder's capture decision
        self.flight = flight
        self.forensic_threshold_s = forensic_threshold_s
        self.metrics = metrics if metrics is not None else GatewayMetrics(
            gateway=name
        )
        self._queue: Deque[_Request] = collections.deque()  # guarded-by: _cond
        self._cond = threading.Condition()
        self._accepting = True  # guarded-by: _cond
        self._completions: Deque[float] = (
            collections.deque(maxlen=2048)
        )  # guarded-by: _comp_lock
        self._comp_lock = threading.Lock()
        pool.add_free_listener(self._wake)
        self._router = threading.Thread(
            target=self._route_loop, name=f"keystone-{name}-router",
            daemon=True,
        )
        self._router.start()
        self.metrics.set_ready(True)

    # -- client side -------------------------------------------------------

    @property
    def accepting(self) -> bool:
        return self._accepting

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    @property
    def pressure(self) -> float:
        return self._pressure

    def set_pressure(self, pressure: float) -> None:
        """SLO-watchdog hook: ``pressure`` in [0, 1] shrinks the
        effective queue bound to ``max_pending * (1 - pressure)`` so
        the gateway sheds *before* the queue saturates while the error
        budget is burning. 0 restores normal admission."""
        self._pressure = min(1.0, max(0.0, float(pressure)))

    @property
    def effective_max_pending(self) -> int:
        if self._pressure <= 0.0:
            return self.max_pending
        return max(1, int(self.max_pending * (1.0 - self._pressure)))

    def estimated_wait_s(self) -> Optional[float]:
        """Pending work (queued + in-lane) over the measured completion
        rate; ``None`` until enough completions exist to estimate."""
        now = time.perf_counter()
        with self._comp_lock:
            while (
                self._completions
                and self._completions[0] < now - RATE_WINDOW_S
            ):
                self._completions.popleft()
            n = len(self._completions)
            if n < MIN_RATE_SAMPLES:
                return None
            span = now - self._completions[0]
        rate = n / max(span, 1e-3)
        pending = len(self._queue) + self.pool.total_load()
        return pending / rate

    def submit(
        self,
        example: Any,
        deadline_ms: Optional[float] = None,
        trace_id: Optional[str] = None,
    ) -> Future:
        """Admit one example or raise ``Overloaded``. The returned
        future resolves with the example's pipeline output (or the
        terminal error after any lane retry). ``trace_id`` adopts a
        remote trace identity (the HTTP frontend's parsed W3C
        ``traceparent``) so the whole admit → coalesce → dispatch
        chain, the latency exemplar, and any flight-recorder capture
        ride the CALLER's trace — one id across the fleet hop."""
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        deadline_s = deadline_ms / 1e3 if deadline_ms is not None else None
        with get_tracer().span(
            "gateway.admit", trace_id=trace_id, gateway=self.name
        ) as span:
            with self._cond:
                if not self._accepting:
                    self.metrics.record_shed("closed")
                    raise Overloaded("closed")
                depth = len(self._queue)
                if depth >= self.max_pending:
                    self.metrics.record_shed("queue_full")
                    raise Overloaded("queue_full", queue_depth=depth)
                if depth >= self.effective_max_pending:
                    # the SLO watchdog tightened admission: the queue
                    # is not FULL, but filling it further while the
                    # latency budget burns only deepens the breach
                    self.metrics.record_shed("slo_pressure")
                    raise Overloaded("slo_pressure", queue_depth=depth)
                if deadline_s is not None:
                    est = self.estimated_wait_s()
                    if est is not None and est > deadline_s:
                        self.metrics.record_shed("deadline")
                        raise Overloaded(
                            "deadline",
                            queue_depth=depth,
                            est_wait_s=est,
                            deadline_s=deadline_s,
                        )
                t = time.perf_counter()
                req = _Request(
                    example=example,
                    future=Future(),
                    t_admit=t,
                    deadline_t=(
                        t + deadline_s if deadline_s is not None else None
                    ),
                    parent_span_id=span.span_id,
                    # the adopted id survives even with tracing off
                    # (null span): the request log / exemplars / the
                    # X-Keystone-Trace echo still correlate with the
                    # router's trace
                    trace_id=getattr(span, "trace_id", None) or trace_id,
                )
                # ride the identity on the future so the HTTP frontend
                # can log a greppable trace_id per request
                req.future.trace_id = req.trace_id
                self._queue.append(req)
                self.metrics.set_queue_depth(len(self._queue))
                self._cond.notify()
        return req.future

    # -- router ------------------------------------------------------------

    def _wake(self) -> None:
        with self._cond:
            self._cond.notify()

    def _route_loop(self) -> None:
        while True:
            with self._cond:
                while (
                    self._accepting
                    and not (self._queue and self.pool.free_capacity() > 0)
                ):
                    # the timeout backstops missed capacity signals
                    # (e.g. a lane flipping healthy on its cool-down)
                    self._cond.wait(0.05)
                if not self._accepting and not self._queue:
                    return  # drained and draining: router done
                if not self._queue:
                    continue
                req = self._queue.popleft()
                self.metrics.set_queue_depth(len(self._queue))
            if req.future.cancelled():
                # caller gave up while queued (e.g. the HTTP frontend
                # shedding a partially-admitted /predict): spend nothing
                continue
            now = time.perf_counter()
            if req.deadline_t is not None and now > req.deadline_t:
                # the deadline died in the queue: shed at hand-off,
                # don't spend engine time on it
                self.metrics.record_shed("expired")
                _fail(
                    req.future,
                    Overloaded(
                        "expired",
                        deadline_s=req.deadline_t - req.t_admit,
                    ),
                )
                continue
            self.metrics.record_queue_wait(now - req.t_admit)
            try:
                lane_fut = self.pool.submit(
                    req.example, parent_span_id=req.parent_span_id
                )
            except Exception as e:
                _fail(req.future, e)
                continue
            self.metrics.set_inflight(self.pool.total_load())
            lane_fut.add_done_callback(
                lambda f, req=req: self._finish(req, f)
            )

    def _finish(self, req: _Request, lane_fut: Future) -> None:
        now = time.perf_counter()
        with self._comp_lock:
            self._completions.append(now)
        self.metrics.set_inflight(self.pool.total_load())
        latency_s = now - req.t_admit
        # the trace id rides onto the histogram as an exemplar: the
        # bucket this latency lands in links straight back to the
        # request's span tree (flight recorder / /debugz)
        self.metrics.record_latency(latency_s, trace_id=req.trace_id)
        lane_index = getattr(lane_fut, "lane_index", None)
        req.future.lane_index = lane_index
        # the measured per-request latency rides with lane/trace id so
        # the HTTP request log reports THIS request's number, not the
        # wait on whichever sibling future was iterated first
        req.future.latency_s = latency_s
        err = lane_fut.exception()
        if err is None:
            self.metrics.record_outcome("ok")
            if not req.future.cancelled():
                req.future.set_result(lane_fut.result())
        else:
            self.metrics.record_outcome("error")
            _fail(req.future, err)
        if self.flight is not None:
            # tail-sampling verdict: only over-threshold or errored
            # requests pin their span tree into the forensic ring
            self.flight.maybe_capture(
                req.trace_id,
                duration_s=latency_s,
                error=err,
                threshold_s=self.forensic_threshold_s,
                gateway=self.name,
                lane=lane_index,
            )

    # -- lifecycle ---------------------------------------------------------

    def close(self, timeout: Optional[float] = 10.0) -> None:
        """Stop admitting (new submits raise ``Overloaded('closed')``),
        let the router drain what was already admitted, then return.
        The pool keeps serving the drained requests; closing it is the
        gateway's job after this returns."""
        with self._cond:
            if not self._accepting:
                return
            self._accepting = False
            self.metrics.set_ready(False)
            self._cond.notify_all()
        self._router.join(timeout)
        if self._router.is_alive():
            logger.warning(
                "admission router still draining after %.1fs", timeout
            )

    def __enter__(self) -> "AdmissionController":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
