"""ctypes bindings for the native IO runtime (native/io.cc).

The reference reaches native code via JNI (utils/external/VLFeat.scala,
EncEval.scala); here the native layer serves the host input pipeline —
multi-threaded CSV parsing and CIFAR record decoding — since the compute
kernels are XLA programs. Falls back to numpy implementations when the
shared library hasn't been built (``make -C native``); the first import
attempts the build automatically.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libkeystone_io.so")
_JPEG_LIB_PATH = os.path.join(_NATIVE_DIR, "libkeystone_jpeg.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False
_jpeg_lib: Optional[ctypes.CDLL] = None
_jpeg_tried = False
# first use commonly happens from inside the streaming loader's decode
# THREAD pool — without the lock, threads arriving while another is
# mid-load see tried=True/lib=None and silently take the slow fallback
# for the whole stream. RLock: the jpeg loader calls _load() while
# holding it (one shared build attempt covers both libraries).
_load_lock = threading.RLock()


def _load() -> Optional[ctypes.CDLL]:
    # the unlocked fast path must only trust _tried AFTER a load attempt
    # fully completed — _load_locked flips it as its last action, never
    # before, or waiting threads would see tried=True/lib=None mid-load
    # and silently take the slow fallback for the whole stream
    if _lib is not None or _tried:
        return _lib
    with _load_lock:
        if _lib is not None or _tried:
            return _lib
        try:
            return _load_locked()
        finally:
            globals()["_tried"] = True


def _is_stale() -> bool:
    return os.path.exists(_LIB_PATH) and any(
        os.path.getmtime(os.path.join(_NATIVE_DIR, f))
        > os.path.getmtime(_LIB_PATH)
        for f in os.listdir(_NATIVE_DIR)
        if f.endswith(".cc") or f == "Makefile"
    )


def _build_once() -> None:
    """Run make under an exclusive file lock: spawn-based decode WORKERS
    all reach first-load together, and concurrent linkers writing the
    same .so would hand some process a partially-written library (it
    would then silently use the slow fallback for its whole lifetime).
    The in-process _load_lock cannot serialize across processes."""
    import fcntl

    with open(os.path.join(_NATIVE_DIR, ".build.lock"), "w") as lf:
        fcntl.flock(lf, fcntl.LOCK_EX)
        # another process may have built while we waited on the lock
        if os.path.exists(_LIB_PATH) and not _is_stale():
            return
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )


def _load_locked() -> Optional[ctypes.CDLL]:
    global _lib
    if (not os.path.exists(_LIB_PATH) or _is_stale()) and os.path.exists(
        os.path.join(_NATIVE_DIR, "Makefile")
    ):
        try:
            _build_once()
        except Exception:
            if not os.path.exists(_LIB_PATH):
                return None
            # rebuild failed but a previously built library exists: load
            # it — missing newer symbols are guarded per-function
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.csv_dims.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.csv_dims.restype = ctypes.c_int
    lib.csv_read_f32.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int,
    ]
    lib.csv_read_f32.restype = ctypes.c_int
    lib.cifar_read.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.cifar_read.restype = ctypes.c_int64
    if not hasattr(lib, "text_ngram_hash_tf"):
        _lib = lib  # stale build without text.cc: IO still usable
        return _lib
    lib.text_ngram_hash_tf.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int64,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64,
        ctypes.c_int,
    ]
    lib.text_ngram_hash_tf.restype = ctypes.c_int64
    _lib = lib
    return _lib


def _load_jpeg() -> Optional[ctypes.CDLL]:
    """The JPEG decoder lives in its own shared library (it links the
    system libjpeg; native/Makefile builds it best-effort so the IO lib
    survives environments without libjpeg)."""
    global _jpeg_lib
    if _jpeg_lib is not None or _jpeg_tried:
        return _jpeg_lib
    with _load_lock:
        if _jpeg_lib is not None or _jpeg_tried:
            return _jpeg_lib
        try:
            return _load_jpeg_locked()
        finally:
            globals()["_jpeg_tried"] = True


def _load_jpeg_locked() -> Optional[ctypes.CDLL]:
    global _jpeg_lib
    _load()  # one shared build attempt covers both libraries
    if not os.path.exists(_JPEG_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_JPEG_LIB_PATH)
    except OSError:
        return None
    lib.jpeg_decode_f32.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_float),
    ]
    lib.jpeg_decode_f32.restype = ctypes.c_int
    lib.jpeg_decode_batch_f32.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int,
    ]
    lib.jpeg_decode_batch_f32.restype = ctypes.c_int64
    _jpeg_lib = lib
    return _jpeg_lib


def native_available() -> bool:
    return _load() is not None


def jpeg_native_available() -> bool:
    return _load_jpeg() is not None


def jpeg_decode_f32(data: bytes, target: int) -> Optional[np.ndarray]:
    """Decode one JPEG to a (target, target, 3) float32 RGB array via the
    native fast path (native/jpeg.cc: DCT-scaled draft decode + triangle
    resize, GIL released for the whole call). Returns None when the
    library is unavailable or this image needs the PIL fallback (corrupt
    stream, CMYK)."""
    lib = _load_jpeg()
    if lib is None:
        return None
    out = np.empty((target, target, 3), np.float32)
    rc = lib.jpeg_decode_f32(
        data, len(data), target,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    return out if rc == 0 else None


def jpeg_decode_batch_f32(
    blobs, target: int, num_threads: int = 0
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Decode a list of JPEG byte strings in one native call with an
    internal thread pool. Returns ``(images (n, target, target, 3)
    float32, ok (n,) bool)``; failed slots have undefined pixels and
    ok=False. Returns None when the library is unavailable."""
    lib = _load_jpeg()
    if lib is None:
        return None
    n = len(blobs)
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum([len(b) for b in blobs], out=offsets[1:])
    concat = b"".join(blobs)
    out = np.empty((n, target, target, 3), np.float32)
    ok = np.zeros(n, np.uint8)
    lib.jpeg_decode_batch_f32(
        concat,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        target,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ok.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        num_threads,
    )
    return out, ok.astype(bool)


def read_csv_f32(
    path: str, delimiter: str = ",", num_threads: int = 0
) -> np.ndarray:
    """Numeric CSV -> (rows, cols) float32. Native multi-threaded parser
    when available, np.loadtxt otherwise."""
    lib = _load()
    if lib is None or delimiter not in (",", " ", "\t"):
        return np.loadtxt(
            path, delimiter=delimiter, dtype=np.float32, ndmin=2
        )
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    if lib.csv_dims(path.encode(), ctypes.byref(rows), ctypes.byref(cols)):
        raise OSError(f"cannot read {path}")
    out = np.empty((rows.value, cols.value), np.float32)
    rc = lib.csv_read_f32(
        path.encode(),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        rows.value,
        cols.value,
        num_threads,
    )
    if rc != 0:
        # ragged or malformed — let numpy produce the proper error
        return np.loadtxt(
            path, delimiter=delimiter, dtype=np.float32, ndmin=2
        )
    return out


def text_ngram_hash_tf(
    docs,
    min_order: int,
    max_order: int,
    num_features: int,
    binarize: bool = False,
    num_threads: int = 0,
):
    """Fused trim/lowercase/tokenize/rolling-ngram-hash TF over a list of
    ASCII document strings (native/text.cc). Returns ``(row_ptr int64
    (n+1,), cols int32 (nnz,), vals float32 (nnz,))`` with per-document
    columns ascending — hash-identical to composing Trim -> LowerCase ->
    Tokenizer -> NGramsHashingTF. Returns None (caller falls back to the
    Python nodes) when the library is unavailable or any doc is
    non-ASCII (C++ tokenization is byte-level)."""
    if num_features <= 0:  # C-side modulo-by-zero would SIGFPE
        raise ValueError(f"num_features must be positive: {num_features}")
    lib = _load()
    if lib is None or not hasattr(lib, "text_ngram_hash_tf"):
        return None
    try:
        blobs = [d.encode("ascii") for d in docs]
    except UnicodeEncodeError:
        return None
    n = len(blobs)
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum([len(b) for b in blobs], out=offsets[1:])
    concat = b"".join(blobs)
    row_ptr = np.zeros(n + 1, np.int64)
    cap = max(2 * len(concat) + 16, 1024)
    for _ in range(2):
        cols = np.empty(cap, np.int32)
        vals = np.empty(cap, np.float32)
        nnz = lib.text_ngram_hash_tf(
            concat,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, min_order, max_order, num_features, int(binarize),
            row_ptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            cap,
            num_threads or (os.cpu_count() or 1),
        )
        if nnz >= 0:
            return row_ptr, cols[:nnz], vals[:nnz]
        cap = int(row_ptr[n])  # exact requirement, filled before -1
    return None


def read_cifar(
    path: str, channels: int = 3, dim: int = 32
) -> Tuple[np.ndarray, np.ndarray]:
    """CIFAR binary -> (labels int32 (n,), images float32 (n, dim, dim, c))."""
    lib = _load()
    rec_len = 1 + channels * dim * dim
    size = os.path.getsize(path)
    n = size // rec_len
    if lib is None:
        raw = np.fromfile(path, dtype=np.uint8)[: n * rec_len].reshape(
            n, rec_len
        )
        labels = raw[:, 0].astype(np.int32)
        images = (
            raw[:, 1:]
            .reshape(n, channels, dim, dim)
            .transpose(0, 2, 3, 1)
            .astype(np.float32)
        )
        return labels, images
    labels = np.empty(n, np.int32)
    images = np.empty((n, dim, dim, channels), np.float32)
    got = lib.cifar_read(
        path.encode(),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n,
        channels,
        dim,
    )
    if got < 0:
        raise OSError(f"cannot read {path}")
    return labels[:got], images[:got]
