"""Adaptive micro-batching: coalesce single-example requests into
bucketed dispatches.

The serving analogue of the BCD solvers' async-stream discipline
(ops/learning/block_ls.py double-buffers slabs so the chip never idles):
here the chip never runs a one-row program per request. ``submit()``
enqueues an example and returns a ``Future``; a dispatcher thread
coalesces everything that arrives within a max-latency deadline (or
until the largest bucket fills, whichever first) into ONE padded
bucket dispatch through a ``CompiledPipeline``, then resolves each
request's future with its own row of the result.

Latency/throughput contract: a lone request waits at most ``max_delay``
before dispatching solo; under load, dispatches fill toward
``max_batch`` and per-request latency approaches the bucket's compiled
execution time. Queue depth, coalesce sizes, and request p50/p99 are
recorded on the shared ``ServingMetrics``.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.observability.tracing import get_tracer
from keystone_tpu.serving.engine import CompiledPipeline

logger = logging.getLogger(__name__)


class MicroBatcher:
    def __init__(
        self,
        engine: CompiledPipeline,
        max_delay_ms: float = 5.0,
        max_batch: Optional[int] = None,
    ):
        self.engine = engine
        self.max_delay = max_delay_ms / 1e3
        self.max_batch = max_batch or engine.max_bucket
        if self.max_batch > engine.max_bucket:
            raise ValueError(
                f"max_batch {self.max_batch} exceeds the engine's largest "
                f"bucket {engine.max_bucket}"
            )
        self.metrics = engine.metrics
        # spec (treedef + leaf shapes/dtypes) of the CURRENT pending
        # window, set by the window's first submit and cleared when the
        # window drains: a mismatched request is rejected AT submit()
        # so one ragged example can't fail a coalesced window of
        # unrelated requests at stack time — and a bad request poisons
        # at most its own window, never the batcher's lifetime
        self._window_spec = None
        self._pending: List[Tuple[Any, Future, float]] = []
        self._cond = threading.Condition()
        self._closed = False
        self._worker = threading.Thread(
            target=self._loop, name="keystone-microbatcher", daemon=True
        )
        self._worker.start()

    # -- client side -------------------------------------------------------

    @staticmethod
    def _leaf_spec(a):
        # shape/dtype WITHOUT materializing a device array — submit()
        # is the per-request hot path; the real conversion happens once
        # per window at stack time
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            return tuple(a.shape), str(a.dtype)
        a = np.asarray(a)
        return a.shape, str(a.dtype)

    def _example_spec(self, example: Any):
        leaves, treedef = jax.tree_util.tree_flatten(example)
        return treedef, tuple(self._leaf_spec(a) for a in leaves)

    def submit(self, example: Any) -> "Future":
        """Enqueue one example (a pytree WITHOUT the leading batch axis);
        the returned future resolves to that example's pipeline output.
        Raises ``ValueError`` when the example's structure/shape/dtype
        disagrees with the current window's first example."""
        spec = self._example_spec(example)
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            if not self._pending:
                self._window_spec = spec
            elif spec != self._window_spec:
                raise ValueError(
                    f"example spec {spec} does not match this window's "
                    f"spec {self._window_spec}"
                )
            self._pending.append((example, fut, time.perf_counter()))
            self.metrics.set_queue_depth(len(self._pending))
            self._cond.notify()
        return fut

    def close(self, timeout: Optional[float] = 10.0) -> None:
        """Flush pending requests and stop the dispatcher thread. If the
        dispatcher can't drain within ``timeout`` (e.g. it is inside a
        cold multi-second XLA compile) this logs a warning and returns —
        the daemon worker keeps resolving in-flight futures as long as
        the process lives. Futures the dead-worker case would strand are
        failed rather than left to hang their waiters."""
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._worker.join(timeout)
        if self._worker.is_alive():
            logger.warning(
                "MicroBatcher dispatcher still running after %.1fs "
                "close timeout (cold compile in flight?); pending "
                "futures will resolve as it finishes", timeout,
            )
            return
        # a CLEAN worker exit provably drains _pending (submit rejects
        # once closed); anything left here means the dispatcher thread
        # died on an unexpected error outside _dispatch's catch — fail
        # those futures rather than hang their waiters
        with self._cond:
            stranded = self._pending[:]
            del self._pending[:]
        for _, fut, _ in stranded:
            if not fut.done():
                fut.set_exception(
                    RuntimeError("MicroBatcher closed before dispatch")
                )

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- dispatcher side ---------------------------------------------------

    def _take_batch(self) -> List[Tuple[Any, Future, float]]:
        """Block until there's work, then wait out the oldest request's
        deadline (or a full batch, or close) and take up to max_batch."""
        with self._cond:
            while not self._pending and not self._closed:
                self._cond.wait()
            if not self._pending:
                return []  # closed and drained
            deadline = self._pending[0][2] + self.max_delay
            while (
                len(self._pending) < self.max_batch
                and not self._closed
            ):
                remaining = deadline - time.perf_counter()
                if remaining <= 0 or not self._cond.wait(remaining):
                    break
            batch = self._pending[: self.max_batch]
            del self._pending[: len(batch)]
            self.metrics.set_queue_depth(len(self._pending))
            return batch

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if not batch:
                return
            self._dispatch(batch)

    def _dispatch(self, batch: List[Tuple[Any, Future, float]]) -> None:
        examples = [ex for ex, _, _ in batch]
        futures = [f for _, f, _ in batch]
        enqueued = [t for _, _, t in batch]
        self.metrics.record_coalesce(len(batch))
        # the engine's serving.dispatch span nests under this one, so
        # /tracez shows coalesce -> dispatch parent links per window
        try:
            with get_tracer().span(
                "microbatch.coalesce",
                engine=self.engine.name,
                window=len(batch),
            ):
                def stack(*xs):
                    # host payloads stack on HOST: the whole window then
                    # crosses to the device as ONE transfer inside the
                    # engine, not one per example
                    if any(isinstance(x, jax.Array) for x in xs):
                        return jnp.stack([jnp.asarray(x) for x in xs])
                    return np.stack([np.asarray(x) for x in xs])

                stacked = jax.tree_util.tree_map(stack, *examples)
                out = self.engine.apply(stacked, sync=True, owned=True)
            done = time.perf_counter()
            for i, fut in enumerate(futures):
                row = jax.tree_util.tree_map(lambda a, i=i: a[i], out)
                try:
                    fut.set_result(row)
                except Exception:
                    continue  # caller cancelled this request; the rest
                    # of the batch must still get their results
                self.metrics.record_request(done - enqueued[i])
        except Exception as e:  # resolve, never hang callers
            for fut in futures:
                if not fut.done():
                    fut.set_exception(e)
