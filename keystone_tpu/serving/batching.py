"""Adaptive micro-batching: coalesce single-example requests into
bucketed dispatches.

The serving analogue of the BCD solvers' async-stream discipline
(ops/learning/block_ls.py double-buffers slabs so the chip never idles):
here the chip never runs a one-row program per request. ``submit()``
enqueues an example and returns a ``Future``; a dispatcher thread
coalesces everything that arrives within a max-latency deadline (or
until the largest bucket fills, whichever first) into ONE padded
bucket dispatch through a ``CompiledPipeline``, then resolves each
request's future with its own row of the result.

Pending requests are segregated by example spec (pytree structure +
per-leaf shape/dtype): interleaved well-formed streams with different
shapes each coalesce into their own spec-homogeneous windows instead of
one stream's requests spuriously erroring against the other's — the
dispatcher always drains the spec whose OLDEST request is closest to
its deadline first, so segregation never starves a stream.

Latency/throughput contract: a lone request waits at most ``max_delay``
before dispatching solo; under load, dispatches fill toward
``max_batch`` and per-request latency approaches the bucket's compiled
execution time. Queue depth, coalesce sizes, and request p50/p99 are
recorded on the shared ``ServingMetrics``.

``swap_engine()`` is the request plane's live re-bucket hook
(gateway/lifecycle.py): it atomically replaces the engine behind the
batcher — queued and future windows dispatch through the replacement,
the window already in flight completes on the old engine, and no
request is dropped or reordered. In pipelined mode the swap also
rebuilds the lane pipeline's host staging pool (bucket sizes may have
changed); windows already in the stages carry their coalesce-time
engine and finish on it.

``pipeline_depth > 0`` turns the lane into a STAGED PIPELINE
(serving/pipeline.py): instead of dispatching each window inline, the
dispatcher hands it to per-stage threads (host-prep → upload → compute
→ deliver) connected by bounded queues, so window k+1's host work and
H2D transfer overlap window k's device compute. Results are
bit-identical to the serial path — both compose the engine's same
stage primitives over identical values. ``host_featurize`` plugs an
items-mode front-end (e.g. a fused tokenizer) into the prep stage of
EITHER mode: clients submit raw items, the hook turns each coalesced
window into the batched array tree the engine stages.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.observability.tracing import get_tracer
from keystone_tpu.serving.engine import CompiledPipeline
from keystone_tpu.serving.pipeline import (
    HostFeaturize,
    LanePipeline,
    resolve_window_futures,
)

logger = logging.getLogger(__name__)

# (example, future, enqueue time, optional parent span id)
_Entry = Tuple[Any, Future, float, Optional[int]]

# NON-ARRAY raw items (strings, records, ragged pytrees) coalesce into
# ONE stream when a host featurizer owns the window: the hook defines
# homogeneity there. ARRAY items still key by (shape, dtype) even in
# items mode — see _example_spec — so mixed-size raw images bucket into
# per-shape windows instead of collapsing into one stream that pads
# every window to the largest image ever seen.
_ITEMS_SPEC = ("items",)


class MicroBatcher:
    def __init__(
        self,
        engine: CompiledPipeline,
        max_delay_ms: float = 5.0,
        max_batch: Optional[int] = None,
        pipeline_depth: int = 0,
        host_featurize: Optional[HostFeaturize] = None,
    ):
        self.engine = engine
        self.max_delay = max_delay_ms / 1e3
        # an explicit max_batch is pinned across engine swaps; the
        # default tracks whatever the current engine's largest bucket is
        self._max_batch_pinned = max_batch is not None
        self.max_batch = max_batch or engine.max_bucket
        if self.max_batch > engine.max_bucket:
            raise ValueError(
                f"max_batch {self.max_batch} exceeds the engine's largest "
                f"bucket {engine.max_bucket}"
            )
        self.host_featurize = host_featurize
        self.pipeline_depth = int(pipeline_depth)
        # pipeline_depth > 0: dispatch through the staged lane pipeline
        # (host-prep/upload/compute/deliver threads, bounded handoffs)
        # instead of inline — see serving/pipeline.py
        self._pipeline: Optional[LanePipeline] = (
            LanePipeline(
                self._assemble, depth=self.pipeline_depth,
                name=engine.name,
                # gauge the pool on whichever engine currently serves
                # the lane, so windows that outlive a swap don't stamp
                # the new pool's footprint onto a retired engine
                current_metrics=lambda: self.metrics,
            )
            if self.pipeline_depth > 0 else None
        )
        self.metrics = engine.metrics
        # pending requests segregated by spec (treedef + leaf
        # shapes/dtypes): each spec coalesces into its own windows, so
        # interleaved streams of different shapes never poison each
        # other — a bad request fails only its own spec's window at
        # dispatch (stack/trace time), never a co-tenant stream's
        self._pending: dict = {}  # spec -> List[_Entry], insertion-ordered
        self._n_pending = 0
        self._cond = threading.Condition()
        self._closed = False
        self._worker = threading.Thread(
            target=self._loop, name="keystone-microbatcher", daemon=True
        )
        self._worker.start()

    # -- client side -------------------------------------------------------

    @staticmethod
    def _leaf_spec(a):
        # shape/dtype WITHOUT materializing a device array — submit()
        # is the per-request hot path; the real conversion happens once
        # per window at stack time
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            return tuple(a.shape), str(a.dtype)
        a = np.asarray(a)
        return a.shape, str(a.dtype)

    def _example_spec(self, example: Any):
        if self.host_featurize is not None:
            # items mode: the featurizer owns window ASSEMBLY, but
            # array items still carry a (shape, dtype) identity worth
            # segregating on — mixed-size raw images used to collapse
            # into one stream and pad every window to the largest
            # image, and the hook had to handle ragged windows. Keyed
            # windows are shape-homogeneous, bucket like array mode,
            # and stage into per-shape pooled buffers. Non-array items
            # (strings, records) have no stable per-item spec and keep
            # the single shared stream.
            if hasattr(example, "shape") and hasattr(example, "dtype"):
                return (
                    "items",
                    tuple(example.shape),
                    str(example.dtype),
                )
            return _ITEMS_SPEC
        leaves, treedef = jax.tree_util.tree_flatten(example)
        return treedef, tuple(self._leaf_spec(a) for a in leaves)

    def submit(
        self, example: Any, parent_span_id: Optional[int] = None
    ) -> "Future":
        """Enqueue one example (a pytree WITHOUT the leading batch axis);
        the returned future resolves to that example's pipeline output.
        ``parent_span_id`` threads an upstream span (e.g. the gateway's
        ``gateway.admit``) through to the window's ``microbatch.coalesce``
        span, which runs on the dispatcher thread."""
        spec = self._example_spec(example)
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            self._pending.setdefault(spec, []).append(
                (example, fut, time.perf_counter(), parent_span_id)
            )
            self._n_pending += 1
            self.metrics.set_queue_depth(self._n_pending)
            self._cond.notify()
        return fut

    def swap_engine(self, engine: CompiledPipeline) -> CompiledPipeline:
        """Atomically replace the engine behind this batcher and return
        the old one. Queued and future windows dispatch through the new
        engine; a window already in flight completes on the old engine
        (the caller lets it drain by dropping its reference — in-flight
        futures resolve from it normally). No request is dropped."""
        with self._cond:
            old, self.engine = self.engine, engine
            self.metrics = engine.metrics
            if not self._max_batch_pinned:
                self.max_batch = engine.max_bucket
            elif self.max_batch > engine.max_bucket:
                # engine.apply chunks oversized windows through its
                # largest bucket, so a too-small replacement degrades
                # (extra dispatches per window) instead of failing swaps
                logger.warning(
                    "swap_engine: pinned max_batch %d exceeds the new "
                    "engine's largest bucket %d; windows will chunk",
                    self.max_batch, engine.max_bucket,
                )
            if self._pipeline is not None:
                # rebuild the host staging pool: its buffers are cut
                # for the old bucket set; in-flight windows keep their
                # coalesce-time engine and finish on it
                self._pipeline.on_swap()
                # reset() dropped all pool accounting — push the zeroed
                # footprint so the gauge mirrors the pool immediately
                # instead of holding the pre-swap value until the next
                # window acquires a buffer. Ordering contract with
                # publish_staging_bytes: self.metrics was reassigned
                # BEFORE the reset and these stamps run AFTER it, so a
                # stage thread publishing under the pool lock can never
                # leave the retired engine carrying post-swap bytes
                old.metrics.set_staging_bytes(0)
                engine.metrics.set_staging_bytes(
                    self._pipeline.pool.staging_bytes
                )
            self._cond.notify()
        return old

    def close(self, timeout: Optional[float] = 10.0) -> None:
        """Flush pending requests and stop the dispatcher thread. If the
        dispatcher can't drain within ``timeout`` (e.g. it is inside a
        cold multi-second XLA compile) this logs a warning and returns —
        the daemon worker keeps resolving in-flight futures as long as
        the process lives. Futures the dead-worker case would strand are
        failed rather than left to hang their waiters."""
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._worker.join(timeout)
        if self._worker.is_alive():
            logger.warning(
                "MicroBatcher dispatcher still running after %.1fs "
                "close timeout (cold compile in flight?); pending "
                "futures will resolve as it finishes", timeout,
            )
            return
        if self._pipeline is not None:
            # the dispatcher has pushed every pending window into the
            # stage chain; flush it through and stop the stage threads
            self._pipeline.close(timeout=timeout)
        # a CLEAN worker exit provably drains _pending (submit rejects
        # once closed); anything left here means the dispatcher thread
        # died on an unexpected error outside _dispatch's catch — fail
        # those futures rather than hang their waiters
        with self._cond:
            stranded = [
                e for entries in self._pending.values() for e in entries
            ]
            self._pending.clear()
            self._n_pending = 0
        for _, fut, _, _ in stranded:
            if not fut.done():
                fut.set_exception(
                    RuntimeError("MicroBatcher closed before dispatch")
                )

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- dispatcher side ---------------------------------------------------

    def _take_batch(self) -> Tuple[List[_Entry], Optional[CompiledPipeline]]:
        """Block until there's work, pick the spec whose oldest request
        is nearest its deadline, wait that deadline out (or a full
        window, or close), and take up to max_batch of that spec."""
        with self._cond:
            while not self._n_pending and not self._closed:
                self._cond.wait()
            if not self._n_pending:
                return [], None  # closed and drained
            # the spec with the OLDEST head request dispatches first:
            # its deadline is the earliest, and age-order across specs
            # means no stream waits behind a younger one
            spec = min(
                self._pending, key=lambda s: self._pending[s][0][2]
            )
            deadline = self._pending[spec][0][2] + self.max_delay
            while (
                len(self._pending[spec]) < self.max_batch
                and not self._closed
            ):
                remaining = deadline - time.perf_counter()
                if remaining <= 0 or not self._cond.wait(remaining):
                    break
            entries = self._pending[spec]
            batch = entries[: self.max_batch]
            del entries[: len(batch)]
            if not entries:
                del self._pending[spec]
            self._n_pending -= len(batch)
            self.metrics.set_queue_depth(self._n_pending)
            # snapshot under the lock so a concurrent swap_engine cannot
            # split a window across two engines
            return batch, self.engine

    def _loop(self) -> None:
        while True:
            batch, engine = self._take_batch()
            if not batch:
                return
            self._dispatch(batch, engine)

    def _assemble(self, examples: List[Any]) -> Tuple[Any, bool]:
        """One window of raw examples -> ``(batched tree, owned)``.
        Shared by the serial dispatch and the pipeline's host-prep
        stage, so both modes assemble identical values. ``owned`` is
        False only on the single-entry fast path — the [1, ...] view
        aliases the caller's buffers, so the engine must keep its
        protective pre-donation copy."""
        if self.host_featurize is not None:
            # items mode: the hook turns raw items into the batched
            # array tree (fresh buffers — featurizers allocate)
            return self.host_featurize(list(examples)), True
        if len(examples) == 1:
            # single-entry fast path (common at low load): skip the
            # stack copy; lift to a [1, ...] VIEW of the caller's tree
            def lift(a):
                if isinstance(a, jax.Array):
                    return jnp.asarray(a)[None]
                return np.asarray(a)[None]

            return (
                jax.tree_util.tree_map(lift, examples[0]),
                False,
            )

        def stack(*xs):
            # host payloads stack on HOST: the whole window then
            # crosses to the device as ONE transfer inside the
            # engine, not one per example
            if any(isinstance(x, jax.Array) for x in xs):
                return jnp.stack([jnp.asarray(x) for x in xs])
            return np.stack([np.asarray(x) for x in xs])

        return jax.tree_util.tree_map(stack, *examples), True

    def _dispatch(
        self, batch: List[_Entry], engine: CompiledPipeline
    ) -> None:
        examples = [ex for ex, _, _, _ in batch]
        futures = [f for _, f, _, _ in batch]
        enqueued = [t for _, _, t, _ in batch]
        metrics = engine.metrics
        metrics.record_coalesce(len(batch))
        # the engine's serving.dispatch span (serial) or the
        # pipeline.<stage> spans nest under this one, so /tracez shows
        # coalesce -> dispatch/stage parent links per window; the
        # window's parent is the FIRST request's upstream span (the
        # gateway.admit that has waited longest), linking the admit ->
        # coalesce -> stages chain across threads
        try:
            with get_tracer().span(
                "microbatch.coalesce",
                parent_id=batch[0][3],
                engine=engine.name,
                window=len(batch),
            ) as span:
                if self._pipeline is not None:
                    # blocks while the prep queue is full — the lane's
                    # backpressure point (pending piles up behind the
                    # batcher and admission sheds upstream)
                    self._pipeline.submit_window(
                        examples, futures, enqueued, engine,
                        span.span_id,
                    )
                    return
                stacked, owned = self._assemble(examples)
                out = engine.apply(stacked, sync=True, owned=owned)
            resolve_window_futures(metrics, out, futures, enqueued)
        except Exception as e:  # resolve, never hang callers
            for fut in futures:
                if not fut.done():
                    fut.set_exception(e)
