"""AOT executable cache: zero-cold-start serving.

Every number since the first serving PR has been a *warm* number: a
fresh gateway process still pays trace + lowering + XLA compile per
bucket before ``/readyz`` flips, which is exactly the cold-start tax
that caps how fast the stack can scale out or roll a new engine
generation. The persistent XLA compilation cache (PR 1) removes the
*compile* but a restarted process still pays trace + lowering +
cache-replay per bucket program.

This module removes the whole thing. ``CompiledPipeline.warmup``
already AOT-lowers every bucket program for the device cost models
(``lower().compile()``); an ``AotStore`` serializes those compiled
executables once — ``jax.experimental.serialize_executable`` — into an
on-disk store keyed by a **fingerprint** of everything that could make
a stored program wrong to reuse:

- the per-example input spec (leaf shapes + dtypes) and the engine's
  full bucket list + the specific bucket,
- the donation + sharding configuration (donation is baked into the
  executable as input/output aliasing),
- jax + jaxlib versions, the backend ("cpu"/"tpu"/"gpu"), the device
  kind ("TPU v4", ...) and device count (serialized programs are
  PJRT-executable bytes — they do not survive a toolchain or hardware
  change),
- a **model token**: a content digest of the fitted pipeline's
  operators and their parameter arrays. The weights are *constants
  inside the serialized program*, so two models with identical shapes
  MUST NOT share an entry — a collision would silently serve another
  model's predictions.

On the load side ``warmup`` installs a deserialized executable
*before any trace happens* for that bucket: a replica (or the
autoscaler's next-generation engine) goes from ``exec()`` to serving
in roughly deserialize time. The contract is **absent-not-broken**,
the same as the device-observability plane: any miss, fingerprint
mismatch, corrupt entry, or deserialize failure falls back silently
to the normal jit + persistent-compile-cache path and is *counted*,
never raised, on the serving path:

- ``keystone_aot_cache_hits_total`` / ``_misses_total`` /
  ``_errors_total`` counters,
- ``keystone_aot_cache_load_seconds`` histogram (deserialize + install
  wall time per entry),
- an ``aot_cache`` block in the admin endpoint's ``/varz`` ``build``
  document (store dir, entry count, hit/miss/error totals).

The store directory is configured beside the persistent compile cache
(``parallel.runtime.setup_aot_cache``: argument, then
``$KEYSTONE_AOT_CACHE``, then ``~/.cache/keystone_tpu/aot``); the
``serve-aot-build`` CLI app pre-populates it at build/deploy time so a
brand-new host starts hot (``bin/smoke-aot.sh`` drills exactly that,
and the ``serving_cold_start_aot`` bench row measures it
cross-process).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import tempfile
import threading
import time
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

# bump to invalidate every existing store entry on a format change
STORE_FORMAT = "keystone-aot-v1"

ENTRY_SUFFIX = ".aotx"

# deserialize+install is milliseconds; a pathological NFS store is
# seconds — the histogram must resolve both
LOAD_SECONDS_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0,
)


# -- version/identity probes (module-level so tests can fake a jax
# -- upgrade without touching the real modules) ---------------------------

def runtime_versions() -> Dict[str, str]:
    """The toolchain part of the fingerprint: serialized executables
    are PJRT bytes and do not survive a jax/jaxlib upgrade."""
    import jax
    import jaxlib

    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
    }


def device_identity() -> Dict[str, Any]:
    """The hardware part of the fingerprint. Best-effort: a backend
    that fails to report identity yields stable placeholders (the
    store then keys only on backend name — still safe, since
    deserialization itself rejects foreign executables)."""
    import jax

    ident: Dict[str, Any] = {
        "backend": None, "device_kind": None, "device_count": None,
    }
    try:
        ident["backend"] = jax.default_backend()
        devices = jax.devices()
        if devices:
            ident["device_kind"] = devices[0].device_kind
            ident["device_count"] = len(devices)
    except Exception:
        pass
    return ident


def _hash_update(h, value: Any) -> None:
    """Deterministically fold one operator attribute into the model
    token. Arrays hash by shape/dtype/bytes (the weights ARE the
    program constants); containers recurse; primitives hash by repr;
    anything else contributes its type name only — weaker, but the
    parameter arrays carry the real identity.

    Every component is FRAMED (type tag + terminator): unframed
    concatenation made distinct parameter sets collide — e.g.
    ``(1, 23)`` and ``(12, 3)`` both fold to the bytes ``123`` — and a
    token collision here means one model silently serving another
    model's predictions."""
    import jax

    if isinstance(value, (np.ndarray, np.generic, jax.Array)):
        arr = np.asarray(value)
        h.update(
            b"a<" + str(arr.shape).encode() + b"|"
            + str(arr.dtype).encode() + b"|"
        )
        h.update(arr.tobytes())
        h.update(b">")
    elif isinstance(value, (str, bytes, int, float, bool, type(None))):
        h.update(b"p<" + repr(value).encode() + b">")
    elif isinstance(value, dict):
        h.update(b"d<")
        for k in sorted(value, key=repr):
            h.update(b"k<" + repr(k).encode() + b">")
            _hash_update(h, value[k])
        h.update(b">")
    elif isinstance(value, (list, tuple)):
        h.update(b"l<")
        for v in value:
            _hash_update(h, v)
        h.update(b">")
    else:
        h.update(b"t<" + type(value).__qualname__.encode() + b">")


def pipeline_token(fitted) -> str:
    """Content digest of a ``FittedPipeline``: operator classes in
    topological order plus every operator's attribute values (parameter
    arrays hashed by content). Two fitted pipelines with identical
    architectures but different weights get different tokens — the
    property that keeps one model's cached executable from ever
    serving another model's predictions.

    Memoized on the pipeline object (the same lazily-attached-cache
    idiom its operators use): an N-lane gateway builds N engines per
    generation from ONE fitted pipeline, and hashing a large model's
    every parameter N times per cold start would be repeated work on
    exactly the path this module optimizes. A ``FittedPipeline`` is
    immutable once fit (refits build new objects), so the cache can't
    go stale."""
    import dataclasses

    cached = getattr(fitted, "_aot_pipeline_token", None)
    if cached is not None:
        return cached
    h = hashlib.sha256()
    for nid in fitted._topo:
        op = fitted.graph.operators[nid]
        # the WIRING is part of the model: same operators in the same
        # topo order compute different things when the edges differ
        # (a Join fed (A(x), x) vs (A(x), A(x))). Ids are hashed by
        # repr — graphs built along different construction paths may
        # token-differ for the same model (a harmless miss), but two
        # different computations can never token-collide.
        h.update(
            b"n<" + repr(nid).encode() + b"|"
            + ",".join(
                repr(d) for d in fitted.graph.dependencies[nid]
            ).encode()
            + b">"
        )
        h.update(b"op<" + type(op).__qualname__.encode() + b">")
        if dataclasses.is_dataclass(op):
            # declared fields only: transformers are dataclasses whose
            # fields ARE the parameters
            state = {
                f.name: getattr(op, f.name, None)
                for f in dataclasses.fields(op)
            }
        else:
            state = getattr(op, "__dict__", None) or {}
        for name in sorted(state):
            if name.startswith("_"):
                # lazily-attached caches (_vmapped_apply,
                # _arr_digest_cache, ...) appear after first use; a
                # token that shifted when the pipeline RAN would turn
                # every restart into a miss
                continue
            h.update(b"f<" + name.encode() + b">")
            _hash_update(h, state[name])
    h.update(
        b"s<"
        + repr(fitted.graph.sink_dependencies[fitted.sink]).encode()
        + b">"
    )
    token = h.hexdigest()
    try:
        fitted._aot_pipeline_token = token
    except Exception:
        pass  # slots/frozen pipeline: just recompute next time
    return token


def runtime_identity() -> Dict[str, Any]:
    """``runtime_versions() + device_identity()`` in one dict — the
    warmup-invariant part of the fingerprint, computed once per warmup
    and passed to every ``bucket_key`` call (re-probing jax per bucket
    would be repeated work on exactly the cold path this module
    optimizes)."""
    return {**runtime_versions(), **device_identity()}


def bucket_key(
    specs: Sequence[Tuple[Tuple[int, ...], Any]],
    buckets: Sequence[int],
    bucket: int,
    donate: bool,
    shard: bool,
    model_token: str,
    identity: Optional[Dict[str, Any]] = None,
    featurize_token: Optional[str] = None,
    sharding_token: Optional[str] = None,
    namespace: Optional[str] = None,
) -> Tuple[str, Dict[str, Any]]:
    """Fingerprint one bucket program. Returns ``(key, meta)`` where
    ``key`` is the store filename stem and ``meta`` is the full
    human-readable field dict — stored inside the entry and re-checked
    on load, so even a filename collision cannot install a wrong
    executable. ``identity`` is ``runtime_identity()``, passed in by
    loops that fingerprint many buckets. ``featurize_token`` is the
    ``pipeline_token`` of a fused device-side featurize stage (engine
    ``featurize=``), or None for plain model programs: the featurize
    parameters are constants inside the serialized executable just like
    the model weights, so fused and unfused programs — and programs
    fused with DIFFERENT featurizers — must never share an entry.
    ``sharding_token`` is ``serving/sharding.sharding_token``'s digest
    of a model-sharded engine's resolved partition-spec tree + mesh
    topology, or None for replicated programs: a mesh-sharded
    executable is a structurally different program (GSPMD-partitioned,
    params as arguments) and must never share an entry with a
    replicated one — while replicated programs' fingerprints stay
    byte-identical to pre-sharding stores (no fleet-wide cold start on
    upgrade). ``namespace`` is the model-zoo partition
    (``AotStore(namespace=model_id)``): two co-hosted models never
    share a cache slot even if their content tokens somehow agreed,
    and the GC accounts each model's bytes separately."""
    meta: Dict[str, Any] = {
        "format": STORE_FORMAT,
        "specs": [
            [list(shape), str(np.dtype(dtype))] for shape, dtype in specs
        ],
        "buckets": [int(b) for b in buckets],
        "bucket": int(bucket),
        "donate": bool(donate),
        "shard": bool(shard),
        "model_token": model_token,
        # present ONLY for fused programs: unconditionally stamping
        # None here would shift every unfused key and cold-start every
        # existing store on upgrade. Fused vs unfused still can never
        # collide — the extra key changes the fused hash, and the meta
        # re-check rejects a planted entry whose key set differs.
        **(
            {"featurize_token": featurize_token}
            if featurize_token is not None else {}
        ),
        # same stamped-only-when-set discipline as featurize_token:
        # unconditionally writing None here would shift every
        # replicated key and cold-start every existing store
        **(
            {"sharding_token": sharding_token}
            if sharding_token is not None else {}
        ),
        # ditto: single-model processes (namespace None) keep their
        # pre-zoo fingerprints byte-identical
        **(
            {"namespace": namespace}
            if namespace is not None else {}
        ),
        **(identity if identity is not None else runtime_identity()),
    }
    blob = json.dumps(meta, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest(), meta


# entry file layout: magic, 8-byte big-endian meta length, the meta as
# canonical JSON, then the pickled executable payload. The JSON
# preamble is validated against the requested fingerprint BEFORE the
# pickle bytes are touched.
ENTRY_MAGIC = b"KAOT1\n"


class AotStore:
    """On-disk store of serialized bucket executables.

    ``save``/``load`` never raise on the serving path: every failure is
    counted (``errors``) and reported as "no entry" so the caller falls
    back to the normal compile path. Entries are written atomically
    (tmp file + rename), so a crashed writer can never leave a
    half-entry a reader would trip over.

    TRUST BOUNDARY: the store dir. Entries carry pickled PJRT
    executables (``jax.experimental.serialize_executable`` is
    pickle-based), and unpickling executes code — so loading an entry
    extends write-access-to-the-dir into code-execution-in-the-server,
    exactly like loading a model checkpoint. The dir is created 0700,
    the fingerprint meta rides in a plain-JSON preamble that is
    validated BEFORE any pickle bytes are touched (a mismatched or
    malformed entry is rejected unpickled), and the remaining rule is
    operational: only let build steps you trust as much as the serving
    binary write to the store."""

    # an in-flight save's tmp file older than this is a crashed
    # writer's leftover, safe to sweep (a live save lasts seconds)
    STALE_TMP_S = 3600.0

    def __init__(
        self, root: str, registry=None, namespace: Optional[str] = None
    ):
        self.root = os.path.abspath(root)
        # the model-zoo partition: folded into every bucket_key this
        # store's engines compute (engine warmup reads it off the
        # store), so entries from different namespaces coexist in one
        # root dir but can never be loaded across — the meta re-check
        # rejects a planted foreign entry before unpickling. None is
        # the single-model default and keeps pre-zoo keys stable.
        self.namespace = namespace
        os.makedirs(self.root, mode=0o700, exist_ok=True)
        self._sweep_stale_tmp()
        # plain per-store totals for status()/tests, plus the shared
        # scrape families on the (global) MetricsRegistry
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock
        self.errors = 0  # guarded-by: _lock
        self.saves = 0  # guarded-by: _lock
        self._lock = threading.Lock()
        from keystone_tpu.observability.registry import (
            get_global_registry,
        )

        reg = registry if registry is not None else get_global_registry()
        self._hits_c = reg.counter(
            "keystone_aot_cache_hits_total",
            "AOT executable store: bucket programs installed from a "
            "serialized entry (no trace, no compile)",
        )
        self._misses_c = reg.counter(
            "keystone_aot_cache_misses_total",
            "AOT executable store: lookups that found no entry "
            "(fell back to the normal compile path)",
        )
        self._errors_c = reg.counter(
            "keystone_aot_cache_errors_total",
            "AOT executable store: corrupt/mismatched/undeserializable "
            "entries and failed saves (fell back silently)",
        )
        self._load_h = reg.histogram(
            "keystone_aot_cache_load_seconds",
            "wall seconds to deserialize, validate, and install one "
            "stored bucket executable (hits only)",
            buckets=LOAD_SECONDS_BUCKETS,
        )
        self._bytes_g = reg.gauge(
            "keystone_aot_store_bytes",
            "on-disk bytes of AOT store entries, per model-zoo "
            "namespace ('default' for single-model stores)",
            ("namespace",),
        )
        self._publish_bytes()

    # -- store layout ------------------------------------------------------

    def path_for(self, key: str) -> str:
        return os.path.join(self.root, key + ENTRY_SUFFIX)

    def entries(self) -> list:
        try:
            return sorted(
                f[: -len(ENTRY_SUFFIX)]
                for f in os.listdir(self.root)
                # mkstemp tmp names also end in the suffix; a crashed
                # writer's leftover must not count as an entry
                if f.endswith(ENTRY_SUFFIX) and not f.startswith(".")
            )
        except OSError:
            return []

    def _sweep_stale_tmp(self) -> None:
        """Remove crashed writers' ``.tmp-*`` leftovers (age-gated: a
        CONCURRENT process's in-flight save must survive)."""
        try:
            now = time.time()
            for f in os.listdir(self.root):
                if not f.startswith(".tmp-"):
                    continue
                path = os.path.join(self.root, f)
                try:
                    if now - os.path.getmtime(path) > self.STALE_TMP_S:
                        os.unlink(path)
                except OSError:
                    pass
        except OSError:
            pass

    # -- accounting --------------------------------------------------------

    def _count(self, which: str) -> None:
        with self._lock:
            setattr(self, which, getattr(self, which) + 1)
        counter = {
            "hits": self._hits_c,
            "misses": self._misses_c,
            "errors": self._errors_c,
        }.get(which)
        if counter is not None:
            counter.inc()

    def record_error(self) -> None:
        """An entry that loaded but failed to EXECUTE (the engine
        validates with one dispatch before trusting it) — or a
        pipeline that couldn't be fingerprinted at all — is charged
        here by the caller."""
        self._count("errors")

    def record_hit(self, seconds: Optional[float] = None) -> None:
        """One stored executable VALIDATED and installed. Counted by
        the engine after its validation dispatch succeeds — not by
        ``load()`` — so ``keystone_aot_cache_hits_total`` never counts
        an executable that deserialized but was thrown away, and the
        load-seconds histogram (``seconds``: the full deserialize +
        validate + install wall) never shows healthy latencies for
        installs that didn't happen."""
        self._count("hits")
        if seconds is not None:
            self._load_h.observe(seconds)

    # -- save / load -------------------------------------------------------

    def save(self, key: str, compiled, meta: Dict[str, Any]) -> Optional[str]:
        """Serialize one ``jax.stages.Compiled`` under ``key``.
        Best-effort: backends whose executables don't serialize (or a
        read-only store dir) log + count an error and return None —
        serving proceeds, the store just stays cold."""
        from jax.experimental import serialize_executable

        path = self.path_for(key)
        try:
            payload, in_tree, out_tree = serialize_executable.serialize(
                compiled
            )
            meta_blob = json.dumps(meta, sort_keys=True).encode()
            blob = (
                ENTRY_MAGIC
                + len(meta_blob).to_bytes(8, "big")
                + meta_blob
                + pickle.dumps(
                    {
                        "payload": payload,
                        "in_tree": in_tree,
                        "out_tree": out_tree,
                    },
                    protocol=pickle.HIGHEST_PROTOCOL,
                )
            )
            fd, tmp = tempfile.mkstemp(
                dir=self.root, prefix=".tmp-", suffix=ENTRY_SUFFIX
            )
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path)  # atomic: readers never see a
                # partial entry
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except Exception:
            self._count("errors")
            logger.info(
                "aot store: could not serialize bucket executable to "
                "%s", path, exc_info=True,
            )
            return None
        with self._lock:
            self.saves += 1
        self._publish_bytes()
        logger.info(
            "aot store: saved bucket %s executable (%d bytes) to %s",
            meta.get("bucket"), len(blob), path,
        )
        return path

    def load(self, key: str, meta: Dict[str, Any]) -> Tuple[Any, str]:
        """Deserialize the entry under ``key`` into a callable
        ``jax.stages.Compiled``. Returns ``(loaded, "hit")`` on
        success, ``(None, "miss")`` when the entry is absent, and
        ``(None, "error")`` when it exists but is corrupt or its
        stored meta disagrees with ``meta`` — the outcome rides back
        so the engine's per-bucket report tells the same story the
        hit/miss/error counters do. The hit COUNTER is not bumped
        here: the caller confirms with ``record_hit()`` once the
        executable survives its validation dispatch. Never raises."""
        from jax.experimental import serialize_executable

        path = self.path_for(key)
        if not os.path.exists(path):
            self._count("misses")
            return None, "miss"
        try:
            with open(path, "rb") as f:
                data = f.read()
            stored_meta, body = self._split_entry(data)
            if stored_meta != meta:
                # key collision or a fingerprint-field drift: the
                # stored program is not provably THIS program — and
                # nothing of it has been unpickled
                raise ValueError(
                    "stored meta disagrees with the requested "
                    "fingerprint"
                )
            blob = pickle.loads(body)
            loaded = serialize_executable.deserialize_and_load(
                blob["payload"], blob["in_tree"], blob["out_tree"]
            )
        except Exception:
            self._count("errors")
            logger.info(
                "aot store: entry %s unusable; falling back to "
                "compile", path, exc_info=True,
            )
            return None, "error"
        return loaded, "hit"

    @staticmethod
    def _split_entry(data: bytes) -> Tuple[Dict[str, Any], bytes]:
        """Entry bytes -> (meta dict from the JSON preamble, pickled
        payload bytes). Raises on anything malformed — WITHOUT having
        unpickled a single byte."""
        if not data.startswith(ENTRY_MAGIC):
            raise ValueError("not an AOT store entry (bad magic)")
        off = len(ENTRY_MAGIC)
        n = int.from_bytes(data[off:off + 8], "big")
        meta_end = off + 8 + n
        if n <= 0 or meta_end > len(data):
            raise ValueError("truncated AOT store entry")
        return json.loads(data[off + 8:meta_end]), data[meta_end:]

    def read_meta(self, key: str) -> Optional[Dict[str, Any]]:
        """The stored fingerprint meta of one entry (JSON preamble
        only — nothing is unpickled), or None when absent/corrupt.
        Ops tooling and tests can audit a store without trusting it."""
        try:
            with open(self.path_for(key), "rb") as f:
                return self._split_entry(f.read())[0]
        except Exception:
            return None

    # -- namespace accounting + GC -----------------------------------------

    def _owned_entries(self) -> list:
        """``(key, size_bytes, mtime)`` for every entry in THIS store's
        namespace, mtime-ascending (the LRU eviction order). Entries
        whose JSON preamble is unreadable are claimed by every
        namespace: they can never be loaded, so any GC may clear them.
        Meta is read from the preamble only — auditing a store must
        never unpickle it."""
        owned = []
        for key in self.entries():
            meta = self.read_meta(key)
            if meta is not None and meta.get("namespace") != self.namespace:
                continue
            try:
                st = os.stat(self.path_for(key))
            except OSError:
                continue  # raced a concurrent eviction
            owned.append((key, int(st.st_size), st.st_mtime))
        owned.sort(key=lambda e: (e[2], e[0]))
        return owned

    def namespace_bytes(self) -> int:
        """On-disk bytes of this namespace's entries — what the
        ``keystone_aot_store_bytes{namespace}`` gauge exports."""
        return sum(size for _, size, _ in self._owned_entries())

    def _publish_bytes(self) -> None:
        try:
            self._bytes_g.set(
                float(self.namespace_bytes()),
                (self.namespace or "default",),
            )
        except Exception:
            # the gauge is observability, not correctness: a raced
            # listdir/stat must never fail a save or a gc
            logger.debug("aot store: bytes gauge update failed",
                         exc_info=True)

    def gc(
        self, max_bytes: int, pinned: Sequence[str] = ()
    ) -> Dict[str, Any]:
        """Evict least-recently-used entries (mtime order — ``save``
        rewrites touch it, so recently refreshed generations survive)
        until this NAMESPACE's on-disk bytes fit ``max_bytes``. Entries
        whose key is in ``pinned`` are never evicted, even if that
        leaves the namespace over budget (a pinned hot model's programs
        beat the byte target). Other namespaces' entries are invisible:
        one model's churn can never GC another model's executables.
        Best-effort like every store op — an unlinkable entry is
        counted as an error and skipped, never raised."""
        report: Dict[str, Any] = {
            "namespace": self.namespace, "evicted": [],
            "evicted_bytes": 0,
        }
        pinned_set = set(pinned)
        owned = self._owned_entries()
        total = sum(size for _, size, _ in owned)
        for key, size, _ in owned:
            if total <= max_bytes:
                break
            if key in pinned_set:
                continue
            try:
                os.unlink(self.path_for(key))
            except OSError:
                self._count("errors")
                continue
            total -= size
            report["evicted"].append(key)
            report["evicted_bytes"] += size
        report["kept_bytes"] = total
        report["over_budget"] = total > max_bytes
        self._publish_bytes()
        if report["evicted"]:
            logger.info(
                "aot store gc (namespace %s): evicted %d entries "
                "(%d bytes), %d bytes kept",
                self.namespace or "default", len(report["evicted"]),
                report["evicted_bytes"], report["kept_bytes"],
            )
        return report

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "dir": self.root,
                "namespace": self.namespace,
                "entries": len(self.entries()),
                "hits": self.hits,
                "misses": self.misses,
                "errors": self.errors,
                "saves": self.saves,
            }


# -- the process-configured store (parallel.runtime owns the dir) ---------

_configured: Optional[AotStore] = None
_configured_lock = threading.Lock()


def configured_store() -> Optional[AotStore]:
    """The store at the dir ``parallel.runtime.setup_aot_cache``
    configured for this process, or None when none was configured
    (engines then skip the AOT path entirely — the default for
    library/test use; the serving CLIs call setup unless
    ``--no-cache``)."""
    global _configured
    from keystone_tpu.parallel import runtime

    root = runtime.aot_cache_dir()
    if root is None:
        return None
    with _configured_lock:
        if _configured is None or _configured.root != os.path.abspath(root):
            try:
                _configured = AotStore(root)
            except Exception:
                # the dir was creatable at setup time but isn't now
                # (cache purge, NFS outage): the serving path must get
                # "no store", never an exception — same contract as
                # every other store failure
                logger.info(
                    "aot store at %s unavailable; serving without it",
                    root, exc_info=True,
                )
                return None
        return _configured


def namespaced_store(namespace: str) -> Optional[AotStore]:
    """A model-zoo view over the process-configured store dir: same
    root, entries fingerprinted (and GC'd) under ``namespace``. None
    when no store dir is configured — the zoo then serves without AOT,
    exactly like a single-model engine would. Not memoized: each model
    owns its view (per-namespace byte gauges and GC state are
    per-instance)."""
    from keystone_tpu.parallel import runtime

    root = runtime.aot_cache_dir()
    if root is None:
        return None
    try:
        return AotStore(root, namespace=str(namespace))
    except Exception:
        logger.info(
            "aot store at %s unavailable for namespace %s; serving "
            "without it", root, namespace, exc_info=True,
        )
        return None


def status() -> Dict[str, Any]:
    """The ``aot_cache`` block of ``/varz``'s build document."""
    store = configured_store()
    if store is None:
        return {"dir": None}
    return store.status()


# -- serve-aot-build: pre-populate the store at build/deploy time ---------

def build_main(argv=None) -> int:
    """``python -m keystone_tpu serve-aot-build [--buckets 8,32,128]``
    — compile every bucket of the (serve-bench/serve-gateway demo)
    pipeline once and serialize the executables into the AOT store, so
    a brand-new host's ``serve-gateway`` goes from exec() to serving
    without a single XLA compile. Real deployments call
    ``CompiledPipeline.warmup`` over their own fitted pipeline with
    the store configured — this entry is the demo/smoke/bench path."""
    import argparse

    import jax.numpy as jnp

    from keystone_tpu.parallel.runtime import (
        setup_aot_cache,
        setup_compilation_cache,
    )
    from keystone_tpu.serving.bench import build_pipeline

    ap = argparse.ArgumentParser(
        prog="keystone_tpu serve-aot-build",
        description="pre-populate the AOT serialized-executable store",
    )
    ap.add_argument("--buckets", default="8,32,128",
                    help="comma-separated row buckets (must match the "
                    "serving config that will load the store)")
    ap.add_argument("--d", type=int, default=256)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--depth", type=int, default=4)
    ap.add_argument("--aot-cache", default=None, metavar="DIR",
                    help="store dir (default: $KEYSTONE_AOT_CACHE, "
                    "then ~/.cache/keystone_tpu/aot)")
    args = ap.parse_args(argv)

    # the persistent compile cache makes the build's own
    # lower().compile() replay from disk on a rebuild, and warmup's jit
    # dispatch replay the same program instead of compiling twice
    setup_compilation_cache()
    root = setup_aot_cache(args.aot_cache)
    if root is None:
        print(json.dumps({"error": "aot cache dir unavailable"}))
        return 1
    store = configured_store()
    if store is None:
        # the dir existed at setup time but the store can't open it
        # now (permission flip, NFS blip): same clean error path as an
        # uncreatable dir, not an AttributeError
        print(json.dumps({"error": "aot store unavailable", "dir": root}))
        return 1
    buckets = tuple(int(b) for b in args.buckets.split(","))
    fitted = build_pipeline(d=args.d, hidden=args.hidden, depth=args.depth)
    engine = fitted.compiled(
        buckets=buckets, name="aot-build", aot_store=store
    )
    t0 = time.perf_counter()
    times = engine.warmup(
        example=jnp.zeros((args.d,), jnp.float32)
    )
    report = {
        "dir": root,
        "buckets": list(engine.buckets),
        "warmup_seconds": {
            str(b): round(t, 3) for b, t in times.items()
        },
        "wall_seconds": round(time.perf_counter() - t0, 3),
        "aot": engine.aot_report(),
        **store.status(),
    }
    print(json.dumps(report), flush=True)
    # entries must exist for every bucket at exit: freshly saved, hit
    # from a previous build, or REPAIRED (a broken entry recompiled
    # and re-saved reports status "error" + fallback "saved" — the
    # store is whole, and failing the deploy step over an already
    # fixed entry would just make the rerun mysteriously green)
    ok = all(
        v.get("status") in ("saved", "hit")
        or v.get("fallback") == "saved"
        for v in (
            engine.aot_report().get(b, {}) for b in engine.buckets
        )
    )
    return 0 if ok else 1
