"""Bucketed compiled execution for ``FittedPipeline``.

``FittedPipeline.jit_batch`` stages the whole batched apply path into
one XLA program — but one program PER BATCH SHAPE: every distinct
request size costs a full compile (seconds on a cold shape). A
``CompiledPipeline`` instead fixes a small set of power-of-two row
buckets, zero-pads each incoming batch up to the smallest covering
bucket, and dispatches the bucket's compiled program; steady-state
traffic therefore compiles at most ``len(buckets)`` programs, however
many distinct batch sizes arrive. Zero pad rows are safe by the
``Dataset`` padding discipline (parallel/dataset.py: rows past ``n``
are zeros and transformers must map them to values safe to keep as
padding); outputs are sliced back to the valid rows.

Input buffers the engine stages are donated to XLA on backends that
support donation (TPU/GPU), so serving doesn't hold two copies of each
padded batch. The optional sharded variant places each staged batch
over the mesh data axis for multi-chip serving — same program, one
compile per bucket, XLA inserts the collectives.

**Mesh-sharded parameters** (``param_sharding=``): the model axis.
``shard=`` scales the *batch*; a model whose parameters exceed one
chip's HBM needs the *weights* split. ``param_sharding`` resolves a
declarative rule set (``serving/sharding.py``: regex over the fitted
pipeline's named param pytree -> ``PartitionSpec``; ``True`` = the
default solver-output rules) against the pipeline, places each param
over the mesh's model axis via ``NamedSharding`` once at construction,
and traces every bucket program with the params as explicit *arguments*
(``ParamBinder``) instead of baked-in constants — each device's
executable holds only its weight shards. Composes with ``shard=``
(rows over ``data``, weights over ``model``, one 2-D mesh) and with
``featurize=`` (the fused stage's params stay baked/replicated; pass
rules matching them to split those too — they ride the same binder
only for the model pipeline). The AOT fingerprint carries a
``sharding_token`` so a mesh-sharded program can never collide with a
replicated one (or with a different partitioning/mesh shape).

**Device-side featurization** (``featurize=``): a second fitted
pipeline — a pure-JAX featurize chain such as the ``ops/images``
Convolver/LCS/FisherVector stacks — fused IN FRONT of the model into
the same per-bucket program. Requests then stage **raw bytes** (e.g.
``uint8`` images: 4× fewer H2D bytes than the f32 features), and the
cast + featurize + predict all ride the single compiled dispatch; XLA
fuses across the featurize/model boundary and the bucket cost model
(MFU/roofline/goodput) automatically accounts for the fused FLOPs.
This is the device-side counterpart of the batcher's ``host_featurize``
seam — use that one for featurizers that can't trace (native/items
code); use this one to kill the host-prep + upload bottleneck for
chains that are already jax. Buckets stay row counts; the raw
per-example shape rides the example spec exactly like any array input,
and the ``keystone_serving_h2d_bytes_total`` counter makes the
wire-bytes reduction a scraped fact.

The dispatch path is factored into stage primitives so the staged lane
pipeline (``serving/pipeline.py``) can run them on separate threads —
``host_stage`` (pad on host into a pooled reusable buffer),
``upload_staged`` (H2D placement, sharded when the engine is), and
``compute_staged`` (the compiled bucket program + dispatch counters) —
while the serial ``apply``/``_dispatch`` path composes exactly the same
primitives inline, which is what makes pipelined results bit-identical
to serial ones. Owned-buffer contract: a staged tree handed to
``compute_staged`` is engine-private by construction (``host_stage``
wrote it, or the caller promised ``owned=True``) and is donated to XLA
where the backend supports it — callers must never reuse buffers they
passed with that promise.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.loadgen import faults
from keystone_tpu.observability import device as device_obs
from keystone_tpu.observability.tracing import get_tracer
from keystone_tpu.parallel import mesh as mesh_lib
from keystone_tpu.parallel.dataset import Dataset, _leading_dim
from keystone_tpu.serving.metrics import ServingMetrics

logger = logging.getLogger(__name__)

DEFAULT_BUCKETS = (8, 64, 512)


def _round_up(n: int, mult: int) -> int:
    return -(-n // mult) * mult


class _ParamBoundFn:
    """Adapts a two-argument ``(params, batch)`` program — what a
    model-sharded engine traces — to the engine's one-argument fn
    convention, binding the placed (sharded, committed) param tree.
    Every dispatch passes the same placed arrays; only the batch
    varies. ``lower`` delegates for the cost-model/AOT path, and the
    wrapped program may be a polymorphic jit fn OR a rigid stored
    ``jax.stages.Compiled`` (see ``_is_stored_executable``)."""

    def __init__(self, fn, params):
        self.fn = fn
        self.params = params

    def __call__(self, staged):
        return self.fn(self.params, staged)

    def lower(self, staged):
        return self.fn.lower(self.params, staged)


def _is_stored_executable(fn) -> bool:
    """True when ``fn`` dispatches a shape/dtype-RIGID stored
    executable (directly, or wrapped with its bound params) — the
    discriminator for the off-spec TypeError detour in
    ``compute_staged``."""
    inner = fn.fn if isinstance(fn, _ParamBoundFn) else fn
    return isinstance(inner, jax.stages.Compiled)


class CompiledPipeline:
    """A ``FittedPipeline`` behind a fixed set of compiled batch shapes.

    Parameters
    ----------
    pipeline:  the fitted (transformer-only) pipeline; its whole batched
               apply path must be traceable (array-mode nodes only —
               host-side items-mode nodes can't stage; use
               ``FittedPipeline.apply`` for those).
    buckets:   ascending row buckets; a batch of n rows dispatches the
               smallest bucket >= n, and batches larger than the biggest
               bucket are chunked through it.
    donate:    donate staged input buffers to XLA (auto-disabled on
               backends without donation support, e.g. CPU).
    shard:     place each staged batch over the mesh data axis
               (multi-chip serving). Buckets are rounded up to a
               multiple of the mesh's data-shard count so every shard
               gets equal rows.
    featurize: optional fitted featurize pipeline fused IN FRONT of
               ``pipeline`` inside every bucket program (device-side
               featurization): callers stage RAW examples (e.g. uint8
               images) and one compiled dispatch runs
               ``pipeline(featurize(raw))``. Must be traceable
               (array-mode, pure JAX) like ``pipeline`` itself; the
               AOT-store fingerprint covers it (one featurizer's
               cached executable can never serve another's).
    param_sharding: shard the MODEL over the mesh's model axis
               (serving/sharding.py): ``True`` resolves the default
               rule set against the pipeline's named params, a
               sequence of ``(regex, PartitionSpec)`` rules or a
               ``{name: spec}`` dict partitions explicitly. Params
               are placed once (sharded ``NamedSharding``) and become
               arguments of every bucket program, so each device
               holds only its shard — models bigger than one chip's
               HBM serve on the mesh. Buckets round up to the mesh's
               data-shard count exactly as under ``shard=`` (staged
               batches are mesh-placed either way).
               ``param_sharding_unmatched="replicate"`` downgrades
               unmatched-param errors to replication.
    """

    def __init__(
        self,
        pipeline,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        *,
        donate: bool = True,
        shard: bool = False,
        mesh=None,
        metrics: Optional[ServingMetrics] = None,
        name: Optional[str] = None,
        aot_store: Any = "auto",
        featurize: Any = None,
        param_sharding: Any = None,
        param_sharding_unmatched: str = "error",
    ):
        if not buckets:
            raise ValueError("need at least one bucket")
        if any(b <= 0 for b in buckets):
            raise ValueError(f"buckets must be positive: {buckets}")
        self.pipeline = pipeline
        self.featurize = featurize
        self.shard = shard
        self.mesh = mesh
        if shard or param_sharding:
            self.mesh = mesh or mesh_lib.current_mesh()
        # -- model axis: declarative param sharding over the mesh ------
        self._binder = None
        self.param_sharding: Optional[Dict[str, Any]] = None
        self._placed_params = None
        if param_sharding:
            from keystone_tpu.serving import sharding as sharding_lib

            self._binder = sharding_lib.ParamBinder(pipeline)
            self.param_sharding = sharding_lib.resolve_param_sharding(
                param_sharding, pipeline,
                params=self._binder.params,
                unmatched=param_sharding_unmatched,
            )
            shard_fns = sharding_lib.make_shard_fns(
                self.param_sharding, self.mesh
            )
            # placed ONCE: sharded committed arrays, reused as the
            # param argument of every bucket program's every dispatch
            self._placed_params = {
                name: fn(self._binder.params[name])
                for name, fn in shard_fns.items()
            }
        self.model_sharded = self._binder is not None
        # staged batches are mesh-placed whenever the engine is mesh-
        # anything: data-sharded batches for shard=, and mesh-committed
        # (data axis may be size 1) batches for model-sharded programs
        # so jit sees committed input shardings consistent with the
        # placed params
        self._place_batch = self.shard or self.model_sharded
        if self._place_batch:
            # every mesh-placed batch splits its leading axis over the
            # data axis — buckets must divide evenly whether the engine
            # shards rows, weights, or both (a model-sharded engine on
            # a mesh with a >1 data axis would otherwise fail every
            # device_put for the undivisible buckets)
            nshards = mesh_lib.n_data_shards(self.mesh)
            buckets = [_round_up(b, nshards) for b in buckets]
        self.buckets: Tuple[int, ...] = tuple(sorted(set(int(b) for b in buckets)))
        self.metrics = metrics if metrics is not None else ServingMetrics()
        # every engine is scrapeable: its per-bucket compile/dispatch
        # counters and latency quantiles export through the global
        # MetricsRegistry (weakref bridge — registration never extends
        # this engine's lifetime) under the `engine` label
        self.name = self.metrics.register(engine=name)
        # device truth for the MFU/roofline series: detected peaks of
        # the local device kind (None on unknown hardware -> those
        # series stay absent) scaled by the engine's device count
        devices = device_obs.device_table()
        peak_flops, peak_membw = (
            (devices[0]["peak_flops"],
             devices[0]["peak_membw_bytes_per_s"])
            if devices else (None, None)
        )
        n_devices = 1
        if (self.shard or self.model_sharded) and self.mesh is not None:
            # the engine's device set is the MESH, counted exactly once
            # whether rows, weights, or both are sharded over it — the
            # MFU denominator (peak x n_devices) must match what the
            # program actually runs on, and N lanes sharing one mesh
            # each count the mesh, never lanes x mesh
            n_devices = int(getattr(self.mesh.devices, "size", 1))
        self.metrics.set_device_peaks(
            peak_flops, peak_membw, n_devices=n_devices
        )
        self.donate = donate and jax.default_backend() in ("tpu", "gpu")
        # AOT serialized-executable store (serving/aot.py): "auto" =
        # the store setup_aot_cache configured for this process (None
        # when none was — the library/test default), None/False =
        # explicitly off, or a concrete AotStore. Engaged only at
        # warmup; apply()'s lazy-compile path never consults it.
        self._aot_store_cfg = aot_store
        # bucket -> {"status": "hit"|"saved"|"miss"|"error", ...} from
        # the last warmup that consulted the store
        self._aot: Dict[int, Dict[str, Any]] = {}
        # bucket -> polymorphic jit fallback created on demand when a
        # bucket's installed STORED executable (shape/dtype-rigid)
        # meets an off-spec input; the stored program keeps serving
        # on-spec traffic, the side fn serves the strays
        self._side_fns: Dict[int, Callable] = {}
        self._fns: Dict[int, Callable] = {}
        # a MicroBatcher worker and direct apply() callers may race to
        # create a bucket's jit fn; two fns would mean two traces, and
        # the <= len(buckets) compile bound is the subsystem's contract
        self._fn_lock = threading.Lock()

    # -- compiled-program management ---------------------------------------

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest bucket covering ``n`` rows (callers chunk above the
        largest bucket)."""
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"batch of {n} rows exceeds the largest bucket "
            f"{self.max_bucket}; chunk it (engine.apply does)"
        )

    def _make_jit(self, bucket: int) -> Callable:
        """A fresh polymorphic jit fn for ``bucket`` (shared builder of
        the dispatch table and the off-spec side path). With a fused
        featurize stage, the whole featurize∘model composition traces
        into ONE program — XLA fuses across the boundary and the cast
        from the raw wire dtype happens on device, inside it."""
        run = self.pipeline._batch_run
        feat_run = (
            self.featurize._batch_run
            if self.featurize is not None else None
        )
        metrics = self.metrics
        binder = self._binder

        if binder is not None:
            # model-sharded: params are explicit program ARGUMENTS —
            # jit reads their committed NamedShardings (and the staged
            # batch's mesh placement) and GSPMD partitions the program;
            # each device's executable holds only its weight shards
            def staged_sharded(params, arr):
                metrics.record_trace(bucket)
                if feat_run is not None:
                    arr = feat_run(arr)
                return binder.run(params, arr)

            return _ParamBoundFn(
                jax.jit(
                    staged_sharded,
                    donate_argnums=(1,) if self.donate else (),
                ),
                self._placed_params,
            )

        def staged(arr):
            # executes at TRACE time only — one increment per XLA
            # compile of this bucket, zero on compiled dispatches
            metrics.record_trace(bucket)
            if feat_run is not None:
                arr = feat_run(arr)
            return run(arr)

        return jax.jit(
            staged, donate_argnums=(0,) if self.donate else ()
        )

    def _fn(self, bucket: int) -> Callable:
        fn = self._fns.get(bucket)
        if fn is not None:
            return fn
        with self._fn_lock:
            fn = self._fns.get(bucket)
            if fn is not None:
                return fn
            fn = self._make_jit(bucket)
            self._fns[bucket] = fn
            return fn

    def _side_fn(self, bucket: int) -> Callable:
        """Polymorphic jit fallback for off-spec inputs on a bucket
        whose installed program is a rigid stored executable — created
        once per bucket, cached BESIDE (never instead of) it, so one
        stray request can't cost on-spec traffic its zero-compile
        program."""
        fn = self._side_fns.get(bucket)
        if fn is not None:
            return fn
        with self._fn_lock:
            fn = self._side_fns.get(bucket)
            if fn is None:
                fn = self._side_fns[bucket] = self._make_jit(bucket)
            return fn

    # -- staging -----------------------------------------------------------

    def _stage(
        self, tree: Any, rows: int, bucket: int, owned: bool = False
    ) -> Any:
        """Pad a pytree of row-major arrays up to ``bucket`` rows with
        zeros (valid by the Dataset zero-pad discipline) and place it.
        ``owned=True`` promises the buffers are engine/batcher-private
        (safe to donate without the protective copy)."""
        pad = bucket - rows

        def pad_leaf(a):
            # caller-owned only if it arrived as a device array; numpy
            # input becomes an engine-private buffer on the H2D transfer
            caller_owned = isinstance(a, jax.Array) and not owned
            a = jnp.asarray(a)
            if pad:
                return jnp.concatenate(
                    [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)]
                )
            if self.donate and caller_owned:
                # exact-size caller-owned buffer: copy so donation can't
                # invalidate an array the caller still holds
                return jnp.array(a, copy=True)
            return a

        staged = jax.tree_util.tree_map(pad_leaf, tree)
        if self._place_batch:
            staged = jax.tree_util.tree_map(
                lambda a: jax.device_put(
                    a, mesh_lib.data_sharding(self.mesh, ndim=a.ndim)
                ),
                staged,
            )
        return staged

    # -- pipeline stage primitives (serving/pipeline.py runs these on
    # -- separate threads; _dispatch composes them inline) ------------------

    def host_stage(self, tree: Any, rows: int, bucket: int, out: Any) -> Any:
        """HOST-side pad of a numpy pytree up to ``bucket`` rows with
        zeros — the pipelined host-prep stage. ``out`` is a matching
        pytree of preallocated ``(bucket, ...)`` buffers (the reusable
        staging pool): valid rows are copied in and the pad region
        zeroed, so steady-state windows allocate nothing on the host.
        Returns ``out``."""
        def fill_leaf(buf, a):
            np.copyto(buf[:rows], np.asarray(a))
            if bucket > rows:
                buf[rows:] = 0
            return buf

        return jax.tree_util.tree_map(fill_leaf, out, tree)

    def upload_staged(self, staged_host: Any) -> Any:
        """H2D placement of a host-staged (already padded) tree — the
        pipelined upload stage. Sharded engines place over the mesh
        data axis; the transfer is async (callers that need the host
        buffers back block on the returned arrays). The device buffers
        are engine-private (the transfer copies), so downstream compute
        may donate them."""
        if self._place_batch:
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(
                    a, mesh_lib.data_sharding(self.mesh, ndim=a.ndim)
                ),
                staged_host,
            )
        return jax.tree_util.tree_map(jax.device_put, staged_host)

    def compute_staged(self, staged: Any, rows: int, bucket: int) -> Any:
        """Dispatch the bucket's compiled program over an
        already-staged (padded + placed) tree and record the dispatch
        counters. ``staged`` must be engine-private — it is donated to
        XLA where the backend supports donation. Returns the full
        padded output (async; callers slice to ``rows`` valid rows and
        own the sync point)."""
        # chaos point: fail the whole window at dispatch (match:
        # engine=<name> to target one lane's engine). Serial apply and
        # the pipelined compute stage both pass through here, so the
        # experiment exercises whichever path traffic does. Unarmed:
        # the armed() gate keeps this a no-op (no ctx dict built).
        if faults.armed() and faults.fire(
            "engine.dispatch.error", {"engine": self.name}
        ) is not None:
            raise faults.FaultInjected(
                "engine.dispatch.error", engine=self.name, bucket=bucket
            )
        # the wire-bytes fact: what this dispatch actually shipped to
        # the device (padded rows included — padding is real traffic on
        # the H2D path). nbytes is array METADATA (shape × itemsize),
        # not a device read, so this stays sync-free; device-featurize
        # engines stage raw uint8 here and the counter is how the ~4×
        # reduction over f32 features becomes a scraped fact.
        h2d_bytes = sum(
            int(getattr(a, "nbytes", 0))
            for a in jax.tree_util.tree_leaves(staged)
        )
        fn = self._fn(bucket)
        try:
            out = fn(staged)
        except TypeError:
            # a stored executable (jax.stages.Compiled) is shape/
            # dtype-RIGID where a jit fn is polymorphic: an off-spec
            # input (an x64-enabled caller, an integer feature batch)
            # would trace its own program on a cold engine but raises
            # here. Match the cold engine exactly: the installed
            # executable KEEPS serving on-spec traffic (its
            # zero-compile program is the whole feature — one stray
            # request must not cost everyone a mid-serving retrace),
            # and this request detours through a side jit fn that
            # traces per-aval just like a cold engine's would. A
            # TypeError from a plain jit fn means the REQUEST itself
            # is malformed — that propagates unchanged.
            if not _is_stored_executable(fn):
                raise
            report = self._aot.setdefault(bucket, {})
            if not report.get("off_spec"):
                # once per bucket, not once per request: a persistently
                # off-spec client must not flood the log at line rate
                report["off_spec"] = True
                logger.warning(
                    "engine %s: bucket %d saw input off the stored "
                    "executable's spec; such requests serve via a "
                    "side jit path", self.name, bucket,
                )
            out = self._side_fn(bucket)(staged)
        self.metrics.record_dispatch(bucket, rows, h2d_bytes=h2d_bytes)
        return out

    # -- serving entry points ----------------------------------------------

    def apply(
        self, data: Any, sync: bool = False, owned: bool = False
    ) -> Any:
        """Serve one batch: pad to the covering bucket (chunking through
        the largest bucket when oversized), dispatch the compiled
        program(s), and return outputs sliced to the valid rows.

        ``data`` is a Dataset, an array, or a pytree of arrays sharing a
        leading example axis. ``sync=True`` blocks until the whole
        result is ready (one host sync, after every chunk is
        enqueued). ``owned=True`` asserts the input buffers belong to
        the engine's caller-of-record (MicroBatcher, warmup) and may be
        donated without the exact-bucket-size protective copy — don't
        pass it for arrays you still need."""
        if isinstance(data, Dataset):
            rows = data.n
            tree = data.array()
        else:
            tree = data
            rows = _leading_dim(tree)
        if rows == 0:
            raise ValueError("cannot serve an empty batch")
        outs: List[Any] = []
        # when chunking happened every slice is a strict subrange —
        # always a fresh engine-private buffer, safe to donate without
        # the protective copy; only the single-chunk identity slice can
        # alias the caller's array
        chunk_owned = owned or rows > self.max_bucket
        t0 = time.perf_counter()
        start = 0
        while start < rows:
            take = min(self.max_bucket, rows - start)
            chunk = jax.tree_util.tree_map(
                lambda a: a[start : start + take], tree
            )
            # every chunk enqueues async — staging chunk k+1 overlaps
            # execution of chunk k; the one host sync comes at the end
            outs.append(self._dispatch(chunk, take, owned=chunk_owned))
            start += take
        result = outs[0] if len(outs) == 1 else jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *outs
        )
        if sync:
            jax.block_until_ready(result)
            # the completion-timed dispatch number: this sync is the
            # first point the device work is provably done (the
            # per-chunk timer above stops at enqueue — execution is
            # async past the compiled call, so that number alone
            # under-reported device time; it survives as the separate
            # dispatch_enqueue series). Async callers own their sync
            # point and record nothing here; the pipelined compute
            # stage records its own completion number per window.
            self.metrics.record_dispatch_complete(
                time.perf_counter() - t0
            )
        return result

    def _dispatch(self, chunk: Any, rows: int, owned: bool = False) -> Any:
        bucket = self.bucket_for(rows)
        with get_tracer().span(
            "serving.dispatch", engine=self.name, bucket=bucket, rows=rows
        ):
            t0 = time.perf_counter()
            staged = self._stage(chunk, rows, bucket, owned=owned)
            out = self.compute_staged(staged, rows, bucket)
            valid = jax.tree_util.tree_map(lambda a: a[:rows], out)
            self.metrics.record_dispatch_enqueue(
                time.perf_counter() - t0
            )
        return valid

    def warmup(
        self,
        example: Any = None,
        batch: Any = None,
        buckets: Optional[Sequence[int]] = None,
    ) -> Dict[int, float]:
        """Compile every bucket up front (zero cold compiles at traffic
        time; with the persistent compilation cache wired — see
        ``parallel.runtime.setup_compilation_cache`` — a restarted
        server replays these compiles from disk).

        The per-example shape/dtype spec comes from ``example`` (a
        pytree for ONE example, no leading axis) or ``batch`` (a pytree
        WITH a leading axis, e.g. any representative request). Returns
        bucket -> compile wall seconds."""
        if (example is None) == (batch is None):
            raise ValueError("pass exactly one of example= or batch=")
        if batch is not None and isinstance(batch, Dataset):
            batch = batch.array()
        leaves, treedef = jax.tree_util.tree_flatten(
            batch if batch is not None else example
        )
        drop = 1 if batch is not None else 0
        specs = [
            (jnp.asarray(a).shape[drop:], jnp.asarray(a).dtype)
            for a in leaves
        ]
        want = list(buckets) if buckets is not None else list(self.buckets)
        unknown = [b for b in want if b not in self.buckets]
        if unknown:  # validate BEFORE compiling anything: a bad bucket
            # late in the list must not leave a half-warmed engine
            raise ValueError(
                f"unknown bucket(s) {unknown} (have {self.buckets})"
            )
        store = self._resolve_aot_store()
        token = feat_token = shard_token = identity = None
        if store is not None:
            from keystone_tpu.serving import aot as aot_lib

            try:
                # all warmup-invariant: hash the model (and the fused
                # featurize stage, when one is configured — its
                # parameters are constants inside the serialized
                # program exactly like the model's weights, so one
                # featurizer's cached executable must never serve
                # another's) and probe the runtime once, not per bucket
                token = aot_lib.pipeline_token(self.pipeline)
                if self.featurize is not None:
                    feat_token = aot_lib.pipeline_token(self.featurize)
                if self.model_sharded:
                    # the partitioning + mesh topology are part of the
                    # program: a mesh-sharded executable must never
                    # share an entry with a replicated one, nor with a
                    # different spec tree or mesh shape
                    from keystone_tpu.serving import (
                        sharding as sharding_lib,
                    )

                    shard_token = sharding_lib.sharding_token(
                        self.param_sharding, self.mesh
                    )
                identity = aot_lib.runtime_identity()
            except Exception:
                # a pipeline whose operators can't be fingerprinted
                # must warm exactly like one with no store configured
                # (absent-not-broken): counted, logged, compiled
                store.record_error()
                logger.info(
                    "aot: could not fingerprint the pipeline; warming "
                    "without the store", exc_info=True,
                )
                store = None
        times: Dict[int, float] = {}
        for b in want:
            zeros = treedef.unflatten(
                [jnp.zeros((b,) + s, d) for s, d in specs]
            )
            key = meta = None
            if store is not None:
                key, meta = aot_lib.bucket_key(
                    specs, self.buckets, b,
                    donate=self.donate, shard=self.shard,
                    model_token=token, identity=identity,
                    featurize_token=feat_token,
                    sharding_token=shard_token,
                    # a namespaced store (the model zoo's per-model
                    # view) partitions its entries; plain stores keep
                    # their pre-zoo fingerprints byte-identical
                    namespace=getattr(store, "namespace", None),
                )
                # the zero-cold-start path: install the serialized
                # executable BEFORE any trace of this bucket can
                # happen; any miss/mismatch/deserialize failure falls
                # through (counted) to the normal compile path below
                load_s = self._try_install_aot(store, key, meta, b, zeros)
                if load_s is not None:
                    times[b] = load_s
                    continue
            fn = self._fn(b)
            staged = self._stage(zeros, b, b, owned=True)
            # outside the timed window: the returned numbers are the
            # dispatch's compile wall, not cost-model extraction
            compiled = self._register_cost_model(
                b, fn, staged, want_executable=store is not None
            )
            if store is not None and compiled is not None:
                # populate the store so the NEXT process (or the
                # autoscaler's next-generation engine) starts hot
                if store.save(key, compiled, meta) is not None:
                    if self._aot.get(b, {}).get("status") == "error":
                        # the report keeps the error visible (a broken
                        # entry was REPLACED, not cleanly created)
                        self._aot[b]["fallback"] = "saved"
                    else:
                        self._aot[b] = {"status": "saved"}
            t0 = time.perf_counter()
            out = fn(staged)
            jax.block_until_ready(out)
            times[b] = time.perf_counter() - t0
        return times

    # -- AOT executable cache (serving/aot.py) ------------------------------

    def _resolve_aot_store(self):
        """The store warmup consults: the process-configured one for
        the default ``"auto"``, None when disabled, or the explicit
        ``AotStore`` the caller passed."""
        if self._aot_store_cfg in (None, False):
            return None
        if self._aot_store_cfg == "auto":
            from keystone_tpu.serving import aot as aot_lib

            return aot_lib.configured_store()
        return self._aot_store_cfg

    def _try_install_aot(self, store, key, meta, bucket, zeros):
        """Deserialize + install one bucket's stored executable and
        VALIDATE it with one real dispatch. Returns the install wall
        seconds on success, None on miss/error (the caller falls back
        to the compile path). Never raises — absent-not-broken is the
        serving-path contract."""
        t0 = time.perf_counter()
        loaded, outcome = store.load(key, meta)
        if loaded is None:
            # "miss" (no entry) or "error" (corrupt/mismatched entry) —
            # the report must tell the same story the store counters do
            self._aot[bucket] = {"status": outcome}
            return None
        if self.model_sharded:
            # a model-sharded bucket program was serialized as the
            # two-argument (params, batch) executable; re-bind this
            # engine's placed params so it dispatches under the
            # engine's one-argument convention
            loaded = _ParamBoundFn(loaded, self._placed_params)
        try:
            # validate BEFORE publishing into _fns: warmup is callable
            # on an engine already taking traffic, and a concurrent
            # dispatcher must never be able to pick up an executable
            # that hasn't survived one real dispatch
            staged = self._stage(zeros, bucket, bucket, owned=True)
            out = loaded(staged)
            jax.block_until_ready(out)
        except Exception:
            # an entry that deserializes but won't run is as broken as
            # a corrupt one: leave dispatch to trace normally
            store.record_error()
            self._aot[bucket] = {"status": "error"}
            logger.info(
                "aot: stored executable for bucket %d failed to "
                "execute; recompiling", bucket, exc_info=True,
            )
            return None
        with self._fn_lock:
            self._fns[bucket] = loaded
        self._register_cost_model_from(
            bucket,
            loaded.fn if isinstance(loaded, _ParamBoundFn) else loaded,
        )
        secs = time.perf_counter() - t0
        # only a VALIDATED install counts as a hit, and the histogram
        # gets the full deserialize+validate+install wall
        store.record_hit(secs)
        self._aot[bucket] = {
            "status": "hit", "load_s": round(secs, 6),
        }
        return secs

    def aot_report(self) -> Dict[int, Dict[str, Any]]:
        """Per-bucket outcome of the AOT-store pass (empty when no
        store was configured): ``hit`` (installed from the store —
        zero trace, zero compile), ``saved`` (compiled normally,
        executable serialized for the next process), ``miss`` (no
        entry, compiled normally), ``error`` (entry present but
        unusable — corrupt, mismatched, or failed its validation
        dispatch — compiled normally; ``fallback: "saved"`` when the
        recompile also repaired the store entry). A hit stays a hit
        even if off-spec inputs later arrive: those detour through a
        side jit fn while the stored executable keeps serving on-spec
        traffic (see ``dispatch``'s TypeError handling)."""
        return {b: dict(v) for b, v in self._aot.items()}

    def _register_cost_model(
        self, bucket: int, fn, staged, want_executable: bool = False
    ):
        """Pull the bucket program's static XLA cost model — FLOPs,
        bytes accessed — and register it on the metrics (the
        MFU/roofline/goodput input). Returns the AOT-compiled
        ``jax.stages.Compiled`` when one was produced (the AOT store's
        save input), else None.

        Reads ``fn.lower(staged).cost_analysis()``: lowering shares the
        jit TRACE cache (the compile-count contract holds, and the
        ``fn(staged)`` dispatch that follows retraces nothing) and the
        analysis runs on the lowered module — no XLA compile. The AOT
        *executable* cache is NOT shared with the jit dispatch path
        (measured: an ``lower().compile()`` here would compile every
        bucket twice), so the executable — which also carries
        ``memory_analysis()``'s temp-HBM number — is built only when
        the persistent compilation cache is configured (the dispatch's
        own compile then replays from disk instead of paying the
        program twice) or when the caller needs it for the AOT store
        (``want_executable``; the store's whole point is that the
        NEXT process pays nothing, so this one eating a cache-cold
        double compile once at build time is the documented price —
        ``serve-aot-build`` configures the compile cache to avoid even
        that). Best-effort by design: backends whose lowering or
        analyses fail (or report nothing) leave the model ABSENT —
        serving and the scrape surface must work identically without
        it."""
        compiled = None
        try:
            lowered = fn.lower(staged)
            # the executable is built BEFORE any cost-model extraction:
            # the store-save path must get its Compiled even if a
            # metrics-side analysis were ever to fail (compiled rides
            # the assignment out through the except)
            if want_executable or getattr(
                jax.config, "jax_compilation_cache_dir", None
            ):
                compiled = lowered.compile()
            model = device_obs.compiled_cost_model(lowered)
            if compiled is not None:
                model.update(device_obs.compiled_cost_model(compiled))
            self.metrics.set_cost_model(bucket, model)
        except Exception:
            logger.debug(
                "no AOT cost analysis for bucket %d", bucket, exc_info=True
            )
        return compiled

    def _register_cost_model_from(self, bucket: int, compiled) -> None:
        """Cost model off an already-loaded executable (the AOT-store
        hit path — there is no Lowered to analyze). Same best-effort
        contract as ``_register_cost_model``."""
        try:
            self.metrics.set_cost_model(
                bucket, device_obs.compiled_cost_model(compiled)
            )
        except Exception:
            logger.debug(
                "no cost analysis from the stored executable for "
                "bucket %d", bucket, exc_info=True,
            )

    __call__ = apply
