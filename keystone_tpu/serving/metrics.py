"""Serving observability.

One ``ServingMetrics`` instance rides along with each engine (and is
shared with its ``MicroBatcher``): per-bucket XLA compile counts — the
number the bucketed design exists to bound — per-bucket dispatch
counts, padded-vs-valid example counts (padding waste), dispatch and
end-to-end request latency percentiles, and a queue-depth gauge.

Built on the generic ``Counter`` / ``LatencyRecorder`` primitives in
``utils/profiling.py`` so the same machinery serves training-side
instrumentation.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from keystone_tpu.utils.profiling import Counter, LatencyRecorder


class ServingMetrics:
    def __init__(self, latency_window: int = 4096):
        # bucket -> number of XLA traces (each trace = one compile)
        self.compiles = Counter()
        # bucket -> number of compiled-program dispatches
        self.dispatches = Counter()
        # valid examples served / padded rows shipped (waste tracking)
        self.examples = Counter()
        self.padded_rows = Counter()
        # wall time of engine dispatches: pad/placement + compiled-call
        # ENQUEUE (execution is async; apply(sync=True) blocks once at
        # the end, outside this number), plus trace+compile on a
        # bucket's FIRST dispatch (warmup moves that cost out of the
        # traffic distribution). End-to-end serving latency lives in
        # request_latency and in the bench's own wall timers.
        self.dispatch_latency = LatencyRecorder(latency_window)
        # enqueue-to-future-resolution time of micro-batched requests
        self.request_latency = LatencyRecorder(latency_window)
        self._queue_depth = 0
        self._coalesced_max = 0
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    # -- engine-side hooks -------------------------------------------------

    def record_trace(self, bucket: int) -> None:
        self.compiles.inc(bucket)

    def record_dispatch(
        self, bucket: int, n_valid: int, seconds: float
    ) -> None:
        self.dispatches.inc(bucket)
        self.examples.inc(None, n_valid)
        self.padded_rows.inc(None, bucket - n_valid)
        self.dispatch_latency.record(seconds)

    # -- batcher-side hooks ------------------------------------------------

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self._queue_depth = depth

    def record_coalesce(self, size: int) -> None:
        with self._lock:
            self._coalesced_max = max(self._coalesced_max, size)

    def record_request(self, seconds: float) -> None:
        self.request_latency.record(seconds)

    # -- queries -----------------------------------------------------------

    @property
    def compile_count(self) -> int:
        return self.compiles.total

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return self._queue_depth

    @property
    def max_coalesced(self) -> int:
        with self._lock:
            return self._coalesced_max

    def examples_per_sec(self) -> float:
        """LIFETIME average (examples since construction / wall time
        since construction) — it decays over idle periods and includes
        warmup, so it's a capacity sanity number, not an instantaneous
        throughput gauge. Benches that need a true rate time their own
        window (serving/bench.py does)."""
        dt = time.perf_counter() - self._t0
        return self.examples.total / dt if dt > 0 else 0.0

    def summary(self) -> Dict:
        """Flat dict suitable for a bench row's ``extra`` or a log line."""

        def ms(v: Optional[float]) -> Optional[float]:
            return round(v * 1e3, 3) if v is not None else None

        return {
            "compiles_per_bucket": {
                str(k): v for k, v in sorted(self.compiles.snapshot().items())
            },
            "dispatches_per_bucket": {
                str(k): v
                for k, v in sorted(self.dispatches.snapshot().items())
            },
            "examples": self.examples.total,
            "padded_rows": self.padded_rows.total,
            "examples_per_sec_lifetime": round(self.examples_per_sec(), 1),
            "dispatch_p50_ms": ms(self.dispatch_latency.p50),
            "dispatch_p99_ms": ms(self.dispatch_latency.p99),
            "request_p50_ms": ms(self.request_latency.p50),
            "request_p99_ms": ms(self.request_latency.p99),
            "queue_depth": self.queue_depth,
            "max_coalesced": self.max_coalesced,
        }
