"""Serving observability.

One ``ServingMetrics`` instance rides along with each engine (and is
shared with its ``MicroBatcher``): per-bucket XLA compile counts — the
number the bucketed design exists to bound — per-bucket dispatch
counts, padded-vs-valid example counts (padding waste), the observed
per-request size histogram (what the bucket autoscaler reads), dispatch
and end-to-end request latency percentiles, and a queue-depth gauge.

Device truth rides on the same instance: ``CompiledPipeline.warmup``
registers each bucket program's XLA cost model (``set_cost_model``:
FLOPs, bytes accessed, temp HBM from ``lower().compile()``'s
``cost_analysis``/``memory_analysis``), and every dispatch then
attributes *modeled device work* — goodput rows vs padded rows per
bucket, modeled FLOPs — to the traffic that caused it. Combined with
the detected per-device-kind peaks (``observability/device.py``,
injected via ``set_device_peaks``) that yields the rolling **MFU**
gauge (modeled FLOP/s over peak FLOP/s, the PaLM-report convention)
and a per-bucket **roofline** classification (arithmetic intensity vs
the device's FLOPs/byte ridge point: compute-bound or
bandwidth-bound). Backends that report no cost analysis degrade to
ABSENT series — never zeros, never errors (the CPU CI contract).

Pipelined-lane serving (``serving/pipeline.py``) adds per-stage series:
a seconds recorder per stage (``host_prep``/``upload``/``compute``/
``deliver``), per-stage handoff-queue depth gauges, a windows-completed
counter, and the derived *bottleneck attribution* — the stage whose
standalone rate (1 / mean stage seconds) is lowest, computed exactly
the way the streaming featurize bench attributes its decode/upload/
compute bottleneck — plus ``overlap_efficiency`` = sustained window
rate over that bottleneck stage's rate (≈1.0 means the lane loses
nothing to serialization; meaningful under saturation, it decays with
idle gaps like every windowed rate here).

Built on the generic ``Counter`` / ``LatencyRecorder`` primitives in
``utils/profiling.py`` so the same machinery serves training-side
instrumentation — and bridged into the process-global
``MetricsRegistry`` (``register()``; ``CompiledPipeline`` does this on
construction) so the admin endpoint's ``/metrics`` exports every
engine's counters under an ``engine`` label. The bridge holds only a
weakref: an engine going out of scope unregisters itself at the next
scrape.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
import weakref
from typing import Deque, Dict, Optional, Tuple

from keystone_tpu.utils.profiling import Counter, LatencyRecorder

# default sliding window of the instantaneous throughput gauge
RATE_WINDOW_S = 30.0

# the staged lane pipeline's stages, in flow order (serving/pipeline.py);
# bottleneck attribution ranges over these
PIPELINE_STAGES = ("host_prep", "upload", "compute", "deliver")

_engine_ids = itertools.count()


class ServingMetrics:
    def __init__(
        self, latency_window: int = 4096, clock=time.perf_counter
    ):
        # every windowed-rate gauge reads this clock; tests inject a
        # fake to make "a window elapsed" a statement instead of a
        # sleep (the real-sleep versions divided by tiny lifetimes and
        # flaked whenever a loaded CI host stretched the gap between
        # record and read)
        self._clock = clock
        # bucket -> number of XLA traces (each trace = one compile)
        self.compiles = Counter()
        # bucket -> number of compiled-program dispatches
        self.dispatches = Counter()
        # goodput accounting, PER BUCKET: valid examples served vs
        # padded rows shipped (cells keyed by bucket; ``.total`` is the
        # engine-wide number the summary/bench read)
        self.examples = Counter()
        self.padded_rows = Counter()
        # bytes actually staged to the device, per bucket (padding
        # included — padding rides the H2D path like any row). The
        # device-featurize win — raw uint8 on the wire instead of f32
        # features — is this counter's ratio, not a claim.
        self.h2d_bytes = Counter()
        # bucket -> static XLA cost model ({flops, bytes_accessed,
        # temp_bytes, ...}), registered once at warmup by
        # CompiledPipeline; absent on backends without cost analysis
        self.cost_models: Dict[int, Dict[str, float]] = {}
        # modeled device FLOPs dispatched (lifetime; absent until a
        # cost model exists for a dispatched bucket)
        self.device_flops = Counter()
        # detected device peaks (observability/device.py); None =
        # unknown hardware -> MFU/roofline series stay absent
        self._peak_flops: Optional[float] = None
        self._peak_membw: Optional[float] = None
        self._n_devices: int = 1
        # live host staging-buffer bytes (HostBufferPool); None until a
        # pipelined lane runs — the PR 6 stale-gauge incident class
        self._staging_bytes: Optional[int] = None  # guarded-by: _lock
        # valid-row count of each dispatch (the observed request-size
        # histogram serving/autoscale.py proposes bucket sets from)
        self.request_sizes = Counter()
        # COMPLETION-timed dispatch wall time: staging through the
        # compiled program's results being ready, recorded at an
        # explicit sync point (``apply(sync=True)`` / the pipelined
        # compute stage). The old enqueue-only number under-reported
        # device time (execution is async past the compiled call);
        # it survives as its own series below.
        self.dispatch_latency = LatencyRecorder(latency_window)
        # ENQUEUE-only dispatch time: pad/placement + compiled-call
        # dispatch, excluding device execution (plus trace+compile on a
        # bucket's FIRST dispatch; warmup moves that out of traffic).
        self.dispatch_enqueue_latency = LatencyRecorder(latency_window)
        # staged-lane pipeline stage seconds (busy time per window per
        # stage) + per-stage handoff-queue depths + completed windows
        self.stage_seconds: Dict[str, LatencyRecorder] = {
            s: LatencyRecorder(latency_window) for s in PIPELINE_STAGES
        }
        self.windows = Counter()
        self._stage_queue_depth: Dict[str, int] = {}  # guarded-by: _lock
        # (timestamp,) per completed pipeline window, pruned like
        # _rate_events: the sustained-window-rate input of the
        # overlap-efficiency gauge
        self._window_events: Deque[float] = (
            collections.deque()
        )  # guarded-by: _lock
        # enqueue-to-future-resolution time of micro-batched requests
        self.request_latency = LatencyRecorder(latency_window)
        self._queue_depth = 0  # guarded-by: _lock
        self._coalesced_max = 0  # guarded-by: _lock
        # (timestamp, valid, padded, modeled flops) per dispatch,
        # pruned to the rate window: the windowed examples/sec,
        # padding-efficiency, and MFU gauges all read this, so idle
        # periods decay to zero instead of diluting a lifetime average
        self._rate_events: Deque[
            Tuple[float, int, int, float]
        ] = collections.deque()  # guarded-by: _lock
        self._lock = threading.Lock()
        self._t0 = self._clock()
        # optional per-model attribution binding
        # (observability/attribution.EngineAttribution): every dispatch
        # fact recorded here is mirrored into the model-labeled ledger,
        # fair-split over shared engines. None (the default) keeps the
        # hot path untouched — one attribute check per dispatch.
        self._attribution = None

    # -- engine-side hooks -------------------------------------------------

    def attach_attribution(self, binding) -> None:
        """Mirror this engine's dispatch facts into a per-model cost
        ledger (``observability/attribution.EngineAttribution``)."""
        self._attribution = binding

    def record_trace(self, bucket: int) -> None:
        self.compiles.inc(bucket)

    def record_dispatch(
        self,
        bucket: int,
        n_valid: int,
        seconds: Optional[float] = None,
        h2d_bytes: Optional[int] = None,
    ) -> None:
        """One compiled-program dispatch: counters + rate events.
        ``seconds``, when given, is a completion-timed wall number and
        feeds ``dispatch_latency`` directly (callers that only know the
        enqueue time use ``record_dispatch_enqueue`` and record the
        completion number at their sync point). ``h2d_bytes`` is the
        staged input tree's byte footprint — what this dispatch shipped
        host-to-device, padding included."""
        padded = bucket - n_valid
        self.dispatches.inc(bucket)
        self.examples.inc(bucket, n_valid)
        self.padded_rows.inc(bucket, padded)
        if h2d_bytes:
            self.h2d_bytes.inc(bucket, int(h2d_bytes))
        self.request_sizes.inc(n_valid)
        # modeled device work for this dispatch: the bucket program's
        # static cost is paid whether rows are valid or padding
        flops = self.cost_models.get(bucket, {}).get("flops", 0.0)
        if flops:
            self.device_flops.inc(None, flops)
        if seconds is not None:
            self.dispatch_latency.record(seconds)
        if self._attribution is not None:
            self._attribution.on_dispatch(
                bucket, n_valid, padded, flops, seconds, h2d_bytes
            )
        now = self._clock()
        with self._lock:
            self._rate_events.append((now, n_valid, padded, flops))
            cutoff = now - RATE_WINDOW_S
            while self._rate_events and self._rate_events[0][0] < cutoff:
                self._rate_events.popleft()

    def record_dispatch_enqueue(self, seconds: float) -> None:
        """Pad/placement + compiled-call dispatch time (no execution)."""
        self.dispatch_enqueue_latency.record(seconds)

    def record_dispatch_complete(self, seconds: float) -> None:
        """Completion-timed dispatch wall time, recorded at the sync
        point where the dispatched results became ready."""
        self.dispatch_latency.record(seconds)
        if self._attribution is not None:
            self._attribution.on_complete(seconds)

    # -- device-truth hooks (engine warmup / observability.device) ---------

    def set_cost_model(self, bucket: int, model: Dict[str, float]) -> None:
        """Register one bucket program's static XLA cost model
        (``CompiledPipeline.warmup`` calls this with the normalized
        ``cost_analysis``/``memory_analysis`` output). Empty models are
        dropped — absence of cost analysis must yield absent series."""
        if model:
            self.cost_models[int(bucket)] = dict(model)

    def set_device_peaks(
        self,
        peak_flops: Optional[float],
        peak_membw: Optional[float] = None,
        n_devices: int = 1,
    ) -> None:
        """Detected hardware peaks (``observability/device.peaks_for``)
        — the MFU denominator and the roofline ridge point. None means
        unknown hardware: the derived series stay absent."""
        self._peak_flops = peak_flops
        self._peak_membw = peak_membw
        self._n_devices = max(1, int(n_devices))

    def set_staging_bytes(self, nbytes: int) -> None:
        """Live host staging-buffer footprint (``HostBufferPool``)."""
        with self._lock:
            self._staging_bytes = int(nbytes)

    # -- pipeline-side hooks (serving/pipeline.py) -------------------------

    def record_stage(self, stage: str, seconds: float) -> None:
        rec = self.stage_seconds.get(stage)
        if rec is not None:
            rec.record(seconds)

    def set_stage_queue_depth(self, stage: str, depth: int) -> None:
        with self._lock:
            self._stage_queue_depth[stage] = depth

    def record_window(self) -> None:
        """One pipelined window fully delivered."""
        self.windows.inc(None)
        now = self._clock()
        with self._lock:
            self._window_events.append(now)
            cutoff = now - RATE_WINDOW_S
            while self._window_events and self._window_events[0] < cutoff:
                self._window_events.popleft()

    # -- batcher-side hooks ------------------------------------------------

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self._queue_depth = depth

    def record_coalesce(self, size: int) -> None:
        with self._lock:
            self._coalesced_max = max(self._coalesced_max, size)

    def record_request(self, seconds: float) -> None:
        self.request_latency.record(seconds)

    # -- queries -----------------------------------------------------------

    @property
    def compile_count(self) -> int:
        return self.compiles.total

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return self._queue_depth

    @property
    def max_coalesced(self) -> int:
        with self._lock:
            return self._coalesced_max

    def examples_per_sec(self, window: float = RATE_WINDOW_S) -> float:
        """Windowed throughput: examples dispatched over the last
        ``window`` seconds (clamped to the instance's lifetime so a
        young engine isn't over-divided, and to ``RATE_WINDOW_S`` —
        events older than that are pruned at record time, so a larger
        window would silently divide a 30s sum by more than 30s). This
        is the gauge ``summary()`` and ``/metrics`` export — unlike the
        lifetime average it goes to zero when traffic stops instead of
        decaying slowly forever."""
        now = self._clock()
        window = min(window, RATE_WINDOW_S, max(now - self._t0, 1e-9))
        cutoff = now - window
        with self._lock:
            served = sum(
                ev[1] for ev in self._rate_events if ev[0] >= cutoff
            )
        return served / window

    def padding_efficiency(
        self, window: float = RATE_WINDOW_S
    ) -> Optional[float]:
        """Windowed goodput fraction: valid rows over all rows shipped
        (valid + padding) across the dispatches of the last ``window``
        seconds. The LIVE counterpart of the offline
        ``autoscale.padding_waste`` estimate — what actually went over
        the wire, not what the histogram model predicts. None with no
        dispatches in the window (absent gauge, not a fake 1.0)."""
        now = self._clock()
        window = min(window, RATE_WINDOW_S, max(now - self._t0, 1e-9))
        cutoff = now - window
        with self._lock:
            valid = padded = 0
            for ev in self._rate_events:
                if ev[0] >= cutoff:
                    valid += ev[1]
                    padded += ev[2]
        total = valid + padded
        return valid / total if total else None

    def flops_per_sec(self, window: float = RATE_WINDOW_S) -> float:
        """Windowed modeled device FLOP/s (zero until a dispatched
        bucket has a registered cost model)."""
        now = self._clock()
        window = min(window, RATE_WINDOW_S, max(now - self._t0, 1e-9))
        cutoff = now - window
        with self._lock:
            flops = sum(
                ev[3] for ev in self._rate_events if ev[0] >= cutoff
            )
        return flops / window

    def mfu(self, window: float = RATE_WINDOW_S) -> Optional[float]:
        """Rolling model FLOPs utilization: windowed modeled FLOP/s
        over the device set's peak FLOP/s (the PaLM-report convention).
        None when the hardware peak is unknown or no dispatched bucket
        carries a cost model — absent series, never a made-up zero."""
        if not self._peak_flops or not self.cost_models:
            return None
        return self.flops_per_sec(window) / (
            self._peak_flops * self._n_devices
        )

    def roofline_bound(self, bucket: int) -> Optional[str]:
        """``"compute"`` or ``"bandwidth"`` for one bucket program:
        arithmetic intensity (modeled FLOPs per byte accessed) above or
        below the device's ridge point (peak FLOP/s over peak memory
        bandwidth). None without a cost model or known peaks."""
        model = self.cost_models.get(bucket)
        if (
            not model
            or not self._peak_flops
            or not self._peak_membw
            or not model.get("bytes_accessed")
            or "flops" not in model
        ):
            return None
        intensity = model["flops"] / model["bytes_accessed"]
        ridge = self._peak_flops / self._peak_membw
        return "compute" if intensity >= ridge else "bandwidth"

    @property
    def staging_bytes(self) -> Optional[int]:
        with self._lock:
            return self._staging_bytes

    # -- pipeline attribution (the streaming bench's model, per lane) ------

    def stage_rates(self) -> Dict[str, float]:
        """Windows/sec each stage could sustain STANDALONE, from its
        mean busy seconds per window (1 / mean) — the per-lane analogue
        of the streaming featurize bench's standalone stage probes."""
        rates: Dict[str, float] = {}
        for stage, rec in self.stage_seconds.items():
            snap = rec.snapshot()
            if snap["count"] and snap["total"] > 0:
                rates[stage] = snap["count"] / snap["total"]
        return rates

    def bottleneck(self) -> Optional[Tuple[str, float]]:
        """``(stage, rate)`` of the slowest stage — the same min-rate
        attribution the streaming bench reports as ``bottleneck`` —
        or None before any pipelined window ran."""
        rates = self.stage_rates()
        if not rates:
            return None
        stage = min(rates, key=rates.get)
        return stage, rates[stage]

    def windows_per_sec(self, window: float = RATE_WINDOW_S) -> float:
        """Sustained pipelined-window completion rate (windowed like
        ``examples_per_sec``)."""
        now = self._clock()
        window = min(window, RATE_WINDOW_S, max(now - self._t0, 1e-9))
        cutoff = now - window
        with self._lock:
            n = sum(1 for t in self._window_events if t >= cutoff)
        return n / window

    def overlap_efficiency(self) -> Optional[float]:
        """Sustained window rate over the bottleneck stage's standalone
        rate: ~1.0 means the lane pipeline loses nothing to
        serialization (can exceed 1.0 — stages measured under overlap
        run slower than they would standalone, making the model
        conservative, exactly like the streaming bench's caveat).
        Meaningful under saturation; decays toward 0 over idle gaps."""
        bn = self.bottleneck()
        if bn is None or bn[1] <= 0:
            return None
        return self.windows_per_sec() / bn[1]

    def pipeline_report(self) -> Optional[Dict]:
        """Per-stage seconds/rates + bottleneck attribution + overlap
        efficiency for this lane (None before any pipelined window)."""
        if not self.windows.total:
            return None
        rates = self.stage_rates()
        stages = {}
        for stage, rec in self.stage_seconds.items():
            snap = rec.snapshot()
            if not snap["count"]:
                continue
            stages[stage] = {
                "mean_ms": round(
                    snap["total"] / snap["count"] * 1e3, 3
                ),
                "p99_ms": round(snap["p99"] * 1e3, 3)
                if snap["p99"] is not None else None,
                "rate_per_s": round(rates.get(stage, 0.0), 1),
            }
        bn = self.bottleneck()
        eff = self.overlap_efficiency()
        with self._lock:
            queue_depths = dict(self._stage_queue_depth)
        return {
            "windows": self.windows.total,
            "windows_per_sec": round(self.windows_per_sec(), 2),
            "stages": stages,
            "stage_queue_depths": queue_depths,
            "bottleneck": bn[0] if bn else None,
            "overlap_efficiency": round(eff, 3) if eff is not None else None,
        }

    def examples_per_sec_lifetime(self) -> float:
        """LIFETIME average (examples since construction / wall time
        since construction) — it decays over idle periods and includes
        warmup, so it's a capacity sanity number, not an instantaneous
        throughput gauge. Benches that need a true rate time their own
        window (serving/bench.py does)."""
        dt = self._clock() - self._t0
        return self.examples.total / dt if dt > 0 else 0.0

    def summary(self) -> Dict:
        """Flat dict suitable for a bench row's ``extra`` or a log line."""

        def ms(v: Optional[float]) -> Optional[float]:
            return round(v * 1e3, 3) if v is not None else None

        dispatch = self.dispatch_latency.snapshot()
        enqueue = self.dispatch_enqueue_latency.snapshot()
        request = self.request_latency.snapshot()
        pipeline = self.pipeline_report()
        eff = self.padding_efficiency()
        mfu = self.mfu()
        out = {
            "compiles_per_bucket": {
                str(k): v for k, v in sorted(self.compiles.snapshot().items())
            },
            "dispatches_per_bucket": {
                str(k): v
                for k, v in sorted(self.dispatches.snapshot().items())
            },
            "examples": self.examples.total,
            "padded_rows": self.padded_rows.total,
            "h2d_bytes_total": self.h2d_bytes.total,
            "h2d_bytes_per_example": (
                round(self.h2d_bytes.total / self.examples.total, 1)
                if self.examples.total else None
            ),
            "padding_efficiency": (
                round(eff, 4) if eff is not None else None
            ),
            "device_flops_total": self.device_flops.total,
            "mfu": round(mfu, 6) if mfu is not None else None,
            "examples_per_sec": round(self.examples_per_sec(), 1),
            "examples_per_sec_lifetime": round(
                self.examples_per_sec_lifetime(), 1
            ),
            "dispatch_p50_ms": ms(dispatch["p50"]),
            "dispatch_p95_ms": ms(dispatch["p95"]),
            "dispatch_p99_ms": ms(dispatch["p99"]),
            "dispatch_enqueue_p50_ms": ms(enqueue["p50"]),
            "request_p50_ms": ms(request["p50"]),
            "request_p95_ms": ms(request["p95"]),
            "request_p99_ms": ms(request["p99"]),
            "queue_depth": self.queue_depth,
            "max_coalesced": self.max_coalesced,
        }
        if pipeline is not None:
            out["pipeline"] = pipeline
        return out

    # -- MetricsRegistry bridge --------------------------------------------

    def register(self, registry=None, engine: Optional[str] = None) -> str:
        """Export this instance's live state through a ``MetricsRegistry``
        (the process-global one by default) under an ``engine`` label.

        Registers a weakref-holding collector: nothing is copied until a
        scrape, the hot-path record_* methods are untouched, and once
        the engine (and its metrics) are garbage-collected the collector
        returns None and is pruned. Returns the engine label used.

        Idempotent against the global registry: a second global
        ``register()`` (e.g. an engine wrapping caller-provided metrics
        that already registered) returns the existing label instead of
        double-exporting every family.

        Label ownership: registering a label that a still-live
        ``ServingMetrics`` already claimed in the same registry
        TRANSFERS it — the newest registration wins and the superseded
        collector prunes itself at the next scrape. That keeps the
        documented engine-swap loop (build replacement under the same
        name, warm, swap) from ever emitting duplicate series, which
        Prometheus rejects scrape-wide."""
        from keystone_tpu.observability.registry import (
            MetricFamily,
            Sample,
            get_global_registry,
        )

        if registry is None and getattr(self, "_registered_label", None):
            return self._registered_label
        reg = registry if registry is not None else get_global_registry()
        label = engine if engine is not None else f"engine{next(_engine_ids)}"
        if registry is None:
            self._registered_label = label
        ref = weakref.ref(self)
        # per-registry label claim table: collector emits only while it
        # is the label's CURRENT owner
        claims = getattr(reg, "_engine_label_claims", None)
        if claims is None:
            claims = reg._engine_label_claims = {}
        claims[label] = ref

        def quantile_samples(rec: LatencyRecorder):
            snap = rec.snapshot()
            out = [
                Sample(
                    "",
                    {"engine": label, "quantile": repr(q)},
                    snap[f"p{int(q * 100)}"],
                )
                for q in (0.5, 0.95, 0.99)
                if snap[f"p{int(q * 100)}"] is not None
            ]
            out.append(Sample("_count", {"engine": label}, snap["count"]))
            out.append(Sample("_sum", {"engine": label}, snap["total"]))
            return out

        def stage_families(m):
            """Pipelined-lane families — emitted only once a staged
            pipeline has run on this engine, so serial engines' scrapes
            stay free of empty stage series."""
            if not m.windows.total:
                return []
            quantiles = []
            for stage, rec in sorted(m.stage_seconds.items()):
                snap = rec.snapshot()
                if not snap["count"]:
                    continue
                quantiles.extend(
                    Sample(
                        "",
                        {
                            "engine": label,
                            "stage": stage,
                            "quantile": repr(q),
                        },
                        snap[f"p{int(q * 100)}"],
                    )
                    for q in (0.5, 0.95, 0.99)
                    if snap[f"p{int(q * 100)}"] is not None
                )
                quantiles.append(Sample(
                    "_count", {"engine": label, "stage": stage},
                    snap["count"],
                ))
                quantiles.append(Sample(
                    "_sum", {"engine": label, "stage": stage},
                    snap["total"],
                ))
            bn = m.bottleneck()
            eff = m.overlap_efficiency()
            with m._lock:
                depths = dict(m._stage_queue_depth)
            return [
                MetricFamily(
                    "keystone_serving_stage_seconds", "summary",
                    "staged-lane pipeline busy seconds per window, "
                    "per stage",
                    quantiles,
                ),
                MetricFamily(
                    "keystone_serving_stage_queue_depth", "gauge",
                    "staged-lane handoff queue depth, per stage",
                    [
                        Sample(
                            "", {"engine": label, "stage": s}, d
                        )
                        for s, d in sorted(depths.items())
                    ],
                ),
                MetricFamily(
                    "keystone_serving_pipeline_windows_total", "counter",
                    "windows fully delivered by the staged lane pipeline",
                    [Sample("", {"engine": label}, m.windows.total)],
                ),
                MetricFamily(
                    "keystone_serving_pipeline_bottleneck", "gauge",
                    "1 on the stage with the lowest standalone rate "
                    "(the lane's bottleneck attribution)",
                    [
                        Sample(
                            "", {"engine": label, "stage": s},
                            1.0 if bn and s == bn[0] else 0.0,
                        )
                        for s in sorted(m.stage_seconds)
                    ],
                ),
                MetricFamily(
                    "keystone_serving_pipeline_overlap_efficiency",
                    "gauge",
                    "sustained window rate over the bottleneck stage's "
                    "standalone rate (~1.0 = nothing lost to "
                    "serialization)",
                    [Sample(
                        "", {"engine": label},
                        eff if eff is not None else 0.0,
                    )],
                ),
            ]

        def device_families(m):
            """Device-truth families — static cost models, rolling MFU,
            roofline classification, goodput. Every family is emitted
            only when its inputs exist (cost analysis present, peaks
            known, pool live): a backend that reports nothing yields
            ABSENT series, the graceful-degradation contract."""
            fams = []
            models = dict(m.cost_models)
            if models:
                per_key = (
                    ("flops", "keystone_device_flops_per_dispatch",
                     "modeled XLA FLOPs per dispatch of the bucket's "
                     "compiled program"),
                    ("bytes_accessed", "keystone_device_bytes_per_dispatch",
                     "modeled bytes accessed per dispatch of the "
                     "bucket's compiled program"),
                    ("temp_bytes", "keystone_device_temp_hbm_bytes",
                     "temp (scratch) device memory of the bucket's "
                     "compiled program"),
                )
                for key, name, help_ in per_key:
                    samples = [
                        Sample(
                            "", {"engine": label, "bucket": str(b)},
                            mod[key],
                        )
                        for b, mod in sorted(models.items())
                        if key in mod
                    ]
                    if samples:
                        fams.append(
                            MetricFamily(name, "gauge", help_, samples)
                        )
                roofline = [
                    (b, m.roofline_bound(b)) for b in sorted(models)
                ]
                roofline = [(b, r) for b, r in roofline if r is not None]
                if roofline:
                    fams.append(MetricFamily(
                        "keystone_device_roofline_bound", "gauge",
                        "1 on the bucket program's roofline side "
                        "(arithmetic intensity vs the device ridge "
                        "point): compute- or bandwidth-bound",
                        [
                            Sample(
                                "",
                                {
                                    "engine": label,
                                    "bucket": str(b),
                                    "bound": side,
                                },
                                1.0 if side == r else 0.0,
                            )
                            for b, r in roofline
                            for side in ("compute", "bandwidth")
                        ],
                    ))
            if m.device_flops.total:
                fams.append(MetricFamily(
                    "keystone_serving_device_flops_total", "counter",
                    "modeled device FLOPs dispatched (per the buckets' "
                    "static cost models)",
                    [Sample("", {"engine": label}, m.device_flops.total)],
                ))
            mfu = m.mfu()
            if mfu is not None:
                fams.append(MetricFamily(
                    "keystone_serving_mfu", "gauge",
                    "rolling model FLOPs utilization: windowed modeled "
                    "FLOP/s over detected peak FLOP/s",
                    [Sample("", {"engine": label}, mfu)],
                ))
            eff = m.padding_efficiency()
            if eff is not None:
                fams.append(MetricFamily(
                    "keystone_serving_padding_efficiency", "gauge",
                    "windowed goodput fraction: valid rows over all "
                    "rows shipped (valid + padding)",
                    [Sample("", {"engine": label}, eff)],
                ))
            staging = m.staging_bytes
            if staging is not None:
                fams.append(MetricFamily(
                    "keystone_serving_staging_bytes", "gauge",
                    "live host staging-buffer bytes held by the lane's "
                    "buffer pool (pooled + in flight)",
                    [Sample("", {"engine": label}, staging)],
                ))
            return fams

        def collect():
            m = ref()
            if m is None or claims.get(label) is not ref:
                return None  # engine gone or label re-claimed by a
                # newer engine: prune this collector
            return stage_families(m) + device_families(m) + [
                MetricFamily(
                    "keystone_serving_compiles_total", "counter",
                    "XLA compiles per bucket",
                    [
                        Sample("", {"engine": label, "bucket": str(b)}, v)
                        for b, v in sorted(m.compiles.snapshot().items())
                    ],
                ),
                MetricFamily(
                    "keystone_serving_dispatches_total", "counter",
                    "compiled-program dispatches per bucket",
                    [
                        Sample("", {"engine": label, "bucket": str(b)}, v)
                        for b, v in sorted(m.dispatches.snapshot().items())
                    ],
                ),
                MetricFamily(
                    "keystone_serving_examples_total", "counter",
                    "valid examples served",
                    [Sample("", {"engine": label}, m.examples.total)],
                ),
                MetricFamily(
                    "keystone_serving_goodput_rows_total", "counter",
                    "valid (non-padding) rows dispatched, per bucket",
                    [
                        Sample("", {"engine": label, "bucket": str(b)}, v)
                        for b, v in sorted(m.examples.snapshot().items())
                    ],
                ),
                MetricFamily(
                    "keystone_serving_padded_rows_total", "counter",
                    "padded rows shipped (bucket waste), per bucket",
                    [
                        Sample("", {"engine": label, "bucket": str(b)}, v)
                        for b, v in sorted(m.padded_rows.snapshot().items())
                    ],
                ),
                MetricFamily(
                    "keystone_serving_h2d_bytes_total", "counter",
                    "bytes staged host-to-device per dispatch, by "
                    "bucket (padding included; raw-on-the-wire "
                    "device-featurize engines show the reduction here)",
                    [
                        Sample("", {"engine": label, "bucket": str(b)}, v)
                        for b, v in sorted(m.h2d_bytes.snapshot().items())
                    ],
                ),
                MetricFamily(
                    "keystone_serving_request_size_total", "counter",
                    "dispatches by valid-row count (autoscaler input)",
                    [
                        Sample("", {"engine": label, "size": str(s)}, v)
                        for s, v in sorted(m.request_sizes.snapshot().items())
                    ],
                ),
                MetricFamily(
                    "keystone_serving_queue_depth", "gauge",
                    "micro-batcher pending requests",
                    [Sample("", {"engine": label}, m.queue_depth)],
                ),
                MetricFamily(
                    "keystone_serving_examples_per_sec", "gauge",
                    f"windowed throughput over the last {RATE_WINDOW_S:.0f}s",
                    [Sample("", {"engine": label}, m.examples_per_sec())],
                ),
                MetricFamily(
                    "keystone_serving_dispatch_latency_seconds", "summary",
                    "engine dispatch wall time, completion-timed at the "
                    "caller's sync point",
                    quantile_samples(m.dispatch_latency),
                ),
                MetricFamily(
                    "keystone_serving_dispatch_enqueue_seconds", "summary",
                    "engine dispatch enqueue time (pad/placement + "
                    "compiled-call dispatch, execution excluded)",
                    quantile_samples(m.dispatch_enqueue_latency),
                ),
                MetricFamily(
                    "keystone_serving_request_latency_seconds", "summary",
                    "end-to-end micro-batched request latency",
                    quantile_samples(m.request_latency),
                ),
            ]

        reg.register_collector(collect)
        return label
