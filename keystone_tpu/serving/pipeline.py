"""Staged lane pipeline: overlap host prep, H2D upload, and device
compute behind one ``MicroBatcher``.

The serving-side analogue of the streaming featurize bench's
decode/upload/compute overlap (bench.py's ``imagenet_stream_featurize``
row): a serial batcher lane runs coalesce → stack → pad → device_put →
compute → deliver one window at a time, so while the device runs window
k, window k+1's host work and H2D transfer sit idle in the queue. Here
the dispatch is split into explicit stages connected by BOUNDED handoff
queues (depth ~2), each stage on its own thread:

    coalesce ──▶ host-prep ──▶ upload ──▶ compute ──▶ deliver
    (batcher     stack or       device_put  compiled    slice valid
     window      host-featurize + H2D sync  bucket fn   rows, resolve
     logic)      + pad into     (buffer     + ready     futures
                 pooled buffer  rides on)   sync (frees
                                            pool buffer)

so window k+1's host-prep and upload overlap window k's device compute.
When a queue fills, the coalesce thread blocks, pending requests pile
up behind the batcher, lane load rises, and the gateway's admission
controller sheds — backpressure is end-to-end, never an unbounded pile.

**Host featurize** is the pluggable prep hook: a callable turning one
coalesced window of RAW examples (any pytree — or non-array items like
strings) into the batched array tree the engine stages. Items-mode /
tokenizer front-ends (the text path's ``FusedTextHashTF``-style fused
featurizers) run behind the engine this way: clients submit raw items,
the featurize stage burns host cores while the device computes the
previous window. The same hook drives the serial path, so pipelined
and serial results stay comparable (and bit-identical — both modes
compose the engine's own stage primitives over identical values).

**Buffer pool**: host-prep writes each padded window into a small
per-(bucket, spec) pool of reusable host staging buffers (double
buffered — ``depth + 1`` per key), so steady-state windows allocate no
host memory. A buffer returns to the pool only once its window's
COMPUTE output is ready — backends may stage host arrays zero-copy
(the CPU backend does), so the first point the staged input is
provably consumed is the execution that read it, not the device_put's
own ready signal. The uploaded device buffers are engine-private and
feed the compiled program's donated arguments on backends with
donation support. ``reset()`` (engine swap) bumps the
pool generation: in-flight windows finish on their old engine and
their buffers — possibly sized for retired buckets — are dropped
instead of re-pooled.

Each stage opens a tracer span (``pipeline.host_prep`` / ``.upload`` /
``.compute`` / ``.deliver``) parented under the window's
``microbatch.coalesce`` span, and records per-stage seconds +
queue-depth series on the window's engine ``ServingMetrics``; the
derived per-lane ``bottleneck`` attribution and ``overlap_efficiency``
mirror the streaming bench's model (see ``ServingMetrics.bottleneck``).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from keystone_tpu.loadgen import faults
from keystone_tpu.observability.tracing import get_tracer

logger = logging.getLogger(__name__)

DEFAULT_DEPTH = 2

# HostFeaturize(raw examples of one window) -> batched pytree of arrays
# with a leading axis of len(examples). Runs on the host-prep thread;
# must be thread-safe and pure (same window -> same values).
HostFeaturize = Callable[[List[Any]], Any]

_SENTINEL = object()


class HostBufferPool:
    """Reusable padded host staging buffers, keyed by
    ``(bucket, treedef, per-leaf row shape/dtype)``.

    ``acquire`` hands out a free buffer tree or allocates one
    (``allocations`` counts these — the no-growth test reads it);
    ``release`` returns it unless the pool already holds
    ``max_per_key`` for that key or the pool generation moved on (an
    engine swap retired the bucket set the buffer was cut for)."""

    def __init__(self, max_per_key: int = DEFAULT_DEPTH + 1):
        self.max_per_key = max_per_key
        self.generation = 0  # guarded-by: _lock
        self.allocations = 0  # guarded-by: _lock
        self._free: Dict[Any, List[Any]] = {}  # guarded-by: _lock
        # live staging footprint: bytes sitting free in the pool +
        # bytes riding in-flight windows (the
        # ``keystone_serving_staging_bytes`` gauge input)
        self._pooled_bytes = 0  # guarded-by: _lock
        self._outstanding_bytes = 0  # guarded-by: _lock
        # a key pins (bucket, treedef, shapes, dtypes), so its buffer
        # size is a constant — computed once per key, not per window
        self._key_bytes: Dict[Any, int] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    @staticmethod
    def _tree_bytes(buffers: Any) -> int:
        return sum(
            int(getattr(a, "nbytes", 0))
            for a in jax.tree_util.tree_leaves(buffers)
        )

    def _bytes_for_locked(self, key: Any, buffers: Any) -> int:
        """Cached per-key buffer size (the ``_locked`` suffix is the
        caller-holds-``self._lock`` convention the guarded-by lint
        rule recognizes)."""
        nbytes = self._key_bytes.get(key)
        if nbytes is None:
            nbytes = self._key_bytes[key] = self._tree_bytes(buffers)
        return nbytes

    @property
    def staging_bytes(self) -> int:
        """Total host bytes the pool currently accounts for (pooled
        free buffers + buffers riding in-flight windows)."""
        with self._lock:
            return self._pooled_bytes + self._outstanding_bytes

    def reset(self) -> None:
        """Engine swap: drop every pooled buffer and invalidate
        outstanding ones (their release becomes a no-op drop)."""
        with self._lock:
            self.generation += 1
            self._free.clear()
            self._key_bytes.clear()  # keys are cut per bucket set
            # old-generation buffers still in flight stop being
            # accounted here — their release is a drop, not a return
            self._pooled_bytes = 0
            self._outstanding_bytes = 0

    def acquire(
        self, key: Any, alloc: Callable[[], Any]
    ) -> Tuple[int, Any]:
        with self._lock:
            free = self._free.get(key)
            if free:
                buffers = free.pop()
                nbytes = self._bytes_for_locked(key, buffers)
                self._pooled_bytes -= nbytes
                self._outstanding_bytes += nbytes
                return self.generation, buffers
            self.allocations += 1
            gen = self.generation
        buffers = alloc()
        with self._lock:
            if gen == self.generation:
                self._outstanding_bytes += self._bytes_for_locked(
                    key, buffers
                )
        return gen, buffers

    def publish_staging_bytes(self, resolve_metrics: Callable[[], Any]) -> None:
        """Stamp the live footprint on ``resolve_metrics()``'s gauge,
        atomically with ``reset()``: a swap reassigns the batcher's
        current metrics BEFORE it resets this pool, and re-stamps both
        gauges AFTER, so a stage thread that selects its target and
        publishes while holding this lock can never leave a retired
        engine carrying the new pool's bytes."""
        with self._lock:
            resolve_metrics().set_staging_bytes(
                self._pooled_bytes + self._outstanding_bytes
            )

    def release(self, key: Any, generation: int, buffers: Any) -> None:
        if buffers is None:
            return  # window died before its buffers were attached
        with self._lock:
            if generation != self.generation:
                # cut for a retired engine's buckets: drop (reset()
                # already zeroed their outstanding-byte accounting)
                return
            nbytes = self._bytes_for_locked(key, buffers)
            self._outstanding_bytes -= nbytes
            free = self._free.setdefault(key, [])
            if len(free) < self.max_per_key:
                free.append(buffers)
                self._pooled_bytes += nbytes


def resolve_window_futures(metrics, valid, futures, enqueued) -> None:
    """Deliver one window: gather ``valid`` (a tree of valid-rows
    outputs) to host numpy ONCE, resolve each future with a row VIEW of
    it, and record the completion-timed per-request latency. Shared by
    the serial batcher dispatch and the pipelined deliver stage so the
    two delivery paths cannot drift — per-row jax.Array slicing here
    would dispatch one device op per request (GIL-heavy; measured as
    the pipelined lane's bottleneck before the single host gather)."""
    valid = jax.tree_util.tree_map(np.asarray, valid)
    done = time.perf_counter()
    for i, fut in enumerate(futures):
        row = jax.tree_util.tree_map(lambda a, i=i: a[i], valid)
        try:
            fut.set_result(row)
        except Exception:
            continue  # caller cancelled this request; the rest of
            # the window must still get their results
        metrics.record_request(done - enqueued[i])


class _Window:
    """One coalesced window riding the stage queues."""

    __slots__ = (
        "examples", "futures", "enqueued", "engine", "owned",
        "parent_span_id", "tree", "rows", "bucket", "host_tree",
        "pool_key", "pool_gen", "device_tree", "out", "valid",
        "fallback", "t_compute0",
    )

    def __init__(self, examples, futures, enqueued, engine, parent_span_id):
        self.examples = examples
        self.futures = futures
        self.enqueued = enqueued
        self.engine = engine
        self.owned = True
        self.parent_span_id = parent_span_id
        self.tree = None          # assembled batched tree (post-prep)
        self.rows = len(examples)
        self.bucket: Optional[int] = None
        self.host_tree = None     # padded host staging (pooled)
        self.pool_key = None
        self.pool_gen = 0
        self.device_tree = None   # staged on device, pre-compute
        self.out = None           # full padded output (async)
        self.valid = None         # sliced valid rows
        self.fallback = False     # rows > engine.max_bucket: serial
        # chunked apply inside the compute stage
        self.t_compute0 = 0.0


def _leading_np(tree) -> bool:
    """True when every leaf is a host (numpy) array — the poolable,
    host-paddable case. Device-array windows pad/place on device via
    the engine's serial ``_stage`` instead."""
    return all(
        not isinstance(a, jax.Array)
        for a in jax.tree_util.tree_leaves(tree)
    )


class LanePipeline:
    """The stage threads + handoff queues behind one pipelined
    ``MicroBatcher``. Construct via ``MicroBatcher(pipeline_depth=N)``;
    windows enter through ``submit_window`` on the batcher's coalesce
    thread and leave by resolving their request futures in deliver."""

    # stage order drives thread wiring and queue-depth attribution
    STAGES = ("host_prep", "upload", "compute", "deliver")

    def __init__(
        self,
        assemble: Callable[[List[Any]], Tuple[Any, bool]],
        depth: int = DEFAULT_DEPTH,
        name: str = "lane",
        current_metrics: Optional[Callable[[], Any]] = None,
    ):
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        self.depth = depth
        self.name = name
        self._assemble = assemble
        # the staging pool belongs to the LANE, so its byte gauge
        # tracks the engine currently serving it — a window that
        # outlives a swap must not stamp the new pool's footprint onto
        # its retired coalesce-time engine (double-counted series)
        self._current_metrics = current_metrics
        self.pool = HostBufferPool(max_per_key=depth + 1)
        self._queues: Dict[str, "queue.Queue"] = {
            s: queue.Queue(maxsize=depth) for s in self.STAGES
        }
        self._closed = False
        self._threads = [
            threading.Thread(
                target=self._stage_loop,
                args=(stage,),
                name=f"keystone-{name}-{stage}",
                daemon=True,
            )
            for stage in self.STAGES
        ]
        for t in self._threads:
            t.start()

    def _publish_staging_bytes(self, fallback_engine) -> None:
        resolve = self._current_metrics
        self.pool.publish_staging_bytes(
            resolve if resolve is not None
            else lambda: fallback_engine.metrics
        )

    # -- intake (the batcher's coalesce thread) ----------------------------

    def submit_window(
        self,
        examples: List[Any],
        futures: List,
        enqueued: List[float],
        engine,
        parent_span_id: Optional[int],
    ) -> None:
        """Hand one coalesced window to the stage chain. BLOCKS while
        the host-prep queue is full — that block is the backpressure
        signal: pending requests pile up behind the batcher, lane load
        rises, and admission sheds before anything here is unbounded."""
        w = _Window(examples, futures, enqueued, engine, parent_span_id)
        self._queues["host_prep"].put(w)
        engine.metrics.set_stage_queue_depth(
            "host_prep", self._queues["host_prep"].qsize()
        )

    # -- stage threads -----------------------------------------------------

    def _stage_loop(self, stage: str) -> None:
        inbox = self._queues[stage]
        i = self.STAGES.index(stage)
        outbox = (
            self._queues[self.STAGES[i + 1]]
            if i + 1 < len(self.STAGES) else None
        )
        fn = getattr(self, f"_{stage}")
        while True:
            w = inbox.get()
            if w is _SENTINEL:
                if outbox is not None:
                    outbox.put(_SENTINEL)
                return
            t0 = time.perf_counter()
            try:
                with get_tracer().span(
                    f"pipeline.{stage}",
                    parent_id=w.parent_span_id,
                    engine=w.engine.name,
                    window=len(w.futures),
                    bucket=w.bucket or 0,
                ):
                    fn(w)
                w.engine.metrics.record_stage(
                    stage, time.perf_counter() - t0
                )
            except Exception as e:
                self._fail_window(w, e)
                continue
            w.engine.metrics.set_stage_queue_depth(stage, inbox.qsize())
            if outbox is not None:
                outbox.put(w)

    def _fail_window(self, w: _Window, err: Exception) -> None:
        """Resolve every future with the stage error (never hang
        callers) and recycle any pooled buffer the window held."""
        if w.pool_key is not None:
            self.pool.release(w.pool_key, w.pool_gen, w.host_tree)
            w.pool_key = None
        for fut in w.futures:
            if not fut.done():
                try:
                    fut.set_exception(err)
                except Exception:
                    pass  # caller cancelled concurrently

    # stage 2: assemble (stack / host featurize) + pad on host into a
    # pooled staging buffer
    def _host_prep(self, w: _Window) -> None:
        engine = w.engine
        # chaos point: stall the prep stage (a slow tokenizer RPC /
        # feature-store brownout). The sleep holds THIS stage thread,
        # so the bounded handoff queues fill, submit_window blocks,
        # lane load rises, and admission sheds — the end-to-end
        # backpressure chain is exactly what the experiment verifies.
        if faults.armed():
            spec = faults.fire(
                "pipeline.host_prep.stall", {"engine": engine.name}
            )
            if spec is not None and spec.delay_ms > 0:
                time.sleep(spec.delay_ms / 1e3)
        w.tree, w.owned = self._assemble(w.examples)
        w.examples = None  # window owns the batched tree from here
        leaves, treedef = jax.tree_util.tree_flatten(w.tree)
        w.rows = leaves[0].shape[0]
        if w.rows > engine.max_bucket:
            # a pinned max_batch wider than a post-swap engine's largest
            # bucket: fall back to the engine's chunked serial apply in
            # the compute stage (degraded, never wrong)
            w.fallback = True
            return
        w.bucket = engine.bucket_for(w.rows)
        if not _leading_np(w.tree):
            # device-array window: pad/place on device exactly like the
            # serial path; upload becomes a pass-through
            w.device_tree = engine._stage(
                w.tree, w.rows, w.bucket, owned=w.owned
            )
            w.tree = None
            return
        key = (
            w.bucket, treedef,
            tuple((a.shape[1:], a.dtype.str) for a in leaves),
        )
        bucket = w.bucket

        def alloc():
            return treedef.unflatten([
                np.zeros((bucket,) + a.shape[1:], a.dtype)
                for a in leaves
            ])

        w.pool_gen, buffers = self.pool.acquire(key, alloc)
        w.pool_key = key
        self._publish_staging_bytes(engine)
        # attach the buffers to the window BEFORE the fill: if a
        # misbehaving featurize hook makes host_stage raise (e.g. a
        # leaf with a mismatched leading dim), _fail_window must
        # recycle the real buffers — releasing a half-built window's
        # host_tree=None would poison the pool key for every later
        # window sharing it
        w.host_tree = buffers
        engine.host_stage(w.tree, w.rows, bucket, out=buffers)
        w.tree = None

    # stage 3: H2D transfer. The pooled host buffer is NOT released
    # here: backends may stage host arrays zero-copy (the CPU backend
    # does — a device_put'd array can read the numpy buffer as late as
    # the consuming execution), so "transfer ready" does not mean
    # "host buffer consumed". The buffer rides with the window and
    # frees once its COMPUTE output is ready — the first point the
    # inputs are provably consumed. depth+1 pooled buffers per key
    # keep prep/upload/compute fully overlapped despite the longer
    # hold.
    def _upload(self, w: _Window) -> None:
        if w.fallback or w.device_tree is not None:
            return
        staged = w.engine.upload_staged(w.host_tree)
        jax.block_until_ready(staged)
        w.device_tree = staged

    # stage 4: the compiled bucket program with donated inputs; the
    # ready sync here is the completion-timed dispatch number the
    # serial path records at apply(sync=True)
    def _compute(self, w: _Window) -> None:
        engine = w.engine
        w.t_compute0 = time.perf_counter()
        if w.fallback:
            # oversized window (pinned max_batch > a post-swap engine's
            # largest bucket): the engine's chunked serial apply
            w.valid = engine.apply(w.tree, sync=True, owned=w.owned)
            w.tree = None
            return
        w.out = engine.compute_staged(w.device_tree, w.rows, w.bucket)
        w.device_tree = None  # donated — never touch it again
        jax.block_until_ready(w.out)
        engine.metrics.record_dispatch_complete(
            time.perf_counter() - w.t_compute0
        )
        if w.pool_key is not None:
            # output ready == inputs consumed: the pooled host buffer
            # is finally safe to hand to a later window's prep
            self.pool.release(w.pool_key, w.pool_gen, w.host_tree)
            w.pool_key = None
            w.host_tree = None
            self._publish_staging_bytes(engine)

    # stage 5: slice valid rows, resolve futures, close the loop on
    # request latency + window-rate series (the single-host-gather
    # rationale lives on resolve_window_futures)
    def _deliver(self, w: _Window) -> None:
        metrics = w.engine.metrics
        if w.valid is None:
            w.valid = jax.tree_util.tree_map(
                lambda a: np.asarray(a)[: w.rows], w.out
            )
            w.out = None
        resolve_window_futures(metrics, w.valid, w.futures, w.enqueued)
        metrics.record_window()

    # -- lifecycle ---------------------------------------------------------

    def on_swap(self) -> None:
        """Engine swapped behind the batcher: rebuild the staging pool
        (bucket sizes may have changed). Windows already in the stages
        carry their coalesce-time engine and finish on it; their
        buffers drop instead of re-pooling (generation bump)."""
        self.pool.reset()

    def close(self, timeout: Optional[float] = 10.0) -> None:
        """Flush in-flight windows through every stage and stop the
        threads. Caller (``MicroBatcher.close``) has already drained
        its pending queue into ``submit_window``."""
        if self._closed:
            return
        self._closed = True
        self._queues["host_prep"].put(_SENTINEL)
        deadline = (
            time.perf_counter() + timeout if timeout is not None else None
        )
        for t in self._threads:
            remaining = (
                None if deadline is None
                else max(0.1, deadline - time.perf_counter())
            )
            t.join(remaining)
        if any(t.is_alive() for t in self._threads):
            logger.warning(
                "lane pipeline %s still draining after %.1fs close "
                "timeout (cold compile in flight?); in-flight futures "
                "resolve as it finishes", self.name, timeout,
            )


__all__ = [
    "DEFAULT_DEPTH",
    "HostBufferPool",
    "HostFeaturize",
    "LanePipeline",
]
