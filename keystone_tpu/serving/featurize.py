"""Reference device-side featurize chains for serving.

``CompiledPipeline(featurize=...)`` fuses any fitted pure-JAX pipeline
in front of the model; this module provides the canonical image chains
the ``--device-featurize`` gateway modes, the featurize bench rows, and
the smoke/tests all share — kept OUT of the benchmark module so the
production CLI path doesn't depend on bench code.

Two chains:

- ``build_featurize_pipeline`` — the *demo* dense-conv stack
  (PixelScaler → Convolver → rectify → pool → vectorize), the cheap
  geometry the PR-14 plumbing was proven on;
- ``build_flagship_featurize_pipeline`` — the paper's flagship
  ImageNetSiftLcsFV featurization: a **branched** DAG (gray→SIFT and
  LCS branches, each PCA → GMM Fisher Vector → Hellinger/L2
  normalization, gathered through ``VectorCombiner``) whose hot loops
  run as Pallas kernels (``ops/images/pallas_kernels``, ``fv_pallas``).
  Fittable-then-frozen: pass ``fit_images`` to fit real PCA/GMM
  parameters through the reference estimator path, or let the seeded
  warm-start stand in where a deterministic chain is what matters
  (gateway startup, benches, tests). Either way the result is a frozen
  pure-JAX ``FittedPipeline`` that ``CompiledPipeline(featurize=)``
  fuses — branches and all — into each per-bucket XLA program.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np


def build_featurize_pipeline(
    img: int = 16,
    channels: int = 3,
    filters: int = 96,
    conv_size: int = 5,
    pool_stride: int = 6,
    pool_size: int = 6,
    seed: int = 7,
) -> Tuple[object, int]:
    """A pure-JAX image featurize chain — raw ``(img, img, C)`` uint8
    in, ``(F,)`` f32 features out: PixelScaler → Convolver (patch
    normalization folded around one XLA conv) → SymmetricRectifier →
    sum-Pooler → channel-major ImageVectorizer, the
    RandomPatchCifar-style dense-conv stack from ``ops/images``.
    Returns ``(fitted_featurize, feature_dim)``. The default geometry
    is the device-featurize demo/bench shape: 16·16·3 = 768 raw uint8
    bytes per example featurize to 768 f32 features = 3072 bytes, so
    shipping raw instead of featurized is a 4× H2D reduction."""
    import jax.numpy as jnp

    from keystone_tpu.ops.images.core import (
        Convolver,
        ImageVectorizer,
        PixelScaler,
        Pooler,
        SymmetricRectifier,
    )

    rng = np.random.default_rng(seed)
    packed = jnp.asarray(
        rng.standard_normal(
            (filters, conv_size * conv_size * channels)
        ).astype(np.float32) * 0.1
    )
    pipe = None
    for node in (
        PixelScaler(),
        Convolver(packed, img, img, channels),
        SymmetricRectifier(),
        Pooler(stride=pool_stride, pool_size=pool_size),
        ImageVectorizer(),
    ):
        pipe = node.to_pipeline() if pipe is None else pipe.and_then(node)
    fitted = pipe.to_pipeline().fit()
    feat_dim = int(
        np.asarray(
            fitted._batch_run(
                jnp.zeros((1, img, img, channels), jnp.uint8)
            )
        ).shape[-1]
    )
    return fitted, feat_dim


def flagship_pipeline(
    rng: np.random.Generator,
    desc_dim: int = 64,
    vocab: int = 16,
    *,
    sift_step: int = 3,
    sift_bin: int = 4,
    sift_scales: int = 4,
    sift_scale_step: int = 1,
    lcs_stride: int = 4,
    lcs_border: int = 16,
    lcs_patch: int = 6,
):
    """The unfitted warm-start ImageNetSiftLcsFV featurize chain —
    everything in ``pipelines/images/imagenet_sift_lcs_fv.build_pipeline``
    before the solver, with seeded random PCA projections and unit
    GMMs standing in for the fitted parameters (the shape/dataflow is
    identical; only the learned values differ). The FV node follows the
    reference's k >= 32 physical choice: the fused Pallas statistics
    kernel for large vocabularies, the plain XLA program below it."""
    import jax.numpy as jnp

    from keystone_tpu.ops.images.core import GrayScaler, PixelScaler
    from keystone_tpu.ops.images.fisher_vector import (
        FisherVector,
        FisherVectorFused,
    )
    from keystone_tpu.ops.images.lcs import LCSExtractor
    from keystone_tpu.ops.images.sift import SIFTExtractor
    from keystone_tpu.ops.learning import BatchPCATransformer
    from keystone_tpu.ops.learning.gmm import GaussianMixtureModel
    from keystone_tpu.ops.stats import (
        NormalizeRows,
        SignedHellingerMapper,
    )
    from keystone_tpu.ops.util.nodes import (
        FloatToDouble,
        MatrixVectorizer,
        VectorCombiner,
    )
    from keystone_tpu.workflow.api import Pipeline

    def branch(prefix, in_dim):
        pca = jnp.asarray(
            rng.standard_normal((desc_dim, in_dim)).astype(np.float32)
            * 0.1
        )
        gmm = GaussianMixtureModel(
            jnp.asarray(
                rng.standard_normal((desc_dim, vocab)), jnp.float32
            ),
            jnp.ones((desc_dim, vocab), jnp.float32),
            jnp.ones((vocab,), jnp.float32) / vocab,
        )
        fv = (
            FisherVectorFused(gmm) if vocab >= 32 else FisherVector(gmm)
        )
        return (
            prefix
            .and_then(BatchPCATransformer(pca.T))
            .and_then(fv)
            .and_then(FloatToDouble())
            .and_then(MatrixVectorizer())
            .and_then(NormalizeRows())
            .and_then(SignedHellingerMapper())
            .and_then(NormalizeRows())
        )

    sift = branch(
        PixelScaler().and_then(GrayScaler())
        .and_then(SIFTExtractor(
            step=sift_step, bin=sift_bin, num_scales=sift_scales,
            scale_step=sift_scale_step,
        ))
        .and_then(SignedHellingerMapper()),
        128,
    )
    lcs = branch(
        LCSExtractor(lcs_stride, lcs_border, lcs_patch).to_pipeline(),
        96,
    )
    return Pipeline.gather([sift, lcs]).and_then(VectorCombiner())


def build_flagship_featurize_pipeline(
    img: int = 64,
    desc_dim: int = 16,
    vocab: int = 16,
    *,
    sift_step: int = 4,
    sift_bin: int = 4,
    sift_scales: int = 2,
    sift_scale_step: int = 1,
    lcs_stride: int = 4,
    lcs_border: int = 16,
    lcs_patch: int = 6,
    seed: int = 7,
    fit_images: Optional[Any] = None,
) -> Tuple[object, int]:
    """The flagship SIFT+LCS→FV featurize chain as a frozen serving
    stage — raw ``(img, img, 3)`` uint8 in, ``(2·2·desc_dim·vocab,)``
    f32 features out. Returns ``(fitted_featurize, feature_dim)``.

    With ``fit_images`` (a ``Dataset`` of ``(img, img, 3)`` images, or
    an array convertible to one) the PCA projections and GMMs are FIT
    through the reference estimator path
    (``compute_pca_and_fisher_branch``: ColumnSampler → ColumnPCA,
    sampled+projected descriptors → GMM); without it, a seeded
    warm-start stands in (``flagship_pipeline``) — deterministic
    parameters, identical graph, which is what gateway startup, the
    bench A/B, and the AOT fingerprint tests need. Both paths freeze to
    the same pure-JAX branched DAG; ``feature_dim`` is probed off a
    zero image through ``_batch_run`` — the exact staging surface the
    serving engine fuses.

    The default geometry (64² raw, 2 SIFT scales, 16-word vocab) keeps
    the CPU smoke under a minute while exercising every node class of
    the full-size chain; ``img`` must cover the LCS border
    (``img > 2·lcs_border``) and the SIFT sampling bounds."""
    import jax.numpy as jnp

    if img <= 2 * lcs_border:
        raise ValueError(
            f"img={img} leaves the LCS keypoint grid empty "
            f"(needs img > 2*lcs_border = {2 * lcs_border})"
        )
    if fit_images is None:
        pipe = flagship_pipeline(
            np.random.default_rng(seed), desc_dim, vocab,
            sift_step=sift_step, sift_bin=sift_bin,
            sift_scales=sift_scales, sift_scale_step=sift_scale_step,
            lcs_stride=lcs_stride, lcs_border=lcs_border,
            lcs_patch=lcs_patch,
        )
    else:
        from keystone_tpu.ops.images.core import GrayScaler, PixelScaler
        from keystone_tpu.ops.images.lcs import LCSExtractor
        from keystone_tpu.ops.images.sift import SIFTExtractor
        from keystone_tpu.ops.stats import SignedHellingerMapper
        from keystone_tpu.ops.util.nodes import VectorCombiner
        from keystone_tpu.parallel.dataset import Dataset
        from keystone_tpu.pipelines.images.imagenet_sift_lcs_fv import (
            ImageNetSiftLcsFVConfig,
            compute_pca_and_fisher_branch,
        )
        from keystone_tpu.workflow.api import Pipeline

        if not isinstance(fit_images, Dataset):
            fit_images = Dataset.from_items(
                [np.asarray(x) for x in fit_images]
            )
        conf = ImageNetSiftLcsFVConfig(
            desc_dim=desc_dim, vocab_size=vocab, seed=seed,
            sift_scale_step=sift_scale_step, lcs_stride=lcs_stride,
            lcs_border=lcs_border, lcs_patch=lcs_patch,
        )
        sift_prefix = (
            PixelScaler().and_then(GrayScaler())
            .and_then(SIFTExtractor(
                step=sift_step, bin=sift_bin, num_scales=sift_scales,
                scale_step=sift_scale_step,
            ))
            .and_then(SignedHellingerMapper())
        )
        lcs_prefix = LCSExtractor(
            lcs_stride, lcs_border, lcs_patch
        ).to_pipeline()
        pipe = Pipeline.gather([
            compute_pca_and_fisher_branch(
                sift_prefix, fit_images, conf, None, None
            ),
            compute_pca_and_fisher_branch(
                lcs_prefix, fit_images, conf, None, None
            ),
        ]).and_then(VectorCombiner())
    fitted = pipe.fit()
    feat_dim = int(
        np.asarray(
            fitted._batch_run(jnp.zeros((1, img, img, 3), jnp.uint8))
        ).shape[-1]
    )
    return fitted, feat_dim


def featurize_token(fitted) -> str:
    """Content digest of a fitted featurize chain — the zoo's CSE
    grouping key (``zoo/cse.py``). Alias of ``aot.pipeline_token``:
    two chains share a prefix iff the SAME fingerprint that partitions
    the AOT store says they compute the same function (operator
    classes + wiring + every parameter array), so "identical
    featurize_token" carries the same never-serve-the-wrong-model
    guarantee in both subsystems."""
    from keystone_tpu.serving.aot import pipeline_token

    return pipeline_token(fitted)


__all__ = [
    "build_featurize_pipeline",
    "build_flagship_featurize_pipeline",
    "featurize_token",
    "flagship_pipeline",
]
