"""Reference device-side featurize chain for serving.

``CompiledPipeline(featurize=...)`` fuses any fitted pure-JAX pipeline
in front of the model; this module provides the canonical image chain
the ``--device-featurize`` gateway mode, the ``serving_device_featurize``
bench row, and the smoke/tests all share — kept OUT of the benchmark
module so the production CLI path doesn't depend on bench code. Real
deployments build their own featurize ``FittedPipeline`` from the
``ops/images`` nodes (Convolver, LCS, FisherVector, ...) the same way.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def build_featurize_pipeline(
    img: int = 16,
    channels: int = 3,
    filters: int = 96,
    conv_size: int = 5,
    pool_stride: int = 6,
    pool_size: int = 6,
    seed: int = 7,
) -> Tuple[object, int]:
    """A pure-JAX image featurize chain — raw ``(img, img, C)`` uint8
    in, ``(F,)`` f32 features out: PixelScaler → Convolver (patch
    normalization folded around one XLA conv) → SymmetricRectifier →
    sum-Pooler → channel-major ImageVectorizer, the
    RandomPatchCifar-style dense-conv stack from ``ops/images``.
    Returns ``(fitted_featurize, feature_dim)``. The default geometry
    is the device-featurize demo/bench shape: 16·16·3 = 768 raw uint8
    bytes per example featurize to 768 f32 features = 3072 bytes, so
    shipping raw instead of featurized is a 4× H2D reduction."""
    import jax.numpy as jnp

    from keystone_tpu.ops.images.core import (
        Convolver,
        ImageVectorizer,
        PixelScaler,
        Pooler,
        SymmetricRectifier,
    )

    rng = np.random.default_rng(seed)
    packed = jnp.asarray(
        rng.standard_normal(
            (filters, conv_size * conv_size * channels)
        ).astype(np.float32) * 0.1
    )
    pipe = None
    for node in (
        PixelScaler(),
        Convolver(packed, img, img, channels),
        SymmetricRectifier(),
        Pooler(stride=pool_stride, pool_size=pool_size),
        ImageVectorizer(),
    ):
        pipe = node.to_pipeline() if pipe is None else pipe.and_then(node)
    fitted = pipe.to_pipeline().fit()
    feat_dim = int(
        np.asarray(
            fitted._batch_run(
                jnp.zeros((1, img, img, channels), jnp.uint8)
            )
        ).shape[-1]
    )
    return fitted, feat_dim


__all__ = ["build_featurize_pipeline"]
