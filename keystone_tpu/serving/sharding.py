"""Declarative mesh-sharding of fitted-pipeline parameters.

Every serving tier so far scales the *batch*: ``CompiledPipeline``
shards staged rows over the mesh data axis, lanes replicate whole
engines, the fleet replicates whole processes. None of that serves a
model whose parameters exceed one chip's HBM — a replicated lane needs
the full weight set resident per device, so the model axis was the one
direction the stack could not grow.

This module closes it with the pattern the fmengine/EasyLM family uses
for exactly this problem (SNIPPETS.md [2]): a **declarative rule
layer** mapping regex patterns over the fitted pipeline's *named
parameter pytree* to ``PartitionSpec``s, so any fitted pipeline gets a
partitioning without hand-written per-model specs:

- ``named_params`` walks the pipeline's topo-ordered operators and
  extracts every array-valued dataclass field under a stable
  ``"<topo#>/<OpClass>/<field>"`` name — the namespace the rules match
  against (the same fields ``aot.pipeline_token`` hashes, so the
  param set and the model fingerprint can't drift apart);
- ``match_partition_rules(rules, params)`` resolves each named param
  to the first matching rule's spec. Scalars (and one-element arrays)
  always stay replicated — partitioning a scalar is never right.
  Unmatched params raise by default, or fall back to replicated under
  an explicit ``unmatched="replicate"`` — silent partial sharding is
  how "fits on the mesh" claims go quietly wrong;
- ``make_shard_fns`` / ``make_gather_fns`` turn a spec tree into
  per-param placement callables (``device_put`` under a
  ``NamedSharding``), validating divisibility up front — an uneven
  split fails at rule-resolution time with the param's name, not at
  dispatch time inside XLA;
- ``DEFAULT_RULES`` covers the repo's solver outputs: 2-D weight
  matrices (block least-squares ``W``, the dense mappers) split on
  their output/feature-block axis over ``MODEL_AXIS``, biases, means
  and everything else replicated;
- ``ParamBinder`` is the functionalization seam the engine traces
  through: the extracted params become explicit *arguments* of the
  bucket program (placed once, sharded, reused every dispatch) instead
  of baked-in constants, so each device's executable holds only its
  shard of the weights. The binder patches an engine-private copy of
  the pipeline at trace time — the caller's fitted pipeline is never
  touched, and concurrent traces serialize on the binder's lock;
- ``sharding_token`` digests the resolved spec tree + mesh shape for
  the AOT store fingerprint (a mesh-sharded program must never share a
  serialized-executable entry with a replicated one — see
  ``aot.bucket_key``).

Composition: the spec tree rides a 2-D ``(data, model)`` mesh
(``parallel/mesh.py``), so batch sharding (``shard=``) and model
sharding (``param_sharding=``) are independent axes of the same mesh —
an engine can split rows over ``data`` while splitting weights over
``model``, and XLA inserts the collectives.
"""

from __future__ import annotations

import copy
import dataclasses
import hashlib
import re
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from keystone_tpu.parallel import mesh as mesh_lib

# regex -> PartitionSpec, resolved first-match-wins against the
# "<topo#>/<OpClass>/<field>" param names of ``named_params``
PartitionRules = Sequence[Tuple[str, PartitionSpec]]

# The repo's solver outputs: every fitted linear map stores its weights
# as one (d_in, d_out) / (D, k) matrix named W (BlockLinearMapper,
# LinearMapper, SparseLinearMapper, the bench _Affine chain), so the
# output/feature-block axis is the LAST one — split it over MODEL_AXIS;
# biases, intercepts, means, scaler state stay replicated (they are
# k- or D-vectors, noise next to the matrices). The trailing catch-all
# is what makes this a complete default: any fitted pipeline resolves,
# with only its weight matrices actually split.
DEFAULT_RULES: PartitionRules = (
    (r"/W$", PartitionSpec(None, mesh_lib.MODEL_AXIS)),
    (r".*", PartitionSpec()),
)


def _scrub_caches(op) -> None:
    """Remove an operator's underscore-prefixed lazily-attached caches
    (``_vmapped_apply``, ``_arr_digest_cache``, ...) — instance-dict
    entries only; declared underscore-less fields are untouched."""
    d = getattr(op, "__dict__", None)
    if not d:
        return
    for key in [k for k in d if k.startswith("_")]:
        del d[key]


def _is_array(value: Any) -> bool:
    return isinstance(value, (np.ndarray, jax.Array)) or (
        isinstance(value, np.generic)
    )


def _array_fields(op) -> List[Tuple[str, Any]]:
    """The array-valued parameter fields of one operator, in sorted
    field order — the same field set ``aot.pipeline_token`` hashes
    (declared dataclass fields, else ``__dict__``, underscore-prefixed
    lazily-attached caches excluded)."""
    if dataclasses.is_dataclass(op):
        state = {
            f.name: getattr(op, f.name, None)
            for f in dataclasses.fields(op)
        }
    else:
        state = getattr(op, "__dict__", None) or {}
    return [
        (name, value)
        for name, value in sorted(state.items())
        if not name.startswith("_") and _is_array(value)
    ]


def _iter_param_sites(fitted):
    """Yield ``(op, field, name, value)`` for every array-valued
    operator field — THE walk behind both ``named_params`` and
    ``ParamBinder``, so the two can never disagree on the namespace."""
    for i, nid in enumerate(fitted._topo):
        op = fitted.graph.operators[nid]
        for field, value in _array_fields(op):
            yield op, field, f"{i}/{type(op).__name__}/{field}", value


def named_params(fitted) -> Dict[str, Any]:
    """The fitted pipeline's parameter pytree as a flat
    ``{"<topo#>/<OpClass>/<field>": array}`` dict — the namespace
    partition rules match against. Topo position (not node id) keys
    the name so two structurally-identical pipelines built along
    different construction paths name their params identically.
    Non-array fields (nested model objects, dicts, config scalars)
    are not extracted: they stay baked into the traced program as
    constants, replicated — only what this function names can be
    sharded."""
    return {
        name: value for _, _, name, value in _iter_param_sites(fitted)
    }


def match_partition_rules(
    rules: PartitionRules,
    params: Dict[str, Any],
    *,
    unmatched: str = "error",
) -> Dict[str, PartitionSpec]:
    """Resolve each named param to the first rule whose regex
    ``re.search``-matches its name (SNIPPETS.md [2]'s
    ``match_partition_rules``, over our operator-field namespace).

    Scalars and one-element arrays are always replicated — a rule
    cannot split what has nothing to split. Params no rule matches
    raise a ``ValueError`` naming them (``unmatched="error"``, the
    default — a model silently served half-sharded is the failure
    mode this layer exists to prevent) or fall back to replicated
    under ``unmatched="replicate"``."""
    if unmatched not in ("error", "replicate"):
        raise ValueError(
            f"unmatched must be 'error' or 'replicate', got {unmatched!r}"
        )
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    specs: Dict[str, PartitionSpec] = {}
    missing: List[str] = []
    for name, value in params.items():
        arr = np.asarray(value) if not isinstance(value, jax.Array) else value
        if arr.ndim == 0 or arr.size <= 1:
            specs[name] = PartitionSpec()
            continue
        for pat, spec in compiled:
            if pat.search(name) is not None:
                specs[name] = spec
                break
        else:
            if unmatched == "replicate":
                specs[name] = PartitionSpec()
            else:
                missing.append(name)
    if missing:
        raise ValueError(
            "no partition rule matched param(s) "
            f"{missing} — add a rule, or pass unmatched='replicate' "
            "to fall back to replication explicitly"
        )
    return specs


def _validate_spec(
    name: str, shape: Tuple[int, ...], spec: PartitionSpec, mesh
) -> None:
    """Divisibility check, up front and by name: ``device_put`` under
    an uneven ``NamedSharding`` fails deep inside jax with the global
    shape — this layer owes the caller the param name and the axis."""
    entries = tuple(spec)
    if len(entries) > len(shape):
        raise ValueError(
            f"partition spec {spec} for {name} has more entries than "
            f"the param has dims ({shape})"
        )
    for dim, entry in enumerate(entries):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        n = 1
        for axis in axes:
            if axis not in mesh.shape:
                raise ValueError(
                    f"partition spec {spec} for {name} names mesh "
                    f"axis {axis!r}, but the mesh has "
                    f"{tuple(mesh.axis_names)}"
                )
            n *= mesh.shape[axis]
        if shape[dim] % n:
            raise ValueError(
                f"param {name} dim {dim} (size {shape[dim]}) does not "
                f"divide over {n} shards of mesh axis {entry!r} — "
                "pad the model dim or change the rule"
            )


def make_shard_fns(
    specs: Dict[str, PartitionSpec], mesh=None
) -> Dict[str, Callable[[Any], jax.Array]]:
    """Per-param placement callables: each shards its param over
    ``mesh`` per the resolved spec (``device_put`` under a
    ``NamedSharding`` — the host stages each device's slice, so the
    full array never needs to fit on one device). Divisibility is
    validated per spec entry here, NOT lazily at placement time."""
    mesh = mesh or mesh_lib.current_mesh()

    def make(name: str, spec: PartitionSpec):
        def shard_fn(value: Any) -> jax.Array:
            # validate (axis names, spec length, divisibility) BEFORE
            # building the NamedSharding: jax's own errors carry the
            # global shape, ours carry the param's NAME
            _validate_spec(name, np.shape(value), spec, mesh)
            return jax.device_put(value, NamedSharding(mesh, spec))

        return shard_fn

    return {name: make(name, spec) for name, spec in specs.items()}


def make_gather_fns(
    specs: Dict[str, PartitionSpec], mesh=None
) -> Dict[str, Callable[[Any], jax.Array]]:
    """The inverse placement: each callable re-replicates its (sharded)
    param over the same mesh — checkpointing, debugging, or handing a
    served model back to host code. Gathering a model that only fits
    sharded is the caller's HBM problem; gather per-param, not all at
    once."""
    mesh = mesh or mesh_lib.current_mesh()
    replicated = NamedSharding(mesh, PartitionSpec())

    def make(name: str):
        def gather_fn(value: Any) -> jax.Array:
            return jax.device_put(value, replicated)

        return gather_fn

    return {name: make(name) for name in specs}


def params_nbytes(params: Dict[str, Any]) -> int:
    """Total parameter bytes — what a REPLICATED engine needs resident
    per device (the number the per-chip budget check compares)."""
    return sum(int(np.asarray(v).nbytes) for v in params.values())


def placed_shard_bytes(placed: Dict[str, jax.Array]) -> Dict[Any, int]:
    """Measured per-device parameter bytes of a placed (sharded) param
    tree: device -> resident bytes, summed over every param's actual
    addressable shards. The ground truth behind "this model fits the
    mesh but not one chip" — read off the buffers, not the specs."""
    per_device: Dict[Any, int] = {}
    for arr in placed.values():
        for shard in arr.addressable_shards:
            per_device[shard.device] = (
                per_device.get(shard.device, 0) + int(shard.data.nbytes)
            )
    return per_device


def sharding_token(
    specs: Dict[str, PartitionSpec], mesh=None
) -> str:
    """Content digest of a resolved partitioning — the AOT-store
    fingerprint component for mesh-sharded programs (``aot.bucket_key
    (sharding_token=)``). Covers the spec of every named param AND the
    mesh topology (axis names + sizes): the same rules over a 1x8 and
    a 2x4 mesh compile different programs, and neither may ever load
    the other's serialized executable."""
    mesh = mesh or mesh_lib.current_mesh()
    h = hashlib.sha256()
    h.update(
        b"mesh<"
        + repr(tuple((str(a), int(s)) for a, s in mesh.shape.items())).encode()
        + b">"
    )
    for name in sorted(specs):
        h.update(f"p<{name}|{specs[name]}>".encode())
    return h.hexdigest()


class ParamBinder:
    """Functionalizes a fitted pipeline's parameters: ``run(params,
    arr)`` executes the pipeline's batched apply path with the named
    param values substituted for the stored ones — under ``jax.jit``
    the params become explicit program *arguments* (sharded, placed
    once, reused every dispatch) instead of baked-in constants.

    The binder works on a PRIVATE copy of the pipeline (same graph
    topology, shallow-copied operator objects): trace-time substitution
    mutates operator fields, and the caller's fitted pipeline — shared
    by every other lane, and the thing ``aot.pipeline_token``
    fingerprints — must never observe a tracer in a field. Concurrent
    traces (two buckets warming on different threads) serialize on the
    binder lock; compiled dispatches never enter ``run`` and pay
    nothing."""

    def __init__(self, fitted):
        ops = {
            nid: copy.copy(op)
            for nid, op in fitted.graph.operators.items()
        }
        # drop the copied operators' lazily-attached caches (the
        # underscore-prefixed convention ``aot.pipeline_token`` also
        # relies on): a shallow copy of an already-used pipeline would
        # otherwise SHARE e.g. ``_vmapped_apply`` — a jit closed over
        # the ORIGINAL operator — and substitution would silently not
        # happen
        for op in ops.values():
            _scrub_caches(op)
        graph = dataclasses.replace(fitted.graph, operators=ops)
        # FittedPipeline deferred to call time would be circular-import
        # free too, but the type is needed right here
        self._pipeline = type(fitted)(graph, fitted.source, fitted.sink)
        # (operator, field, name) substitution sites + the pristine
        # values restored after every trace — the same walk that names
        # the params, so sites and namespace can't drift
        self._sites: List[Tuple[Any, str, str]] = []
        self.params: Dict[str, Any] = {}
        for op, field, name, value in _iter_param_sites(self._pipeline):
            self._sites.append((op, field, name))
            self.params[name] = value
        self._lock = threading.Lock()

    def run(self, params: Dict[str, Any], arr: Any) -> Any:
        """The traceable (params, batch) -> outputs path. Executes at
        trace time only; the restore in ``finally`` keeps tracers from
        outliving their trace inside the private pipeline's fields —
        including the lazily-attached caches the trace itself creates
        (``Transformer._jitted_vmap`` builds an inner jit over the
        operator, whose trace cache would otherwise carry this trace's
        param tracers into the next trace)."""
        with self._lock:
            try:
                for op, field, name in self._sites:
                    setattr(op, field, params[name])
                return self._pipeline._batch_run(arr)
            finally:
                for op, field, name in self._sites:
                    setattr(op, field, self.params[name])
                for op in self._pipeline.graph.operators.values():
                    _scrub_caches(op)


def resolve_param_sharding(
    param_sharding: Any,
    fitted,
    *,
    params: Optional[Dict[str, Any]] = None,
    unmatched: str = "error",
) -> Dict[str, PartitionSpec]:
    """Normalize an engine's ``param_sharding=`` argument to a resolved
    ``{name: PartitionSpec}`` tree: ``True`` means ``DEFAULT_RULES``, a
    sequence of ``(regex, PartitionSpec)`` rules is matched against the
    pipeline's named params, and a dict of already-resolved specs
    passes through (validated against the real param names). Callers
    that already extracted the named params (the engine holds its
    binder's) pass them via ``params`` to skip a second walk."""
    if params is None:
        params = named_params(fitted)
    if param_sharding is True:
        return match_partition_rules(
            DEFAULT_RULES, params, unmatched=unmatched
        )
    if isinstance(param_sharding, dict):
        unknown = sorted(set(param_sharding) - set(params))
        if unknown:
            raise ValueError(
                f"param_sharding names unknown params {unknown} "
                f"(have {sorted(params)})"
            )
        specs = {name: PartitionSpec() for name in params}
        specs.update(param_sharding)
        return specs
    return match_partition_rules(
        param_sharding, params, unmatched=unmatched
    )


__all__ = [
    "DEFAULT_RULES",
    "ParamBinder",
    "make_gather_fns",
    "make_shard_fns",
    "match_partition_rules",
    "named_params",
    "params_nbytes",
    "placed_shard_bytes",
    "resolve_param_sharding",
    "sharding_token",
]
