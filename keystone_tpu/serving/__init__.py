"""Serving subsystem: the deployable half of the SURVEY §7 lowering.

A ``FittedPipeline`` is the trained artifact (the reference's
serializable ``FittedPipeline``); this package turns it into a
production inference engine:

- ``CompiledPipeline`` (engine.py): bucketed compiled execution —
  incoming batches are zero-padded up to a fixed set of row buckets so
  steady-state traffic compiles at most ``len(buckets)`` XLA programs,
  with input-buffer donation and an optional mesh-sharded variant.
  ``featurize=`` fuses a second fitted (pure-JAX) featurize pipeline
  in front of the model inside every bucket program — device-side
  featurization: raw uint8 staged (~4× fewer H2D bytes than f32
  features, counted by ``keystone_serving_h2d_bytes_total``), cast +
  featurize + predict in one dispatch. ``param_sharding=`` shards the
  MODEL over the mesh's model axis (see sharding.py below) — models
  bigger than one chip's HBM serve on the mesh.
- ``sharding.py``: the declarative model-sharding layer —
  ``match_partition_rules`` (regex over the fitted pipeline's named
  param pytree -> ``PartitionSpec`` tree), ``make_shard_fns`` /
  ``make_gather_fns`` placement callables, a default rule set for the
  repo's solver outputs (weight matrices split on the output axis,
  biases replicated), and the ``ParamBinder`` functionalization seam
  that turns params into sharded program arguments.
- ``MicroBatcher`` (batching.py): adaptive micro-batching — a
  thread-safe queue that coalesces single-example ``submit()`` requests
  into spec-homogeneous windows (interleaved request streams with
  different shapes each get their own) under a max-latency deadline,
  with a ``swap_engine()`` hook for zero-downtime engine replacement.
- ``LanePipeline`` (pipeline.py): the staged serving lane behind
  ``MicroBatcher(pipeline_depth=N)`` — host-prep (stack or a pluggable
  ``host_featurize`` items-mode hook + pad into a reusable host buffer
  pool), H2D upload, device compute, and deliver run on separate
  threads behind bounded handoff queues, so one window's host work
  overlaps the previous window's device compute. Bit-identical to
  serial dispatch; per-stage spans/metrics with streaming-bench-style
  bottleneck attribution.
- ``ServingMetrics`` (metrics.py): per-bucket compile/dispatch counts,
  request-size histogram, queue depth, p50/p95/p99 latency, windowed
  examples/sec — auto-registered into the process-global
  ``MetricsRegistry`` (``keystone_tpu.observability``) so the admin
  endpoint's ``/metrics`` scrapes every live engine.
- ``suggest_buckets`` (autoscale.py): propose the k-bucket set that
  minimizes expected padding waste over the observed request-size
  histogram (the metrics-driven replacement for operator-chosen
  buckets).
- ``AotStore`` (aot.py): the zero-cold-start layer — each bucket's
  compiled executable serialized into a fingerprinted on-disk store at
  warmup, deserialized + installed BEFORE any trace on the next
  process/engine generation, with silent counted fallback to the
  normal compile path on any miss or mismatch.

Persistent-compile-cache setup lives in
``keystone_tpu.parallel.runtime.setup_compilation_cache`` (a restarted
server warms from disk instead of recompiling); the AOT store dir is
configured beside it (``setup_aot_cache``). The request plane in
FRONT of these engines — admission control, replica lanes, live
re-bucketing, HTTP — is ``keystone_tpu.gateway``.
"""

from keystone_tpu.serving.aot import AotStore
from keystone_tpu.serving.autoscale import padding_waste, suggest_buckets
from keystone_tpu.serving.batching import MicroBatcher
from keystone_tpu.serving.engine import CompiledPipeline
from keystone_tpu.serving.metrics import ServingMetrics
from keystone_tpu.serving.pipeline import (
    HostBufferPool,
    HostFeaturize,
    LanePipeline,
)
from keystone_tpu.serving.sharding import (
    DEFAULT_RULES,
    make_gather_fns,
    make_shard_fns,
    match_partition_rules,
    named_params,
)

__all__ = [
    "AotStore",
    "CompiledPipeline",
    "DEFAULT_RULES",
    "HostBufferPool",
    "HostFeaturize",
    "LanePipeline",
    "MicroBatcher",
    "ServingMetrics",
    "make_gather_fns",
    "make_shard_fns",
    "match_partition_rules",
    "named_params",
    "padding_waste",
    "suggest_buckets",
]
