"""Serving subsystem: the deployable half of the SURVEY §7 lowering.

A ``FittedPipeline`` is the trained artifact (the reference's
serializable ``FittedPipeline``); this package turns it into a
production inference engine:

- ``CompiledPipeline`` (engine.py): bucketed compiled execution —
  incoming batches are zero-padded up to a fixed set of row buckets so
  steady-state traffic compiles at most ``len(buckets)`` XLA programs,
  with input-buffer donation and an optional mesh-sharded variant.
- ``MicroBatcher`` (batching.py): adaptive micro-batching — a
  thread-safe queue that coalesces single-example ``submit()`` requests
  into the smallest covering bucket under a max-latency deadline.
- ``ServingMetrics`` (metrics.py): per-bucket compile/dispatch counts,
  queue depth, p50/p99 latency, examples/sec.

Persistent-compile-cache setup lives in
``keystone_tpu.parallel.runtime.setup_compilation_cache`` (a restarted
server warms from disk instead of recompiling).
"""

from keystone_tpu.serving.batching import MicroBatcher
from keystone_tpu.serving.engine import CompiledPipeline
from keystone_tpu.serving.metrics import ServingMetrics

__all__ = ["CompiledPipeline", "MicroBatcher", "ServingMetrics"]
