"""Bucket autoscaling: propose a bucket set from observed traffic.

Today the engine's row buckets are operator-chosen; this module closes
the ROADMAP loop ("autoscale the bucket set from observed traffic") by
reading the per-request size histogram a live ``ServingMetrics``
accumulates (``request_sizes``: valid rows per dispatch) and proposing
the ``k``-bucket set that minimizes expected padding waste — the
Clipper-style move of letting measured traffic drive the batching
policy instead of a config constant.

The optimization is exact: with sizes sorted ascending, an optimal
bucket set assigns each size to the smallest covering bucket, so
buckets partition the sizes into contiguous segments and each segment's
bucket must be its maximum size (any larger only adds padding). That
makes it a classic 1-D DP over segment boundaries —
``cost(i..j) = Σ count_s · (size_j − size_s)`` for sizes i..j — solved
in O(m²k) for m distinct observed sizes, which is tiny (m is bounded
by the largest bucket, typically ≤ a few hundred).

Deployment loop: scrape sizes (``/metrics`` exports them as
``keystone_serving_request_size_total``), call ``suggest_buckets``,
build a fresh ``CompiledPipeline`` with the proposal, warm it, swap.

``padding_waste`` is the OFFLINE model; the live truth is the
per-bucket goodput accounting every dispatch records
(``keystone_serving_goodput_rows_total`` / ``padded_rows_total`` and
the ``padding_efficiency`` gauge, serving/metrics.py).
``predicted_efficiency`` bridges the two so the gateway can log
model-vs-observed at each re-bucket and the bench can assert they
agree.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

from keystone_tpu.serving.metrics import ServingMetrics

Histogram = Dict[int, int]


def _histogram_of(
    source: Union[ServingMetrics, Histogram]
) -> Histogram:
    if isinstance(source, ServingMetrics):
        source = source.request_sizes.snapshot()
    hist = {int(s): int(c) for s, c in source.items() if c > 0 and s and int(s) > 0}
    return hist


def padding_waste(hist: Histogram, buckets: Sequence[int]) -> int:
    """Total padded rows shipped serving ``hist`` through ``buckets``
    (requests above the largest bucket chunk through it, matching
    ``CompiledPipeline.apply``)."""
    buckets = sorted(buckets)
    top = buckets[-1]
    waste = 0
    for size, count in hist.items():
        tail = size % top if size > top else size
        if tail:
            covering = next(b for b in buckets if tail <= b)
            waste += (covering - tail) * count
    return waste


def predicted_efficiency(
    source: Union[ServingMetrics, Histogram], buckets: Sequence[int]
) -> Optional[float]:
    """The padding efficiency (valid rows over all rows shipped) the
    ``padding_waste`` model PREDICTS for serving ``source``'s histogram
    through ``buckets`` — the offline counterpart of the live
    ``ServingMetrics.padding_efficiency`` gauge, which is what makes
    ``suggest_buckets`` decisions auditable: the gateway logs observed
    efficiency next to this prediction at every re-bucket. None on an
    empty histogram."""
    hist = _histogram_of(source)
    valid = sum(size * count for size, count in hist.items())
    if not valid:
        return None
    return valid / (valid + padding_waste(hist, buckets))


def suggest_buckets(
    metrics: Union[ServingMetrics, Histogram],
    k: int,
    max_bucket: Optional[int] = None,
) -> Tuple[int, ...]:
    """The ≤``k``-bucket set minimizing expected padded rows over the
    observed per-request size histogram.

    ``metrics`` is a live ``ServingMetrics`` or a plain
    ``{size: count}`` histogram. ``max_bucket`` forces the largest
    bucket (it is always in the returned set — chunking needs it):
    observed sizes above it are modeled exactly as serving would pay
    for them (full ``max_bucket`` chunks are waste-free, only the
    ``size % max_bucket`` tail pads), matching ``padding_waste`` and
    ``CompiledPipeline.apply``. Returns an ascending tuple, possibly
    shorter than ``k`` when fewer distinct sizes were seen.

    Raises ``ValueError`` on an empty histogram — a proposal from zero
    observations would just be noise.
    """
    if k < 1:
        raise ValueError(f"need k >= 1 buckets, got {k}")
    hist = _histogram_of(metrics)
    if max_bucket is not None:
        folded: Histogram = {}
        for size, count in hist.items():
            if size > max_bucket:
                # serving-time chunking: full chunks pad nothing; the
                # tail is what the lower buckets have to cover
                size = size % max_bucket
                if size == 0:
                    continue
            folded[size] = folded.get(size, 0) + count
        hist = folded
        if not hist and _histogram_of(metrics):
            # all traffic chunks evenly through the forced bucket
            return (max_bucket,)
    if not hist:
        raise ValueError(
            "no observed request sizes to propose buckets from"
        )
    if max_bucket is not None:
        # a zero-count pseudo-size so the DP's top segment lands on the
        # forced bucket (its own waste contribution is zero)
        hist = dict(hist)
        hist[max_bucket] = hist.get(max_bucket, 0)

    sizes = sorted(hist)
    counts = [hist[s] for s in sizes]
    m = len(sizes)
    if m <= k:
        return tuple(sizes)

    # seg_cost[i][j]: padded rows if sizes[i..j] share bucket sizes[j]
    pref = [0] * (m + 1)  # pref[t] = counts[0] + ... + counts[t-1]
    for t in range(m):
        pref[t + 1] = pref[t] + counts[t]
    seg_cost = [[0] * m for _ in range(m)]
    for i in range(m):
        acc = 0
        for j in range(i + 1, m):
            # going j-1 -> j raises the segment bucket to sizes[j]:
            # every request in sizes[i..j-1] pays the difference
            acc += (sizes[j] - sizes[j - 1]) * (pref[j] - pref[i])
            seg_cost[i][j] = acc

    INF = float("inf")
    # best[j][b]: min waste covering sizes[0..j] with exactly b buckets
    best = [[INF] * (k + 1) for _ in range(m)]
    cut = [[-1] * (k + 1) for _ in range(m)]
    for j in range(m):
        best[j][1] = seg_cost[0][j]
    for b in range(2, k + 1):
        for j in range(b - 1, m):
            for i in range(b - 1, j + 1):
                # last segment is sizes[i..j]
                prev = best[i - 1][b - 1]
                if prev + seg_cost[i][j] < best[j][b]:
                    best[j][b] = prev + seg_cost[i][j]
                    cut[j][b] = i

    buckets = []
    j, b = m - 1, k
    while b >= 1:
        if b == 1:
            buckets.append(sizes[j])
            break
        i = cut[j][b]
        buckets.append(sizes[j])
        j, b = i - 1, b - 1
    return tuple(sorted(buckets))
