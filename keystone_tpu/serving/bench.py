"""Serving benchmarks: the tracked serving metrics.

- ``serving_cold_vs_warm_latency`` — one shape, cold (trace + XLA
  compile + dispatch) vs warm (compiled dispatch) latency through the
  engine; ``speedup`` is the whole point of bucketing + warmup + the
  persistent compile cache, and the acceptance floor is warm p50 >= 10x
  faster than cold.
- ``serving_bucketed_throughput`` — examples/sec through a bucketed
  engine fed every batch size 1..max_bucket (the steady-state traffic
  mix that would recompile per-request without buckets), with the
  engine's compile/padding counters attached.
- ``serving_microbatch_p99`` — p99 end-to-end request latency of
  concurrent single-example ``submit()``s coalesced by the
  ``MicroBatcher`` under a small deadline.
- ``serving_gateway_p99`` — the same concurrent single-example load
  pushed through the FULL request plane (``keystone_tpu/gateway/``:
  admission -> lane routing -> micro-batch -> engine); the delta over
  ``serving_microbatch_p99`` prices the gateway layer. The value is
  read by scraping the gateway's ``/metrics`` histogram (PromQL-style
  ``histogram_quantile`` over the exported ``le`` buckets), so the
  regression row IS the series operators alert on.
- ``serving_swap_blip`` — p99 latency of requests issued while a forced
  live engine swap runs under steady load (zero failures asserted) —
  the cost of closing the autoscale loop live.
- ``serving_pipeline_overlap`` — sustained lane throughput of a
  PIPELINED ``MicroBatcher`` (staged host-prep/upload/compute/deliver,
  serving/pipeline.py) vs the serial batcher on a workload whose
  host featurize is a non-trivial fraction of window time, with
  per-stage standalone rates, bottleneck attribution, and
  ``overlap_efficiency`` mirroring ``bench_imagenet_stream_featurize``'s
  model (one-sided ``>= 0.8`` assert; outputs bit-identical asserted).
- ``serving_goodput_mfu`` — device-truth accounting under mixed-size
  traffic: measured padding efficiency off the live per-bucket goodput
  counters, asserted against the ``padding_waste`` model's prediction
  for the same observed histogram (the offline estimate the live
  counters supersede must agree with reality), plus modeled device
  FLOPs, the rolling MFU gauge, and each bucket's roofline class where
  hardware peaks are known (``KEYSTONE_PEAK_FLOPS`` /
  ``KEYSTONE_PEAK_MEMBW_GBPS`` override for unlisted hardware; without
  peaks those fields report null — never fabricated zeros).
- ``serving_device_featurize`` — the device-side featurization A/B
  (``--featurize``/``--featurize-only``; run by
  ``bin/smoke-featurize.sh``): the same image featurize chain + model
  served through a ``host_featurize`` gateway (prep stage featurizes
  on host, engine stages f32 features) vs a ``device_featurize``
  gateway (raw uint8 staged, cast + featurize + predict fused into one
  per-bucket XLA program). Asserted: outputs allclose, device-path H2D
  bytes/request <= 1/3 of the host path (off the engines' own
  ``keystone_serving_h2d_bytes_total`` counters), and sustained
  device-path examples/sec >= host. Headline: device examples/sec.
- ``serving_sharded_vs_replicated`` — the model-axis row
  (``--shard``/``--shard-only``; run by ``bin/smoke-shard.sh``): the
  SAME fitted model served mesh-sharded (one lane,
  ``param_sharding=True`` over a (1, N)-device mesh —
  serving/sharding.py's default partition rules split every weight
  matrix over the model axis and the params become sharded program
  arguments) vs N replicated lanes, swept over model sizes. Asserted:
  sharded outputs allclose to replicated at every size both can
  serve, and the **over-one-device-budget model** — whose total
  parameter bytes exceed the row's per-chip budget, so the replicated
  path refuses to build — serves sharded with its measured
  max-per-device parameter bytes (read off the placed buffers'
  actual shards, not the specs) inside the budget. The row JSON
  carries the crossover curve: per model size, parameter MB,
  sharded vs replicated examples/sec. Headline: sharded
  examples/sec on the over-budget model. Needs >= 2 devices
  (``XLA_FLAGS=--xla_force_host_platform_device_count=8`` on CPU).
- ``serving_chaos_lane_kill`` / ``serving_chaos_prep_stall`` — the
  chaos-harness regression rows (``--chaos``; run by
  ``bin/smoke-chaos.sh``): sustained open-loop load through a full
  gateway while a fault point fires mid-window (one lane killed /
  the pipelined host-prep stage stalled), with the
  ``loadgen/invariants.py`` verdict ASSERTED in the row — every
  admitted request resolves, failures are typed sheds only (zero
  untyped 500s), readiness recovers once the fault clears, and p99
  returns to within 1.5x the pre-fault value within 10 s of the
  fault clearing. The headline value is the post/pre p99 ratio.
- ``serving_router_failover`` — the fleet tier's regression row
  (``--fleet``/``--fleet-only``; run by ``bin/smoke-fleet.sh``):
  open-loop load through the ``keystone_tpu/fleet/`` router fronting
  TWO in-process gateway replicas while one replica's responses are
  black-holed mid-run (``router.replica.blackhole`` — the HTTP-level
  equivalent of killing the process). The invariant verdict is
  asserted (nothing lost, typed sheds only, readiness holds,
  recovered p99 within 1.5x pre-fault) and the headline fleet p99
  comes from the ROUTER'S OWN federated ``/metrics`` — per-replica
  ``le`` buckets merged by ``prometheus.merge_histograms`` — so the
  row proves the federation surface, not a bench-local stopwatch.
- ``serving_autoscale_ramp`` — the elasticity row (``--autoscale``/
  ``--autoscale-only``; run by ``bin/smoke-autoscale.sh``): a
  step-load ramp (low → ~3x-one-replica surge → low, rates calibrated
  to the host) through a live ``keystone_tpu/autoscale/`` control
  loop — router + supervisor + SLO-driven policy over in-process
  replicas — with ``router.replica.partition`` severing the original
  replica mid-scale-up. Asserted: the fleet scales out (>= 2
  replicas), the loadgen invariant verdict stays green (nothing
  lost, typed sheds only, p99 recovers after the partition clears),
  the partition actually fired, and the fleet drain-retires back to
  the 1-replica baseline once the load drops. Headline: the
  recovered post-fault p99.

Callable standalone (``python -m keystone_tpu serve-bench``) or from
the repo-level ``bench.py`` which passes its own ``emit`` so rows land
in the round's BENCH JSON with ``vs_baseline`` wiring (null for now —
the reference published no serving numbers; the field exists so future
rounds can ratio against THESE rows). ``--profile-dir DIR`` wraps the
whole run in a ``jax.profiler`` trace (``utils/profiling.trace``), so
any existing row can be captured for Perfetto/XProf without code
edits.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Any, Sequence

import jax.numpy as jnp
import numpy as np

from keystone_tpu.workflow.api import Transformer


@dataclasses.dataclass(eq=False)
class _Affine(Transformer):
    """Per-example tanh(x @ W + b) — enough real work per node that the
    staged program isn't trivially constant-folded."""

    W: Any
    b: Any

    def apply(self, x):
        return jnp.tanh(x @ self.W + self.b)


def build_pipeline(
    d: int = 256, hidden: int = 512, depth: int = 4, seed: int = 0
):
    """An estimator-free array-mode chain -> FittedPipeline (depth
    matmul nodes: a realistic compile cost for the cold/warm row).
    ``seed`` varies the weights — the zoo spec loader uses it so two
    same-shaped models carry distinct params (and therefore distinct
    AOT model tokens)."""
    rng = np.random.default_rng(seed)
    dims = [d] + [hidden] * (depth - 1) + [d]
    pipe = None
    for i in range(depth):
        w = jnp.asarray(
            rng.standard_normal((dims[i], dims[i + 1])).astype(np.float32)
            / np.sqrt(dims[i])
        )
        b = jnp.asarray(np.zeros(dims[i + 1], np.float32))
        node = _Affine(w, b)
        pipe = node.to_pipeline() if pipe is None else pipe.and_then(node)
    return pipe.to_pipeline().fit()


def affine_head(W, b):
    """One ``tanh(x @ W + b)`` node as a standalone FittedPipeline —
    the refittable HEAD the online-lifecycle loop re-solves.
    ``base.and_then(affine_head(W, b))`` composes it back onto a
    feature base; with the weights drawn by ``build_split_pipeline``
    the composition is the same graph ``build_pipeline`` builds."""
    W = jnp.asarray(np.asarray(W, np.float32))
    b = jnp.asarray(np.asarray(b, np.float32))
    return _Affine(W, b).to_pipeline().to_pipeline().fit()


def build_split_pipeline(
    d: int = 256, hidden: int = 512, depth: int = 4, seed: int = 0
):
    """``build_pipeline`` split at the last layer: returns
    ``(base, W, b)`` where ``base`` is the first ``depth - 1`` layers
    (the frozen featurizer the refit accumulator reads activations
    from) and ``(W, b)`` is the final layer's weights.
    ``base.and_then(affine_head(W, b))`` serves OUTPUTS BITWISE EQUAL
    to ``build_pipeline(d, hidden, depth, seed)`` — the rng stream is
    drawn in the identical order — so a gateway can boot on the split
    form and the lifecycle loop can re-solve just the head."""
    if depth < 2:
        raise ValueError(f"split needs depth >= 2, got {depth}")
    rng = np.random.default_rng(seed)
    dims = [d] + [hidden] * (depth - 1) + [d]
    pipe = None
    for i in range(depth - 1):
        w = jnp.asarray(
            rng.standard_normal((dims[i], dims[i + 1])).astype(np.float32)
            / np.sqrt(dims[i])
        )
        b = jnp.asarray(np.zeros(dims[i + 1], np.float32))
        node = _Affine(w, b)
        pipe = node.to_pipeline() if pipe is None else pipe.and_then(node)
    head_w = jnp.asarray(
        rng.standard_normal((dims[depth - 1], dims[depth])).astype(
            np.float32
        )
        / np.sqrt(dims[depth - 1])
    )
    head_b = jnp.asarray(np.zeros(dims[depth], np.float32))
    return pipe.to_pipeline().fit(), head_w, head_b


def bench_cold_vs_warm(
    emit, fitted, buckets: Sequence[int], d: int, warm_reps: int = 30
) -> None:
    import jax

    # the cold number must measure a REAL XLA compile, so BOTH caches
    # are detached: aot_store=False keeps the serialized-executable
    # store out (it only engages at warmup(), which this row never
    # calls — the explicit False makes the contract load-bearing
    # instead of incidental), and the persistent compile cache is
    # unhooked below for exactly the first dispatch (with it wired —
    # bench.py main() does — a rerun would replay the executable from
    # disk and deflate cold_ms)
    engine = fitted.compiled(buckets=buckets, aot_store=False)
    rng = np.random.default_rng(1)
    n = max(1, buckets[0] - 1)  # padded path, not the exact bucket size
    x = rng.standard_normal((n, d)).astype(np.float32)

    cache_dir = None
    try:
        cache_dir = jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir", None)
    except AttributeError:
        pass
    try:
        t0 = time.perf_counter()
        engine.apply(x, sync=True)
        cold_ms = (time.perf_counter() - t0) * 1e3
    finally:
        if cache_dir is not None:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
    if engine.metrics.compile_count != 1:
        raise RuntimeError(
            "cold apply expected exactly one compile: "
            + str(engine.metrics.summary())
        )

    warm = []
    for _ in range(warm_reps):
        t0 = time.perf_counter()
        engine.apply(x, sync=True)
        warm.append((time.perf_counter() - t0) * 1e3)
    if engine.metrics.compile_count != 1:
        raise RuntimeError(
            "warm dispatches retraced: " + str(engine.metrics.summary())
        )
    warm_p50 = float(np.percentile(warm, 50))
    speedup = cold_ms / warm_p50
    emit(
        "serving_cold_vs_warm_latency", cold_ms, "ms",
        extra={
            "warm_p50_ms": round(warm_p50, 3),
            "warm_p99_ms": round(float(np.percentile(warm, 99)), 3),
            "speedup": round(speedup, 1),
            "bucket": engine.bucket_for(n),
            "batch": n,
        },
    )


def bench_bucketed_throughput(
    emit, fitted, buckets: Sequence[int], d: int, passes: int = 3
) -> None:
    engine = fitted.compiled(buckets=buckets)
    rng = np.random.default_rng(2)
    mb = engine.max_bucket
    # every size when small, else a spread hitting every bucket + edges
    # (a remote-dispatch device costs ~100 ms per sync, so the full
    # 1..max sweep would measure the tunnel, not the engine)
    if mb <= 32:
        sizes = list(range(1, mb + 1))
    else:
        sizes = sorted(
            set(int(s) for s in rng.integers(1, mb + 1, 24))
            | set(engine.buckets) | {1, mb}
        )
    xs = {
        n: rng.standard_normal((n, d)).astype(np.float32) for n in sizes
    }
    engine.warmup(example=jnp.zeros((d,), jnp.float32))
    served = 0
    t0 = time.perf_counter()
    for _ in range(passes):
        for n, x in xs.items():
            engine.apply(x, sync=True)
            served += n
    dt = time.perf_counter() - t0
    summary = engine.metrics.summary()
    if engine.metrics.compile_count > len(engine.buckets):
        raise RuntimeError(f"recompile bound broken: {summary}")
    emit(
        "serving_bucketed_throughput", served / dt, "examples/sec",
        extra={
            "distinct_batch_sizes": len(xs),
            "compiles": engine.metrics.compile_count,
            "buckets": list(engine.buckets),
            "padded_rows": summary["padded_rows"],
            "dispatch_p50_ms": summary["dispatch_p50_ms"],
            "dispatch_p99_ms": summary["dispatch_p99_ms"],
        },
    )


def bench_microbatch(
    emit, fitted, buckets: Sequence[int], d: int,
    n_requests: int = 256, n_threads: int = 8, max_delay_ms: float = 2.0,
) -> None:
    from keystone_tpu.serving.batching import MicroBatcher

    engine = fitted.compiled(buckets=buckets)
    engine.warmup(example=jnp.zeros((d,), jnp.float32))
    rng = np.random.default_rng(3)
    examples = rng.standard_normal((n_requests, d)).astype(np.float32)
    futures = [None] * n_requests
    t0 = time.perf_counter()
    with MicroBatcher(engine, max_delay_ms=max_delay_ms) as mb:

        def client(tid):
            for i in range(tid, n_requests, n_threads):
                futures[i] = mb.submit(examples[i])

        threads = [
            threading.Thread(target=client, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for f in futures:
            f.result(timeout=30)
    dt = time.perf_counter() - t0
    m = engine.metrics
    p99 = m.request_latency.p99
    emit(
        "serving_microbatch_p99", (p99 or 0.0) * 1e3, "ms",
        extra={
            "requests": n_requests,
            "client_threads": n_threads,
            "max_delay_ms": max_delay_ms,
            "request_p50_ms": round((m.request_latency.p50 or 0) * 1e3, 3),
            "max_coalesced": m.max_coalesced,
            "dispatches": m.dispatches.total,
            "requests_per_sec": round(n_requests / dt, 1),
        },
    )


def bench_gateway(
    emit, fitted, buckets: Sequence[int], d: int,
    n_requests: int = 256, n_threads: int = 8, n_lanes: int = 2,
) -> None:
    """``serving_gateway_p99`` — p99 end-to-end latency through the FULL
    request plane (admission queue -> lane routing -> micro-batch ->
    engine) under concurrent load; comparable against the bare
    ``serving_microbatch_p99`` row to price the gateway layer.

    The headline value is read by SCRAPING the gateway's own
    ``/metrics`` (``keystone_gateway_request_latency_seconds`` buckets
    -> ``histogram_quantile`` interpolation) rather than bench-local
    stopwatches — the regression number is provably the same series
    operators alert on. The client-side measurement rides along in
    ``extra`` for cross-checking bucket-resolution error."""
    import urllib.request

    import jax.numpy as jnp

    from keystone_tpu.gateway import Gateway, GatewayServer
    from keystone_tpu.gateway.admission import Overloaded
    from keystone_tpu.observability.prometheus import (
        histogram_buckets,
        quantile_from_buckets,
    )

    rng = np.random.default_rng(4)
    examples = rng.standard_normal((n_requests, d)).astype(np.float32)
    with Gateway(
        fitted, buckets=buckets, n_lanes=n_lanes, max_delay_ms=2.0,
        warmup_example=jnp.zeros((d,), jnp.float32),
        name="bench-gateway",
    ) as gw:
        # each client thread times its own requests SYNCHRONOUSLY
        # (submit -> result), so a latency is recorded exactly when its
        # request resolves — no done-callback race — and a shed predict
        # is counted instead of crashing the bench
        latencies = []
        lock = threading.Lock()
        t0 = time.perf_counter()

        def client(tid):
            for i in range(tid, n_requests, n_threads):
                t = time.perf_counter()
                try:
                    gw.predict(examples[i]).result(timeout=60)
                except Overloaded:
                    continue  # shows up in the shed counter
                with lock:
                    latencies.append(time.perf_counter() - t)

        threads = [
            threading.Thread(target=client, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        m = gw.metrics
        if not latencies:
            raise RuntimeError(
                "gateway bench: every request was shed; summary="
                + str(m.registry.varz().get(
                    "keystone_gateway_shed_total"
                ))
            )
        # the regression number comes off the wire: scrape /metrics
        # exactly like an operator's Prometheus would and compute the
        # quantile from the exported le buckets
        with GatewayServer(gw, port=0, registry=m.registry) as srv:
            with urllib.request.urlopen(
                srv.url("/metrics"), timeout=15
            ) as resp:
                exposition = resp.read().decode("utf-8")
        buckets_scraped = histogram_buckets(
            exposition,
            "keystone_gateway_request_latency_seconds",
            {"gateway": gw.name},
        )
        p99_s = quantile_from_buckets(0.99, buckets_scraped)
        if p99_s is None:
            raise RuntimeError(
                "gateway bench: /metrics had no latency buckets:\n"
                + exposition
            )
        emit(
            "serving_gateway_p99",
            p99_s * 1e3, "ms",
            extra={
                "source": "scraped /metrics histogram_quantile",
                "requests": n_requests,
                "served": len(latencies),
                "client_threads": n_threads,
                "lanes": n_lanes,
                "client_p99_ms": round(
                    float(np.percentile(latencies, 99)) * 1e3, 3
                ),
                "p50_ms": round(
                    (quantile_from_buckets(0.5, buckets_scraped) or 0)
                    * 1e3, 3
                ),
                "requests_per_sec": round(len(latencies) / dt, 1),
                "shed": int(m.outcome_count("shed")),
                "errors": int(m.outcome_count("error")),
                "retries": int(m.retry_count()),
            },
        )


def bench_swap_blip(
    emit, fitted, buckets: Sequence[int], d: int,
    n_requests: int = 256, n_threads: int = 4,
) -> None:
    """``serving_swap_blip`` — p99 latency of requests issued WHILE a
    forced live engine swap (build + warm + atomic re-point + drain)
    runs under steady load, with the zero-failure requirement asserted;
    the blip is the price of closing the autoscale loop live."""
    import jax.numpy as jnp

    from keystone_tpu.gateway import Gateway

    rng = np.random.default_rng(5)
    examples = rng.standard_normal((n_requests, d)).astype(np.float32)
    with Gateway(
        fitted, buckets=buckets, n_lanes=2, max_delay_ms=2.0,
        warmup_example=jnp.zeros((d,), jnp.float32),
        name="bench-swap",
    ) as gw:
        latencies = [0.0] * n_requests
        failures = [0]
        swap_s = [0.0]

        def client(tid):
            for i in range(tid, n_requests, n_threads):
                t = time.perf_counter()
                try:
                    gw.predict(examples[i]).result(timeout=60)
                except Exception:
                    failures[0] += 1
                latencies[i] = time.perf_counter() - t

        threads = [
            threading.Thread(target=client, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        t0 = time.perf_counter()
        gw.rebucket(force=True)  # the live swap, mid-load
        swap_s[0] = time.perf_counter() - t0
        for t in threads:
            t.join()
        if failures[0] != 0:
            raise RuntimeError(
                f"{failures[0]} requests failed across the live swap"
            )
        emit(
            "serving_swap_blip",
            float(np.percentile(latencies, 99)) * 1e3, "ms",
            extra={
                "requests": n_requests,
                "p50_ms": round(
                    float(np.percentile(latencies, 50)) * 1e3, 3
                ),
                "swap_wall_ms": round(swap_s[0] * 1e3, 1),
                "swaps": int(gw.metrics.swap_count()),
                "failures": failures[0],
                "buckets_after": list(gw.buckets),
            },
        )


def bench_online_refit(
    emit,
    d: int = 24,
    hidden: int = 32,
    depth: int = 3,
    buckets: Sequence[int] = (4, 16),
    n_threads: int = 4,
    max_ticks: int = 60,
) -> None:
    """``serving_online_refit`` — the full online-lifecycle loop, both
    directions, under open-loop load:

    1. PROMOTION: the gateway serves a STALE head (the teacher's final
       layer was redrawn); labeled feedback streams in; the controller
       solves a candidate and walks it shadow → canary → promoted
       (atomic engine swap) while client threads hammer /predict.
       Asserted: ZERO failed requests across the whole rollout (the
       swap-blip discipline of ``serving_swap_blip``), the candidate's
       held-out error BEATS the stale incumbent's, and the promoted
       model now serves.
    2. ROLLBACK: ``lifecycle.refit.poison`` is armed, so the next
       feedback window folds garbage into the normal equations; the
       solved candidate must be caught by the held-out accuracy gate
       and auto-rolled back within ONE policy tick of entering shadow
       — with the incumbent's serving never perturbed (candidates
       only ever saw mirrored traffic).

    The emitted value is the p99 client latency across phase 1 — the
    price of running an entire model rollout under live load."""
    import jax.numpy as jnp

    from keystone_tpu.gateway import Gateway
    from keystone_tpu.lifecycle.controller import LifecycleController
    from keystone_tpu.lifecycle.policy import PromotionConfig
    from keystone_tpu.lifecycle.teacher import teacher_labels
    from keystone_tpu.loadgen import faults

    head_seed = 77  # the teacher the refit must catch up to
    base, head_w, head_b = build_split_pipeline(
        d=d, hidden=hidden, depth=depth, seed=0
    )
    stale = base.and_then(affine_head(head_w, head_b))
    rng = np.random.default_rng(11)
    examples = rng.standard_normal((256, d)).astype(np.float32)

    def labeled(n):
        xs = rng.standard_normal((n, d)).astype(np.float32)
        return xs, teacher_labels(
            xs, d, hidden, depth, seed=0, head_seed=head_seed
        )

    with Gateway(
        stale, buckets=buckets, n_lanes=2, max_delay_ms=2.0,
        warmup_example=jnp.zeros((d,), jnp.float32),
        name="bench-lifecycle",
    ) as gw:
        ctrl = LifecycleController(
            gw, base=base, head_builder=affine_head,
            feature_dim=hidden, out_dim=d, name="bench",
            config=PromotionConfig(
                min_shadow_pairs=8, min_canary_requests=8,
                promote_after_healthy_ticks=1,
            ),
            canary_fraction=0.25, min_refit_samples=128,
            interval_s=None, refit_chunk=32,
        )
        stop = threading.Event()
        lat: list = [[] for _ in range(n_threads)]
        fails = [0] * n_threads

        def client(tid):
            i = tid
            while not stop.is_set():
                t = time.perf_counter()
                try:
                    gw.predict(
                        examples[i % len(examples)]
                    ).result(timeout=60)
                except Exception:
                    fails[tid] += 1
                lat[tid].append(time.perf_counter() - t)
                i += n_threads

        threads = [
            threading.Thread(target=client, args=(t,), daemon=True)
            for t in range(n_threads)
        ]
        try:
            # -- phase 1: promotion under load
            ctrl.add_feedback(*labeled(384))
            for t in threads:
                t.start()
            t0 = time.perf_counter()
            ticks = 0
            status = ctrl.status()
            while status["state"] != "promoted" and ticks < max_ticks:
                status = ctrl.tick()
                ticks += 1
                time.sleep(0.05)  # let mirrored/canary traffic flow
            promote_s = time.perf_counter() - t0
            cand_err = status["errors"]["candidate"]
            inc_err = status["errors"]["incumbent"]
            if status["state"] != "promoted":
                raise RuntimeError(
                    f"candidate not promoted after {ticks} ticks: "
                    f"{status}"
                )
            if not (cand_err is not None and inc_err is not None
                    and cand_err < inc_err):
                raise RuntimeError(
                    "promoted candidate does not beat the stale "
                    f"incumbent on held-out labels: candidate="
                    f"{cand_err} incumbent={inc_err}"
                )
            # -- phase 2: poisoned refit must auto-roll back
            faults.get_injector().arm(
                "lifecycle.refit.poison", count=8
            )
            try:
                ctrl.add_feedback(*labeled(384))
                status = ctrl.tick()  # solves v2, arms its shadow
                rb_ticks = 0
                while (status["state"] != "rolled_back"
                       and rb_ticks < 3):
                    status = ctrl.tick()
                    rb_ticks += 1
            finally:
                faults.get_injector().disarm("lifecycle.refit.poison")
            if status["state"] != "rolled_back":
                raise RuntimeError(
                    f"poisoned candidate was not rolled back: {status}"
                )
            if rb_ticks > 1:
                raise RuntimeError(
                    "rollback took more than one policy tick after "
                    f"shadow start ({rb_ticks})"
                )
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
            ctrl.close()
        failures = sum(fails)
        if failures:
            raise RuntimeError(
                f"{failures} requests failed across the live rollout"
            )
        latencies = [x for sub in lat for x in sub]
        emit(
            "serving_online_refit",
            float(np.percentile(latencies, 99)) * 1e3, "ms",
            extra={
                "requests": len(latencies),
                "failures": failures,
                "ticks_to_promote": ticks,
                "promote_wall_s": round(promote_s, 2),
                "candidate_err": cand_err,
                "incumbent_err": inc_err,
                "rollback_reason": status["last_reason"],
                "rollback_ticks_after_shadow": rb_ticks,
                "promotions": status["promotions"],
            },
        )


def bench_pipeline_overlap(
    emit, fitted, buckets: Sequence[int], d: int,
    n_windows: int = 32, prep_latency_ms: float = 10.0,
    pipeline_depth: int = 2,
) -> None:
    """``serving_pipeline_overlap`` — the tentpole's regression row:
    the same items-mode workload through a SERIAL lane and a PIPELINED
    lane. The host featurize models a LATENCY-bound front-end — a
    tokenizer RPC / feature-store fetch with a fixed per-window service
    time plus light host assembly — which is both the realistic
    items-mode profile and the honest overlap demonstration on a
    CPU-backend host: there the "device" compute shares the host's
    cores, so a host-FLOP-burning prep stage has nothing spare to
    overlap INTO (serial already saturates the machine), exactly like
    the streaming featurize bench's remote-tunnel upload stage is
    latency-bound rather than core-bound. Serial pays
    prep + upload + compute per window end-to-end; the staged pipeline
    runs window k+1's prep wait under window k's device compute, so
    sustained throughput approaches the bottleneck stage's standalone
    rate instead of the stages' sum.

    Mirrors ``bench_imagenet_stream_featurize``'s model: per-stage
    standalone rates (1 / mean busy seconds, off the lane's own
    ``ServingMetrics``), min-rate ``bottleneck`` attribution, and
    ``overlap_efficiency`` = sustained window rate / bottleneck rate,
    asserted one-sided ``>= 0.8`` (stage busy-times are measured UNDER
    overlap — contention inflates them — so the model is conservative
    and efficiency may exceed 1.0). On hosts with >= 2 cores the row
    also asserts the acceptance floor: pipelined sustained >= 1.2x
    serial. Outputs are asserted BIT-identical between the two modes."""
    import os

    from keystone_tpu.serving.batching import MicroBatcher

    window = max(buckets)
    rng = np.random.default_rng(6)
    scale = np.linspace(0.5, 1.5, d).astype(np.float32)
    items = rng.standard_normal(
        (n_windows * window, d)
    ).astype(np.float32)

    def featurize(raw):
        # items-mode front-end: fixed service latency (tokenizer RPC /
        # feature-store fetch — sleeps release the GIL, like a real
        # socket wait) + light host assembly
        time.sleep(prep_latency_ms / 1e3)
        return np.stack(
            [np.asarray(r, np.float32) for r in raw]
        ) * scale

    def drive(depth):
        engine = fitted.compiled(buckets=buckets)
        engine.warmup(example=jnp.zeros((d,), jnp.float32))
        with MicroBatcher(
            engine, max_delay_ms=200.0, max_batch=window,
            pipeline_depth=depth, host_featurize=featurize,
        ) as mb:
            # one unmeasured window warms BLAS paths + pool buffers
            warm = rng.standard_normal((window, d)).astype(np.float32)
            for f in [mb.submit(x) for x in warm]:
                f.result(timeout=120)
            # best-of-2 sustained passes (the stream bench's discipline:
            # scheduler jitter is large relative to a short run)
            dt = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                futures = [mb.submit(x) for x in items]
                rows = [
                    np.asarray(f.result(timeout=300)) for f in futures
                ]
                dt = min(dt, time.perf_counter() - t0)
        return engine, dt, rows

    serial_engine, serial_dt, serial_rows = drive(0)
    piped_engine, piped_dt, piped_rows = drive(pipeline_depth)

    for i, (a, b) in enumerate(zip(serial_rows, piped_rows)):
        if not np.array_equal(a, b):
            raise RuntimeError(
                f"row {i}: pipelined output differs from serial"
            )

    m = piped_engine.metrics
    stage_rates = m.stage_rates()
    bottleneck = min(stage_rates, key=stage_rates.get)
    sustained = n_windows / piped_dt  # windows/sec, bench-timed
    serial_rate = n_windows / serial_dt
    efficiency = sustained / stage_rates[bottleneck]
    speedup = sustained / serial_rate
    cores = os.cpu_count() or 1
    if efficiency <= 0.8:
        raise RuntimeError(
            f"pipelined lane sustains {sustained:.1f} windows/s but "
            f"the bottleneck stage ({bottleneck}) alone does "
            f"{stage_rates[bottleneck]:.1f} — overlap is broken "
            f"(efficiency {efficiency:.2f} <= 0.8; stages: "
            + ", ".join(
                f"{s} {r:.1f}/s" for s, r in sorted(stage_rates.items())
            ) + ")"
        )
    if cores >= 2 and speedup < 1.2:
        raise RuntimeError(
            f"pipelined lane is only {speedup:.2f}x the serial batcher "
            f"({sustained:.1f} vs {serial_rate:.1f} windows/s) on a "
            f"{cores}-core host — stage overlap buys nothing"
        )
    report = m.pipeline_report()
    emit(
        "serving_pipeline_overlap",
        sustained * window, "examples/sec",
        extra={
            "windows": n_windows,
            "window": window,
            "pipeline_depth": pipeline_depth,
            "host_cores": cores,
            "sustained_windows_per_sec": round(sustained, 2),
            "serial_windows_per_sec": round(serial_rate, 2),
            "speedup_vs_serial": round(speedup, 2),
            "stage_rates_per_sec": {
                s: round(r, 1) for s, r in sorted(stage_rates.items())
            },
            "bottleneck": bottleneck,
            "overlap_efficiency": round(efficiency, 3),
            "host_prep_mean_ms":
                report["stages"]["host_prep"]["mean_ms"],
            "compute_mean_ms": report["stages"]["compute"]["mean_ms"],
            "bit_identical": True,
        },
    )


def bench_goodput_mfu(
    emit, fitted, buckets: Sequence[int], d: int, passes: int = 2
) -> None:
    """``serving_goodput_mfu`` — drive a mixed-size sweep and read the
    device-truth plane back: measured padding efficiency (live
    per-bucket goodput/padded counters), modeled FLOPs + rolling MFU,
    and the roofline class per bucket. The acceptance assert is
    measured efficiency >= the ``padding_waste``-model prediction for
    the same observed histogram minus tolerance — the live counters
    are the ground truth the offline estimate must agree with."""
    from keystone_tpu.serving.autoscale import predicted_efficiency

    engine = fitted.compiled(buckets=buckets)
    engine.warmup(example=jnp.zeros((d,), jnp.float32))
    rng = np.random.default_rng(7)
    mb = engine.max_bucket
    sizes = sorted(
        set(int(s) for s in rng.integers(1, mb + 1, 16)) | {1, mb}
    )
    xs = {
        n: rng.standard_normal((n, d)).astype(np.float32) for n in sizes
    }
    for _ in range(passes):
        for x in xs.values():
            engine.apply(x, sync=True)
    m = engine.metrics
    measured = m.padding_efficiency()
    predicted = predicted_efficiency(
        m.request_sizes.snapshot(), engine.buckets
    )
    if measured is None:
        raise RuntimeError("no dispatches recorded")
    if predicted is None:
        raise RuntimeError("no request-size histogram")
    if measured < predicted - 0.02:
        raise RuntimeError(
            f"measured padding efficiency {measured:.4f} fell below "
            f"the padding_waste-model prediction {predicted:.4f} — the "
            f"live goodput counters and the offline model disagree"
        )
    mfu = m.mfu()
    cost_model_buckets = sorted(m.cost_models)
    emit(
        "serving_goodput_mfu", measured, "padding_efficiency",
        extra={
            "predicted_efficiency": round(predicted, 4),
            "goodput_rows": m.examples.total,
            "padded_rows": m.padded_rows.total,
            "distinct_batch_sizes": len(xs),
            "buckets": list(engine.buckets),
            "device_flops_total": m.device_flops.total,
            "flops_per_dispatch": {
                str(b): m.cost_models[b].get("flops")
                for b in cost_model_buckets
            },
            "mfu": round(mfu, 8) if mfu is not None else None,
            "roofline": {
                str(b): m.roofline_bound(b) for b in engine.buckets
            },
            "cost_analysis_available": bool(cost_model_buckets),
        },
    )


def bench_device_featurize(
    emit,
    img: int = 16,
    hidden: int = 256,
    depth: int = 3,
    buckets: Sequence[int] = (8, 32),
    n_requests: int = 384,
    n_threads: int = 8,
    n_check: int = 32,
    min_h2d_reduction: float = 3.0,
) -> None:
    """``serving_device_featurize`` — the device-side featurization A/B:
    the SAME featurize chain (``build_featurize_pipeline``) and model
    served two ways through full gateways —

    - **host path**: the existing ``host_featurize`` seam — the prep
      stage featurizes each coalesced window on the host (jitted batch
      featurize, the strongest host baseline) and the engine stages the
      resulting f32 features;
    - **device path**: ``device_featurize`` — raw uint8 images stage
      into the pooled staging buffers, and cast + featurize + predict
      ride ONE fused per-bucket XLA program.

    Asserted (raises, not asserts): outputs numerically matching
    (allclose), H2D bytes/request on the device path ≤ 1/3 of the host
    path (read off the engines' own ``keystone_serving_h2d_bytes_total``
    counters, padding included — the scraped fact, not the geometric
    claim), and sustained device-path examples/sec >= the host path
    (one bounded re-measure absorbs scheduler jitter: both paths are
    re-run once before the row fails). Headline: device-path
    examples/sec; ``extra`` carries both paths' rates, bytes/request,
    and per-stage bottleneck attribution — the host path's bottleneck
    sits in ``host_prep`` (featurize burns the prep stage), the device
    path's moves off ``host_prep``/``upload`` into the fused dispatch.

    The host path also pays the cost the seam can't avoid: window sizes
    vary with coalescing, so the host featurizer retraces per new
    window size while the device path's fused programs are bounded by
    the bucket list — warm passes cover the common sizes for fairness,
    but the structural difference is the measurement's point."""
    import jax.numpy as jnp

    from keystone_tpu.gateway import Gateway
    from keystone_tpu.serving.engine import CompiledPipeline
    from keystone_tpu.serving.featurize import build_featurize_pipeline

    featurize, feat_d = build_featurize_pipeline(img=img)
    model = build_pipeline(d=feat_d, hidden=hidden, depth=depth)
    rng = np.random.default_rng(11)
    check = rng.integers(
        0, 256, (n_check, img, img, 3), dtype=np.uint8
    )
    raws = rng.integers(
        0, 256, (n_requests, img, img, 3), dtype=np.uint8
    )

    feat_jit = featurize.jit_batch()

    def host_hook(raw):
        batch = np.stack([np.asarray(r, np.uint8) for r in raw])
        return np.asarray(feat_jit(batch))

    def drive(gw, inputs):
        served = [None] * len(inputs)
        errors = []

        def client(tid):
            # a shed/timeout must FAIL the row, not silently kill this
            # thread: a dead client issues fewer requests, which would
            # shrink dt and overstate the path's rate (and leave None
            # outputs the comparison would trip over later)
            try:
                for i in range(tid, len(inputs), n_threads):
                    served[i] = np.asarray(
                        gw.predict(inputs[i]).result(timeout=120)
                    )
            except Exception as e:
                errors.append(e)

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=client, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise RuntimeError(
                f"device-featurize bench client failed on "
                f"{gw.name}: {errors[0]!r}"
            ) from errors[0]
        return time.perf_counter() - t0, served

    def measure(gw, host_inputs):
        # unmeasured warm pass (pool buffers, BLAS paths, the host
        # hook's per-window-size retraces for the common sizes), then
        # best-of-2 sustained passes — the stream bench's discipline
        drive(gw, host_inputs[: n_requests // 2])
        dt = float("inf")
        for _ in range(2):
            dt = min(dt, drive(gw, host_inputs)[0])
        return n_requests / dt

    def engine_of(gw) -> CompiledPipeline:
        return gw.pool.lanes[0].engine

    gw_host = Gateway(
        model, buckets=buckets, n_lanes=1, max_delay_ms=2.0,
        host_featurize=host_hook,
        warmup_example=jnp.zeros((feat_d,), jnp.float32),
        name="bench-feat-host",
    )
    gw_dev = Gateway(
        model, buckets=buckets, n_lanes=1, max_delay_ms=2.0,
        device_featurize=featurize,
        warmup_example=jnp.zeros((img, img, 3), jnp.uint8),
        name="bench-feat-device",
    )
    try:
        host = {"outputs": drive(gw_host, list(check))[1]}
        dev = {"outputs": drive(gw_dev, list(check))[1]}
        host["rate"] = measure(gw_host, list(raws))
        dev["rate"] = measure(gw_dev, list(raws))
        if dev["rate"] < host["rate"]:
            # one bounded re-measure of BOTH paths (scheduler jitter on
            # a loaded CI host is large relative to one pass); best of
            # all observed passes per path, then the assert is final
            host["rate"] = max(
                host["rate"], measure(gw_host, list(raws))
            )
            dev["rate"] = max(dev["rate"], measure(gw_dev, list(raws)))
        for side, gw in (("host", gw_host), ("device", gw_dev)):
            m = engine_of(gw).metrics
            report = m.pipeline_report() or {}
            d_ = host if side == "host" else dev
            d_["bytes_per_request"] = (
                m.h2d_bytes.total / m.examples.total
            )
            d_["bottleneck"] = report.get("bottleneck")
            d_["compiles"] = m.compiles.total
    finally:
        gw_host.close()
        gw_dev.close()
    maxdiff = max(
        float(np.abs(a - b).max())
        for a, b in zip(host["outputs"], dev["outputs"])
    )
    for i, (a, b) in enumerate(zip(host["outputs"], dev["outputs"])):
        if not np.allclose(a, b, rtol=1e-4, atol=1e-5):
            raise RuntimeError(
                f"device-featurize output {i} diverges from the host "
                f"path (max abs diff {np.abs(a - b).max():.3e})"
            )
    reduction = host["bytes_per_request"] / dev["bytes_per_request"]
    if reduction < min_h2d_reduction:
        raise RuntimeError(
            f"device path ships {dev['bytes_per_request']:.0f} "
            f"H2D bytes/request vs the host path's "
            f"{host['bytes_per_request']:.0f} — only "
            f"{reduction:.2f}x fewer (need >= {min_h2d_reduction}x)"
        )
    if dev["rate"] < host["rate"]:
        raise RuntimeError(
            f"device-featurize path sustains {dev['rate']:.1f} ex/s "
            f"vs the host path's {host['rate']:.1f} — raw-on-the-wire "
            "must at least match the host featurize seam"
        )
    if dev["bottleneck"] in ("host_prep", "upload"):
        raise RuntimeError(
            f"device-featurize lane still bottlenecks on "
            f"{dev['bottleneck']} — the fused program was supposed to "
            "move the limiting stage off host prep/H2D"
        )
    emit(
        "serving_device_featurize",
        dev["rate"], "examples/sec",
        extra={
            "host_examples_per_sec": round(host["rate"], 1),
            "device_examples_per_sec": round(dev["rate"], 1),
            "speedup_vs_host": round(dev["rate"] / host["rate"], 3),
            "h2d_bytes_per_request_host": round(
                host["bytes_per_request"], 1
            ),
            "h2d_bytes_per_request_device": round(
                dev["bytes_per_request"], 1
            ),
            "h2d_reduction": round(reduction, 2),
            "raw_shape": [img, img, 3],
            "feature_dim": feat_d,
            "buckets": list(buckets),
            "requests": n_requests,
            "client_threads": n_threads,
            "host_bottleneck": host["bottleneck"],
            "device_bottleneck": dev["bottleneck"],
            "host_compiles": host["compiles"],
            "device_compiles": dev["compiles"],
            "outputs_allclose": True,
            "max_abs_diff": maxdiff,
        },
    )


def bench_flagship_featurize(
    emit,
    img: int = 48,
    desc_dim: int = 64,
    vocab: int = 32,
    hidden: int = 256,
    depth: int = 3,
    buckets: Sequence[int] = (8, 32),
    n_requests: int = 192,
    n_threads: int = 8,
    n_check: int = 16,
    min_h2d_reduction: float = 3.0,
) -> None:
    """``serving_flagship_featurize`` — the device-featurize A/B on the
    paper's FLAGSHIP chain (``build_flagship_featurize_pipeline``): the
    branched SIFT+LCS → PCA → GMM Fisher Vector → Hellinger/L2 DAG,
    with the hot loops as Pallas kernels (``sift_bin_sample``,
    ``plane_sandwich``, and — at this row's ``vocab >= 32`` — the fused
    FV statistics kernel), served two ways through full gateways:

    - **host path**: ``host_featurize`` runs the jitted flagship batch
      featurize on the host per coalesced window and ships the
      ``(4·desc_dim·vocab,)`` f32 features;
    - **device path**: raw ``(img, img, 3)`` uint8 on the wire; cast +
      both branches + combine + predict ride ONE fused per-bucket XLA
      program.

    Asserted (raises, not asserts): fused outputs allclose to the host
    path (rtol=1e-4/atol=1e-5 — the repo's established fusion
    tolerance); H2D bytes/request ≤ 1/3 of the host path off the
    engines' own counters (this row's geometry: 48²·3 raw uint8 =
    6912 B vs 8192 f32 features = 32 KiB, ~4.7× geometric); sustained
    fused ex/s >= host (one bounded re-measure absorbs jitter); and the
    device-truth series for the fused program are PRESENT — every
    warmed bucket published an XLA cost model, and when the hardware
    peaks are known (``observability/device.peaks_for``; CI exports
    ``KEYSTONE_PEAK_FLOPS``/``KEYSTONE_PEAK_MEMBW_GBPS`` on CPU) the
    rolling MFU and per-bucket roofline class are non-None. Headline:
    fused-path examples/sec."""
    import jax.numpy as jnp

    from keystone_tpu.gateway import Gateway
    from keystone_tpu.serving.engine import CompiledPipeline
    from keystone_tpu.serving.featurize import (
        build_flagship_featurize_pipeline,
    )

    featurize, feat_d = build_flagship_featurize_pipeline(
        img=img, desc_dim=desc_dim, vocab=vocab
    )
    model = build_pipeline(d=feat_d, hidden=hidden, depth=depth)
    rng = np.random.default_rng(13)
    check = rng.integers(
        0, 256, (n_check, img, img, 3), dtype=np.uint8
    )
    raws = rng.integers(
        0, 256, (n_requests, img, img, 3), dtype=np.uint8
    )

    feat_jit = featurize.jit_batch()

    def host_hook(raw):
        batch = np.stack([np.asarray(r, np.uint8) for r in raw])
        return np.asarray(feat_jit(batch))

    def drive(gw, inputs):
        served = [None] * len(inputs)
        errors = []

        def client(tid):
            # a shed/timeout must FAIL the row, not silently kill this
            # thread (same contract as bench_device_featurize)
            try:
                for i in range(tid, len(inputs), n_threads):
                    served[i] = np.asarray(
                        gw.predict(inputs[i]).result(timeout=300)
                    )
            except Exception as e:
                errors.append(e)

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=client, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise RuntimeError(
                f"flagship-featurize bench client failed on "
                f"{gw.name}: {errors[0]!r}"
            ) from errors[0]
        return time.perf_counter() - t0, served

    def measure(gw, host_inputs):
        drive(gw, host_inputs[: n_requests // 2])
        dt = float("inf")
        for _ in range(2):
            dt = min(dt, drive(gw, host_inputs)[0])
        return n_requests / dt

    def engine_of(gw) -> CompiledPipeline:
        return gw.pool.lanes[0].engine

    gw_host = Gateway(
        model, buckets=buckets, n_lanes=1, max_delay_ms=2.0,
        host_featurize=host_hook,
        warmup_example=jnp.zeros((feat_d,), jnp.float32),
        name="bench-flagship-host",
    )
    gw_dev = Gateway(
        model, buckets=buckets, n_lanes=1, max_delay_ms=2.0,
        device_featurize=featurize,
        warmup_example=jnp.zeros((img, img, 3), jnp.uint8),
        name="bench-flagship-device",
    )
    try:
        host = {"outputs": drive(gw_host, list(check))[1]}
        dev = {"outputs": drive(gw_dev, list(check))[1]}
        host["rate"] = measure(gw_host, list(raws))
        dev["rate"] = measure(gw_dev, list(raws))
        if dev["rate"] < host["rate"]:
            host["rate"] = max(
                host["rate"], measure(gw_host, list(raws))
            )
            dev["rate"] = max(dev["rate"], measure(gw_dev, list(raws)))
        for side, gw in (("host", gw_host), ("device", gw_dev)):
            m = engine_of(gw).metrics
            report = m.pipeline_report() or {}
            d_ = host if side == "host" else dev
            d_["bytes_per_request"] = (
                m.h2d_bytes.total / m.examples.total
            )
            # padding-independent wire cost: every dispatch stages
            # exactly bucket * bytes-per-row, so dividing the staged
            # total by the dispatched row count recovers the per-row
            # footprint however well the windows happened to fill
            d_["bytes_per_row"] = m.h2d_bytes.total / sum(
                b * n for b, n in m.dispatches.snapshot().items()
            )
            d_["bottleneck"] = report.get("bottleneck")
            d_["compiles"] = m.compiles.total
        m_dev = engine_of(gw_dev).metrics
        cost_model_buckets = sorted(m_dev.cost_models)
        mfu = m_dev.mfu(window=1e9)  # whole-run window: the row's
        # sustained passes all count, not just the trailing seconds
        roofline = {
            str(b): m_dev.roofline_bound(b)
            for b in engine_of(gw_dev).buckets
        }
        peaks_known = bool(
            m_dev._peak_flops and m_dev._peak_membw
        )
    finally:
        gw_host.close()
        gw_dev.close()
    maxdiff = max(
        float(np.abs(a - b).max())
        for a, b in zip(host["outputs"], dev["outputs"])
    )
    for i, (a, b) in enumerate(zip(host["outputs"], dev["outputs"])):
        if not np.allclose(a, b, rtol=1e-4, atol=1e-5):
            raise RuntimeError(
                f"flagship fused output {i} diverges from the host "
                f"featurize path (max abs diff {np.abs(a - b).max():.3e})"
            )
    # gate on the per-ROW footprint, not per-request: per-request
    # bytes fold in window fill, which is a batching/arrival property
    # (and flaps under load), while per-row is exactly what the wire
    # format costs — raw uint8 pixels vs f32 features
    reduction = host["bytes_per_row"] / dev["bytes_per_row"]
    if reduction < min_h2d_reduction:
        raise RuntimeError(
            f"flagship device path stages {dev['bytes_per_row']:.0f} "
            f"H2D bytes/bucket-row vs the host path's "
            f"{host['bytes_per_row']:.0f} — only "
            f"{reduction:.2f}x fewer (need >= {min_h2d_reduction}x)"
        )
    if dev["rate"] < host["rate"]:
        raise RuntimeError(
            f"flagship fused path sustains {dev['rate']:.1f} ex/s vs "
            f"the host path's {host['rate']:.1f} — raw-on-the-wire "
            "must at least match the host featurize seam"
        )
    # MFU/roofline presence for the fused program — the device-truth
    # series the perf claim rides on. Cost models come from XLA cost
    # analysis at warmup and must exist on every backend; the derived
    # MFU/roofline additionally need known hardware peaks.
    if not cost_model_buckets:
        raise RuntimeError(
            "the fused flagship program published no XLA cost model "
            "for any bucket — MFU/roofline series cannot exist"
        )
    if peaks_known and (
        mfu is None or any(v is None for v in roofline.values())
    ):
        raise RuntimeError(
            f"device peaks are known but the derived series are "
            f"absent (mfu={mfu}, roofline={roofline}) — the fused "
            "program's MFU/roofline must be present"
        )
    emit(
        "serving_flagship_featurize",
        dev["rate"], "examples/sec",
        extra={
            "host_examples_per_sec": round(host["rate"], 1),
            "device_examples_per_sec": round(dev["rate"], 1),
            "speedup_vs_host": round(dev["rate"] / host["rate"], 3),
            "h2d_bytes_per_request_host": round(
                host["bytes_per_request"], 1
            ),
            "h2d_bytes_per_request_device": round(
                dev["bytes_per_request"], 1
            ),
            "h2d_bytes_per_row_host": round(host["bytes_per_row"], 1),
            "h2d_bytes_per_row_device": round(dev["bytes_per_row"], 1),
            "h2d_reduction": round(reduction, 2),
            "raw_shape": [img, img, 3],
            "feature_dim": feat_d,
            "desc_dim": desc_dim,
            "vocab": vocab,
            "fv_kernel": "pallas_fused" if vocab >= 32 else "xla",
            "buckets": list(buckets),
            "requests": n_requests,
            "client_threads": n_threads,
            "host_bottleneck": host["bottleneck"],
            "device_bottleneck": dev["bottleneck"],
            "host_compiles": host["compiles"],
            "device_compiles": dev["compiles"],
            "outputs_allclose": True,
            "max_abs_diff": maxdiff,
            "cost_model_buckets": cost_model_buckets,
            "mfu": round(mfu, 8) if mfu is not None else None,
            "roofline": roofline,
            "peaks_known": peaks_known,
        },
    )


def bench_zoo(
    emit,
    img: int = 34,
    hidden: int = 128,
    depth: int = 2,
    buckets: Sequence[int] = (4, 16),
    n_requests: int = 96,
    n_threads: int = 8,
    n_check: int = 12,
    min_speedup: float = 1.5,
) -> None:
    """``serving_zoo`` — the cross-model featurize CSE A/B: TWO models
    sharing the paper's flagship SIFT+LCS→FV featurize prefix
    (``build_flagship_featurize_pipeline``) with different heads,
    served two ways at equal device count —

    - **baseline**: two independent gateways (the two-process proxy:
      each owns its lanes and fused engine, so every request pays the
      shared featurize prefix TWICE, once per model);
    - **zoo**: one ``ModelZoo`` whose CSE grouping
      (``zoo.featurize_groups``) co-hosts both heads behind ONE
      ``SharedPrefixEngine`` — the prefix runs once per coalesced
      window and the featurized activations fan out to each head
      inside the same fused program.

    Every request is an ensemble fan-out (one example → both models'
    predictions), so examples/sec counts ensemble examples on both
    sides. Asserted (raises, not asserts): per-model zoo outputs
    allclose to the solo baselines (rtol=1e-4/atol=1e-5, the repo's
    fusion tolerance); the shared prefix is compiled ONCE per bucket
    (zoo compiles == len(buckets) vs the baseline's 2x — both sides
    run with the AOT store detached so the trace counters are the
    fact, not a cache artifact); the zoo side issues strictly fewer
    device dispatches for the same request stream (one window serves
    both heads); and sustained zoo ex/s >= ``min_speedup`` x the
    baseline, with one bounded re-measure of BOTH sides absorbing
    scheduler jitter before the row may fail."""
    import jax.numpy as jnp

    from keystone_tpu.gateway import Gateway
    from keystone_tpu.serving.featurize import (
        build_flagship_featurize_pipeline,
    )
    from keystone_tpu.zoo import (
        BuiltModel, ModelRegistry, ModelSpec, ModelZoo,
    )

    featurize, feat_d = build_flagship_featurize_pipeline(img=img)
    heads = {
        mid: build_pipeline(
            d=feat_d, hidden=hidden, depth=depth, seed=seed
        )
        for mid, seed in (("alpha", 1), ("beta", 2))
    }
    model_ids = tuple(heads)
    rng = np.random.default_rng(17)
    check = rng.integers(
        0, 256, (n_check, img, img, 3), dtype=np.uint8
    )
    raws = rng.integers(
        0, 256, (n_requests, img, img, 3), dtype=np.uint8
    )
    warm = jnp.zeros((img, img, 3), jnp.uint8)

    def drive(submit, inputs, label):
        served = [None] * len(inputs)
        errors = []

        def client(tid):
            # a shed/timeout must FAIL the row, not silently kill the
            # thread — a dead client shrinks dt and overstates the rate
            try:
                for i in range(tid, len(inputs), n_threads):
                    served[i] = submit(inputs[i])
            except Exception as e:
                errors.append(e)

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=client, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise RuntimeError(
                f"zoo bench client failed on {label}: {errors[0]!r}"
            ) from errors[0]
        return time.perf_counter() - t0, served

    def measure(submit, label):
        # unmeasured warm half-pass, then best-of-2 sustained passes
        drive(submit, list(raws[: n_requests // 2]), label)
        dt = float("inf")
        for _ in range(2):
            dt = min(dt, drive(submit, list(raws), label)[0])
        return n_requests / dt

    def totals(gateways):
        compiles = dispatches = 0
        for gw in gateways:
            for lane in gw.pool.lanes:
                m = lane.engine.metrics
                compiles += m.compiles.total
                dispatches += m.dispatches.total
        return compiles, dispatches

    # baseline: two independent single-model gateways. AOT detached on
    # BOTH sides so the compile counters measure tracing, not cache
    # hits (the shared engine refuses AOT by construction; the solos
    # must match that footing for the 2x-compiles claim to be honest).
    solo = {
        mid: Gateway(
            head, buckets=buckets, n_lanes=1, max_delay_ms=2.0,
            device_featurize=featurize, warmup_example=warm,
            aot_store=None, name=f"bench-zoo-solo-{mid}",
        )
        for mid, head in heads.items()
    }

    reg = ModelRegistry()
    for mid, head in heads.items():
        reg.register(ModelSpec(
            model_id=mid,
            build=(lambda h=head: BuiltModel(
                fitted=h, featurize=featurize
            )),
            buckets=buckets,
            lanes=1,
            input_dtype=np.uint8,
            warmup_example=warm,
            max_delay_ms=2.0,
            default=(mid == model_ids[0]),
        ))
    zoo = ModelZoo(reg, cse=True)

    def base_submit(x):
        futs = {m: solo[m].predict(x) for m in model_ids}
        return {
            m: np.asarray(f.result(timeout=120))
            for m, f in futs.items()
        }

    def zoo_submit(x):
        out = zoo.predict_many(x, model_ids).result(timeout=120)
        return {m: np.asarray(out[m]) for m in model_ids}

    try:
        hosted = zoo.host()
        if not any(len(unit) == 2 for unit in hosted):
            raise RuntimeError(
                f"zoo did not CSE-group the two flagship heads "
                f"(hosted units: {hosted}) — identical featurize "
                "tokens must co-host behind one SharedPrefixEngine"
            )
        base_outs = drive(base_submit, list(check), "baseline")[1]
        zoo_outs = drive(zoo_submit, list(check), "zoo")[1]
        base_rate = measure(base_submit, "baseline")
        zoo_rate = measure(zoo_submit, "zoo")
        for _ in range(3):
            if zoo_rate >= min_speedup * base_rate:
                break
            # bounded re-measures of BOTH sides (scheduler jitter on
            # a loaded CI host is large relative to one pass); best
            # of all observed passes per side, then the gate is final
            base_rate = max(
                base_rate, measure(base_submit, "baseline")
            )
            zoo_rate = max(zoo_rate, measure(zoo_submit, "zoo"))
        base_compiles, base_dispatches = totals(solo.values())
        zoo_compiles, zoo_dispatches = totals(
            [zoo.gateway_for(model_ids[0])]
        )
    finally:
        zoo.close()
        for gw in solo.values():
            gw.close()

    maxdiff = 0.0
    for i, (b, z) in enumerate(zip(base_outs, zoo_outs)):
        for mid in model_ids:
            maxdiff = max(
                maxdiff, float(np.abs(b[mid] - z[mid]).max())
            )
            if not np.allclose(b[mid], z[mid], rtol=1e-4, atol=1e-5):
                raise RuntimeError(
                    f"zoo output for model {mid!r} diverges from its "
                    f"solo gateway on example {i} (max abs diff "
                    f"{np.abs(b[mid] - z[mid]).max():.3e}) — the "
                    "shared prefix must not change any head's answer"
                )
    if zoo_compiles > len(buckets):
        raise RuntimeError(
            f"zoo side traced {zoo_compiles} programs for "
            f"{len(buckets)} buckets — the shared prefix was supposed "
            "to compile ONCE per bucket for the whole group"
        )
    if base_compiles < 2 * zoo_compiles:
        raise RuntimeError(
            f"baseline traced {base_compiles} programs vs the zoo's "
            f"{zoo_compiles} — the two-gateway baseline must pay the "
            "featurize prefix per model for this A/B to mean anything"
        )
    if base_dispatches <= zoo_dispatches:
        raise RuntimeError(
            f"zoo issued {zoo_dispatches} device dispatches vs the "
            f"baseline's {base_dispatches} for the same request "
            "stream — one coalesced window must serve BOTH heads"
        )
    if zoo_rate < min_speedup * base_rate:
        raise RuntimeError(
            f"zoo sustains {zoo_rate:.1f} ensemble ex/s vs the "
            f"two-gateway baseline's {base_rate:.1f} — only "
            f"{zoo_rate / base_rate:.2f}x (need >= {min_speedup}x): "
            "sharing the featurize prefix did not pay for itself"
        )
    emit(
        "serving_zoo",
        zoo_rate, "examples/sec",
        extra={
            "baseline_examples_per_sec": round(base_rate, 1),
            "zoo_examples_per_sec": round(zoo_rate, 1),
            "speedup_vs_two_gateways": round(zoo_rate / base_rate, 3),
            "min_speedup": min_speedup,
            "models": list(model_ids),
            "cse_groups": [list(u) for u in hosted],
            "baseline_compiles": base_compiles,
            "zoo_compiles": zoo_compiles,
            "baseline_dispatches": base_dispatches,
            "zoo_dispatches": zoo_dispatches,
            "raw_shape": [img, img, 3],
            "feature_dim": feat_d,
            "buckets": list(buckets),
            "requests": n_requests,
            "client_threads": n_threads,
            "outputs_allclose": True,
            "max_abs_diff": maxdiff,
        },
    )


def bench_attribution_drift(
    emit,
    img: int = 16,
    hidden: int = 64,
    depth: int = 2,
    buckets: Sequence[int] = (2, 8, 32),
    n_per_model: int = 40,
    n_threads: int = 4,
    base_mix: str = "1:0.8,2:0.2",
    shift_mix: str = "24:1.0",
    max_p99_ratio: float = 1.05,
    sum_tolerance: float = 1e-6,
) -> None:
    """``serving_attribution_drift`` — the attribution & drift plane
    end-to-end: a two-model zoo (CSE-shared featurize prefix, so the
    fair-split rule is actually exercised) planned against a small-size
    mixture, driven through a MID-RUN WORKLOAD SHIFT — ``alpha``'s
    request sizes swap from ``base_mix`` to ``shift_mix`` (loadgen's
    size-mixture grammar) while ``beta`` stays on the planned mixture.

    Gates (raise, not assert):

    - **sum invariant**: per-model ledger totals
      (``observability/attribution.py``) sum to the engine-side
      counters — goodput/padded rows, dispatches, modeled FLOPs, H2D
      bytes, completion-timed device seconds — within
      ``sum_tolerance`` relative, CSE fair-split windows included;
    - **drift selectivity**: after the shift, the PSI score trips the
      threshold for ``alpha`` ONLY (``beta`` scores but stays under),
      and nothing is flagged before the shift;
    - **re-plan audit**: ``/driftz`` carries a non-empty
      recommendation whose proposed buckets for the shifted model move
      toward the new dominant size (the smallest bucket covering the
      shifted size strictly tightens — the forced top bucket is pinned
      at the spec cap, so growth shows up as better coverage below
      it);
    - **overhead**: client-observed p99 with attribution attached
      <= ``max_p99_ratio`` x an identical zoo with the bindings
      detached, with bounded re-measures of both sides absorbing
      scheduler jitter (same posture as the router trace-overhead
      row)."""
    from keystone_tpu.loadgen.trace import parse_size_mix
    from keystone_tpu.serving.featurize import build_featurize_pipeline
    from keystone_tpu.zoo import (
        BuiltModel, ModelRegistry, ModelSpec, ModelZoo,
    )
    from keystone_tpu.zoo.optimizer import ChipBudget, plan_placement

    featurize, feat_d = build_featurize_pipeline(img=img)
    heads = {
        mid: build_pipeline(
            d=feat_d, hidden=hidden, depth=depth, seed=seed
        )
        for mid, seed in (("alpha", 1), ("beta", 2))
    }
    model_ids = tuple(heads)
    warm = jnp.zeros((img, img, 3), jnp.uint8)
    rng = np.random.default_rng(23)
    pool = rng.integers(0, 256, (16, img, img, 3), dtype=np.uint8)

    def build_zoo():
        reg = ModelRegistry()
        for i, (mid, head) in enumerate(heads.items()):
            reg.register(ModelSpec(
                model_id=mid,
                build=(lambda h=head: BuiltModel(
                    fitted=h, featurize=featurize
                )),
                buckets=buckets,
                lanes=1,
                input_dtype=np.uint8,
                warmup_example=warm,
                max_delay_ms=2.0,
                # the planner's assumed mixture — what base_mix's live
                # traffic matches and shift_mix's diverges from
                expected_sizes={
                    s: max(1, int(round(w * 100)))
                    for s, w in parse_size_mix(base_mix)
                },
                default=(i == 0),
            ))
        return ModelZoo(reg, cse=True)

    def sizes_from(mix_spec: str, n: int):
        mix = parse_size_mix(mix_spec)
        weights = np.asarray([w for _, w in mix], dtype=float)
        return [
            int(s) for s in rng.choice(
                [s for s, _ in mix], size=n, p=weights / weights.sum()
            )
        ]

    def schedule_for(mix_by_model):
        requests = []
        for mid, mix_spec in mix_by_model.items():
            requests.extend(
                (mid, s) for s in sizes_from(mix_spec, n_per_model)
            )
        rng.shuffle(requests)
        return requests

    def drive(zoo, schedule):
        """Run one phase: per request, one drift observation + ``size``
        admitted instances; returns per-request client latencies."""
        latencies = [None] * len(schedule)
        errors = []

        def client(tid):
            try:
                for i in range(tid, len(schedule), n_threads):
                    mid, size = schedule[i]
                    zoo.observe_request(mid, size)
                    t0 = time.perf_counter()
                    futs = [
                        zoo.predict(pool[j % len(pool)], mid)
                        for j in range(size)
                    ]
                    for f in futs:
                        f.result(timeout=120)
                    latencies[i] = time.perf_counter() - t0
            except Exception as e:
                errors.append(e)

        threads = [
            threading.Thread(target=client, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise RuntimeError(
                f"attribution bench client failed: {errors[0]!r}"
            ) from errors[0]
        return [lat for lat in latencies if lat is not None]

    def p99(latencies):
        return float(np.percentile(np.asarray(latencies), 99))

    def gateways_of(zoo):
        return {id(zoo.gateway_for(m)): zoo.gateway_for(m)
                for m in model_ids}.values()

    def engine_totals(zoo):
        out = {
            "goodput_rows": 0.0, "padded_rows": 0.0,
            "dispatches": 0.0, "device_flops": 0.0,
            "h2d_bytes": 0.0, "device_seconds": 0.0,
        }
        for gw in gateways_of(zoo):
            for lane in gw.pool.lanes:
                m = lane.engine.metrics
                out["goodput_rows"] += m.examples.total
                out["padded_rows"] += m.padded_rows.total
                out["dispatches"] += m.dispatches.total
                out["device_flops"] += m.device_flops.total
                out["h2d_bytes"] += m.h2d_bytes.total
                out["device_seconds"] += (
                    m.dispatch_latency.snapshot()["total"]
                )
        return out

    base_schedule = schedule_for({m: base_mix for m in model_ids})
    shift_schedule = schedule_for(
        {"alpha": shift_mix, "beta": base_mix}
    )

    zoo = build_zoo()
    try:
        zoo.host()
        profiles = zoo.profiles(build=True)
        budget = ChipBudget(lane_budget=len(model_ids))
        zoo.apply_plan(
            plan_placement(profiles, budget),
            budget=budget, profiles=profiles,
        )
        old_buckets = {
            m: zoo.plan.placement_for(m).buckets for m in model_ids
        }
        drive(zoo, base_schedule)  # matches the plan: nothing drifts
        pre_shift = zoo.driftz()
        on_latencies = drive(zoo, shift_schedule)
        doc = zoo.driftz()
        attr = zoo.attributionz()
        eng = engine_totals(zoo)
        led = zoo.attribution.totals()
    finally:
        zoo.close()

    # -- gate 1: the sum invariant (CSE fair-split included) ---------------
    rel_errs = {}
    for field, eng_total in eng.items():
        led_total = led[field]
        rel = (
            abs(eng_total - led_total) / abs(eng_total)
            if eng_total else abs(led_total)
        )
        rel_errs[field] = rel
        if rel > sum_tolerance:
            raise RuntimeError(
                f"attribution {field} totals diverge: engines "
                f"{eng_total} vs ledger {led_total} "
                f"({rel:.2e} rel > {sum_tolerance:.0e}) — per-model "
                "charges must sum exactly to engine totals"
            )
    # -- gate 2: drift fires on the shifted model only ---------------------
    if pre_shift["drifted"]:
        raise RuntimeError(
            f"models {pre_shift['drifted']} flagged as drifted while "
            "traffic still matched the plan's mixture"
        )
    scores = doc["scores"]
    if "alpha" not in doc["drifted"]:
        raise RuntimeError(
            f"the shifted model never tripped the PSI threshold "
            f"(scores {scores}, threshold {doc['threshold']}) — "
            f"{base_mix} -> {shift_mix} is a full population swap"
        )
    if "beta" in doc["drifted"]:
        raise RuntimeError(
            f"beta flagged as drifted (scores {scores}) though its "
            "mixture never changed — drift must be per-model, not "
            "engine-wide"
        )
    if "beta" not in scores:
        raise RuntimeError(
            "beta produced no PSI score despite a baseline and "
            f"{n_per_model} windowed observations"
        )
    # -- gate 3: the re-plan audit -----------------------------------------
    rec = doc["recommendation"]
    if not rec or not rec.get("changes"):
        raise RuntimeError(
            f"drift tripped but /driftz carries no re-plan "
            f"recommendation (got {rec!r})"
        )
    if "alpha" not in rec["changes"]:
        raise RuntimeError(
            f"re-plan changed {sorted(rec['changes'])} but not the "
            "shifted model — the recommendation must follow the drift"
        )
    proposed = {
        p["model"]: tuple(p["buckets"])
        for p in rec["proposed_plan"]["placements"]
    }
    shift_size = max(s for s, _ in parse_size_mix(shift_mix))

    def covering(bucket_set):
        # what the shifted size actually pays under this bucket set
        # (sizes over the top bucket chunk through it waste-free)
        fits = [b for b in bucket_set if b >= shift_size]
        return min(fits) if fits else max(bucket_set)

    if covering(proposed["alpha"]) >= covering(old_buckets["alpha"]):
        raise RuntimeError(
            f"shifted model's proposed buckets {proposed['alpha']} "
            f"don't cover size {shift_size} any tighter than the "
            f"applied plan's {old_buckets['alpha']} though live "
            f"sizes moved from {base_mix} to {shift_mix} — the "
            "re-plan is not directionally correct"
        )
    # -- gate 4: attribution overhead --------------------------------------
    def measure_off():
        zoo_off = build_zoo()
        try:
            zoo_off.host()
            for gw in gateways_of(zoo_off):
                for lane in gw.pool.lanes:
                    # identical serving shape, ledger mirror detached:
                    # the A/B isolates the binding's hot-path cost
                    lane.engine.metrics.attach_attribution(None)
            drive(zoo_off, base_schedule)  # warm parity with the on side
            return p99(drive(zoo_off, shift_schedule))
        finally:
            zoo_off.close()

    p99_on = p99(on_latencies)
    p99_off = measure_off()
    for _ in range(2):
        if p99_on <= max_p99_ratio * p99_off:
            break
        # bounded re-measures (scheduler jitter on a loaded CI host
        # dwarfs the binding's cost); best observed per side is final
        zoo_on2 = build_zoo()
        try:
            zoo_on2.host()
            drive(zoo_on2, base_schedule)
            p99_on = min(p99_on, p99(drive(zoo_on2, shift_schedule)))
        finally:
            zoo_on2.close()
        p99_off = min(p99_off, measure_off())
    if p99_on > max_p99_ratio * p99_off:
        raise RuntimeError(
            f"attribution-on p99 {p99_on * 1e3:.1f} ms vs off "
            f"{p99_off * 1e3:.1f} ms — "
            f"{p99_on / p99_off:.3f}x exceeds {max_p99_ratio}x: the "
            "ledger mirror is not allowed to tax the serving path"
        )

    emit(
        "serving_attribution_drift",
        scores.get("alpha"), "psi",
        extra={
            "scores": scores,
            "threshold": doc["threshold"],
            "drifted": doc["drifted"],
            "base_mix": base_mix,
            "shift_mix": shift_mix,
            "attribution_rel_err_max": max(rel_errs.values()),
            "attribution_totals": {
                k: round(v, 6) for k, v in led.items()
            },
            "per_model_device_seconds": {
                m: round(
                    attr["models"][m]["device_seconds"], 6
                )
                for m in attr["models"]
            },
            "replan_changed_models": sorted(rec["changes"]),
            "buckets_before": {
                m: list(b) for m, b in old_buckets.items()
            },
            "buckets_proposed": {
                m: list(b) for m, b in proposed.items()
            },
            "p99_on_ms": round(p99_on * 1e3, 3),
            "p99_off_ms": round(p99_off * 1e3, 3),
            "p99_ratio": round(p99_on / p99_off, 3),
            "max_p99_ratio": max_p99_ratio,
            "requests_per_model_per_phase": n_per_model,
        },
    )


def bench_sharded_vs_replicated(
    emit,
    sizes: Sequence[int] = (128, 256, 512),
    big_d: int = 1024,
    depth: int = 3,
    buckets: Sequence[int] = (8, 32),
    n_requests: int = 192,
    n_threads: int = 8,
    n_check: int = 16,
    replicated_lanes: int = 2,
    device_budget_mb: float = 6.0,
) -> None:
    """``serving_sharded_vs_replicated`` — the model axis A/B: the
    same fitted model served

    - **replicated**: ``replicated_lanes`` shared-nothing lanes, each
      holding the FULL parameter set (the pre-sharding scaling story,
      and what a per-chip HBM budget caps);
    - **sharded**: ONE lane whose engine runs ``param_sharding=True``
      over a ``(data=1, model=N)`` mesh spanning every local device —
      the default rules split each weight matrix over the model axis,
      the params ride as sharded program arguments, and each device
      holds only its shard.

    Swept over ``sizes`` (square ``depth``-layer models, parameter
    bytes ~ ``depth * d^2 * 4``) plus ``big_d``, sized to exceed the
    row's **per-device parameter budget** (``device_budget_mb`` —
    virtual CPU devices have no real HBM wall, so the budget plays
    the chip; on real TPUs it would be the HBM limit the
    device-memory sampler reports). Per size the row asserts (raises,
    never ``assert``):

    - sharded outputs allclose to the replicated path's;
    - the big model's TOTAL parameter bytes exceed the budget (the
      replicated path is refused — recorded ``over_budget``, exactly
      what a real per-chip OOM would make of it) while its measured
      per-device placed-parameter bytes — summed over the actual
      shard buffers, ``sharding.placed_shard_bytes`` — fit, and it
      SERVES: the capability the replicated stack lacks outright;
    - every size both paths can serve contributes a crossover-curve
      entry (params_mb, sharded/replicated examples/sec) to the row
      JSON — on shared-core virtual CPU devices the rates measure
      dispatch/collective overhead rather than real chip scaling, so
      the curve is reported, not asserted.
    """
    import jax
    import jax.numpy as jnp

    from keystone_tpu.gateway import Gateway
    from keystone_tpu.parallel import mesh as mesh_lib
    from keystone_tpu.serving import sharding as sharding_lib

    n_devices = len(jax.devices())
    if n_devices < 2:
        raise RuntimeError(
            "serving_sharded_vs_replicated needs >= 2 devices; on CPU "
            "run under XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    budget = int(device_budget_mb * 1e6)
    mesh = mesh_lib.make_mesh(n_data=1, n_model=n_devices)

    def drive(gw, inputs):
        served = [None] * len(inputs)
        errors = []

        def client(tid):
            try:
                for i in range(tid, len(inputs), n_threads):
                    served[i] = np.asarray(
                        gw.predict(inputs[i]).result(timeout=120)
                    )
            except Exception as e:
                errors.append(e)

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=client, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise RuntimeError(
                f"shard bench client failed on {gw.name}: "
                f"{errors[0]!r}"
            ) from errors[0]
        return time.perf_counter() - t0, served

    def measure(gw, inputs):
        drive(gw, inputs[: len(inputs) // 2])  # unmeasured warm pass
        dt = float("inf")
        for _ in range(2):
            dt = min(dt, drive(gw, inputs)[0])
        return len(inputs) / dt

    curve = []
    rng = np.random.default_rng(17)
    for d in tuple(sizes) + (int(big_d),):
        model = build_pipeline(d=d, hidden=d, depth=depth)
        total = sharding_lib.params_nbytes(
            sharding_lib.named_params(model)
        )
        fits_one_device = total <= budget
        check = [
            rng.standard_normal((d,)).astype(np.float32)
            for _ in range(n_check)
        ]
        raws = [
            rng.standard_normal((d,)).astype(np.float32)
            for _ in range(n_requests)
        ]
        entry = {
            "d": d,
            "params_mb": round(total / 1e6, 2),
            "fits_one_device": fits_one_device,
        }
        with mesh_lib.use_mesh(mesh):
            gw_s = Gateway(
                model, buckets=buckets, n_lanes=1, max_delay_ms=2.0,
                param_sharding=True,
                warmup_example=jnp.zeros((d,), jnp.float32),
                name=f"bench-shard-{d}",
            )
        gw_r = None
        if fits_one_device:
            gw_r = Gateway(
                model, buckets=buckets, n_lanes=replicated_lanes,
                max_delay_ms=2.0,
                warmup_example=jnp.zeros((d,), jnp.float32),
                name=f"bench-repl-{d}",
            )
        else:
            # the capability gap itself: a replicated lane needs the
            # FULL parameter set resident per device, and this model's
            # exceeds the per-device budget — exactly what a real
            # per-chip HBM wall makes of it
            entry["replicated"] = "over_budget"
        try:
            engine = gw_s.pool.lanes[0].engine
            if not engine.model_sharded:
                raise RuntimeError(
                    f"d={d}: the sharded gateway's engine is not "
                    "model-sharded"
                )
            per_dev = sharding_lib.placed_shard_bytes(
                engine._placed_params
            )
            max_dev = max(per_dev.values())
            entry["max_device_params_mb"] = round(max_dev / 1e6, 2)
            if max_dev > budget:
                raise RuntimeError(
                    f"d={d}: sharded per-device parameter bytes "
                    f"{max_dev} exceed the {budget}-byte budget — the "
                    "partition rules did not actually split the model"
                )
            outs_s = drive(gw_s, check)[1]
            if gw_r is not None:
                outs_r = drive(gw_r, check)[1]
                for i, (a, b) in enumerate(zip(outs_s, outs_r)):
                    if not np.allclose(a, b, rtol=1e-4, atol=1e-5):
                        raise RuntimeError(
                            f"d={d}: sharded output {i} diverges from "
                            f"the replicated path (max abs diff "
                            f"{np.abs(a - b).max():.3e})"
                        )
                entry["outputs_allclose"] = True
                entry["replicated_examples_per_sec"] = round(
                    measure(gw_r, raws), 1
                )
            entry["sharded_examples_per_sec"] = round(
                measure(gw_s, raws), 1
            )
        finally:
            gw_s.close()
            if gw_r is not None:
                gw_r.close()
        curve.append(entry)

    big = curve[-1]
    if big["fits_one_device"]:
        raise RuntimeError(
            f"big_d={big_d} fits the {device_budget_mb} MB device "
            "budget — the over-budget leg measured nothing; raise "
            "big_d or lower the budget"
        )
    if "sharded_examples_per_sec" not in big:
        raise RuntimeError(
            "the over-budget model did not serve on the sharded path"
        )
    if not all(
        e.get("outputs_allclose") for e in curve if e["fits_one_device"]
    ):
        raise RuntimeError(f"parity missing from the curve: {curve}")
    emit(
        "serving_sharded_vs_replicated",
        big["sharded_examples_per_sec"], "examples/sec",
        extra={
            "n_devices": n_devices,
            "mesh": {"data": 1, "model": n_devices},
            "device_budget_mb": device_budget_mb,
            "replicated_lanes": replicated_lanes,
            "depth": depth,
            "buckets": list(buckets),
            "requests": n_requests,
            "crossover_curve": curve,
            "over_budget_d": big_d,
            "over_budget_params_mb": big["params_mb"],
            "over_budget_max_device_params_mb": big[
                "max_device_params_mb"
            ],
            "over_budget_served_sharded": True,
        },
    )


def bench_cold_start_aot(
    emit,
    buckets: Sequence[int] = (4, 8, 16, 32, 64, 128),
    d: int = 128, hidden: int = 256, depth: int = 40,
    lanes: int = 4, min_speedup: float = 3.0,
) -> None:
    """``serving_cold_start_aot`` — the zero-cold-start acceptance row,
    measured CROSS-PROCESS so no in-process cache can flatter it: spawn
    a genuinely fresh ``serve-gateway`` subprocess twice — once with
    every persistence layer off (``--no-cache``), once with a
    pre-populated AOT executable store (built by an untimed
    ``serve-aot-build`` subprocess) — and time each from ``exec()`` to
    ``/readyz`` 200 and to the first successful ``/predict``. The warm
    run's XLA compile cache points at a FRESH empty dir, so its entire
    speedup is attributable to the serialized executables alone, and
    ``keystone_aot_cache_hits_total`` is scraped off the warm child's
    own ``/metrics`` to prove the store (not a recompile) served it.

    The pipeline here is deliberately DEEPER than the other rows' (40
    matmul nodes, 4 lanes, 6 buckets — many compiles, cheap dispatches):
    cold-start economics only matter for programs whose compiles
    dominate process startup, exactly the regime real models live in —
    with the toy 4-node pipeline the interpreter+import constant
    (~3 s, identical in both runs and untouchable by any executable
    cache) would swamp the thing being measured, and a FLOP-heavy wide
    pipeline would instead measure the warmup validation dispatches
    both runs share.

    The in-process ``serving_cold_vs_warm_latency`` row deliberately
    keeps measuring a REAL trace + XLA compile (both caches detached
    in-row); this row is the complementary claim — that a fresh
    process can skip that compile entirely."""
    import collections
    import os
    import re
    import shutil
    import signal
    import subprocess
    import sys
    import tempfile
    import urllib.request

    from keystone_tpu.observability import prometheus

    workdir = tempfile.mkdtemp(prefix="keystone-aot-bench-")
    aot_dir = os.path.join(workdir, "aot")
    shape_args = [
        "--d", str(d), "--hidden", str(hidden), "--depth", str(depth),
        "--buckets", ",".join(str(b) for b in buckets),
    ]

    def child_env(**caches):
        # explicit cache env per child: the bench's own environment
        # may carry KEYSTONE_* pointers — or JAX's own persistent
        # compile-cache env (JAX_COMPILATION_CACHE_DIR etc., which jax
        # honors WITHOUT setup_compilation_cache, so --no-cache alone
        # wouldn't keep it out of the cold baseline) — that would
        # contaminate a run
        env = {
            k: v for k, v in os.environ.items()
            if not (
                k.startswith("KEYSTONE_")
                or k == "JAX_COMPILATION_CACHE_DIR"
                or k.startswith("JAX_PERSISTENT_CACHE")
            )
        }
        # pin the children to the PARENT'S backend: on a host whose
        # device is exclusively locked (TPU), an unpinned child would
        # fail device init and silently downgrade to CPU — the row
        # would then pass while measuring the wrong platform. Pinned,
        # the child fails LOUDLY (its traceback lands in tail_text)
        # instead of flattering the number.
        import jax

        env["JAX_PLATFORMS"] = (
            os.environ.get("JAX_PLATFORMS") or jax.default_backend()
        )
        env.update(caches)
        return env

    def measure(args, env):
        """One fresh gateway process: wall seconds from spawn to the
        bound URL, to /readyz 200, and to the first /predict 200, plus
        its /metrics AOT-hit count."""
        t0 = time.perf_counter()
        proc = subprocess.Popen(
            [sys.executable, "-m", "keystone_tpu", "serve-gateway",
             "--gateway-port", "0", "--lanes", str(lanes)]
            + shape_args + args,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
        )
        # watchdog: a wedged child must fail the row, not hang the bench
        watchdog = threading.Timer(600.0, proc.kill)
        watchdog.daemon = True
        watchdog.start()
        tail = collections.deque(maxlen=200)

        def tail_text():
            # snapshot first: the drainer thread appends concurrently,
            # and joining a live deque raises "mutated during
            # iteration" — which would mask the child's actual crash
            # log in the error message being built
            return "".join(tail.copy())

        try:
            url = None
            for line in proc.stdout:
                tail.append(line)
                m = re.search(r"http://127\.0\.0\.1:\d+", line)
                if m:
                    url = m.group(0)
                    break
            if url is None:
                raise RuntimeError(
                    "serving_cold_start_aot: gateway subprocess died "
                    "before binding:\n" + tail_text()
                )
            # keep DRAINING the child's merged stdout/stderr: a chatty
            # child (XLA warnings, verbose logging) would otherwise
            # fill the ~64KB pipe and block inside its own write —
            # wedging warmup and burning the whole poll deadline
            threading.Thread(
                target=lambda: tail.extend(proc.stdout),
                daemon=True,
            ).start()
            deadline = time.perf_counter() + 600.0
            while True:
                # bounded + liveness-checked: a child the watchdog
                # killed (or that crashed after binding) must fail the
                # row, not spin this poll forever
                if proc.poll() is not None:
                    raise RuntimeError(
                        "serving_cold_start_aot: gateway subprocess "
                        f"exited (rc {proc.returncode}) before "
                        "/readyz went 200:\n" + tail_text()
                    )
                if time.perf_counter() > deadline:
                    raise RuntimeError(
                        "serving_cold_start_aot: /readyz never went "
                        "200 within 600s"
                    )
                try:
                    if urllib.request.urlopen(
                        url + "/readyz", timeout=5
                    ).status == 200:
                        break
                except Exception:
                    time.sleep(0.02)
            t_ready = time.perf_counter() - t0
            body = json.dumps({"instances": [[0.0] * d]}).encode()
            urllib.request.urlopen(
                urllib.request.Request(
                    url + "/predict", data=body,
                    headers={"Content-Type": "application/json"},
                ),
                timeout=120,
            ).read()
            t_predict = time.perf_counter() - t0
            with urllib.request.urlopen(
                url + "/metrics", timeout=15
            ) as resp:
                exposition = resp.read().decode("utf-8")
            hits = sum(
                value
                for name, _labels, value in prometheus.parse_samples(
                    exposition
                )
                if name == "keystone_aot_cache_hits_total"
            )
        finally:
            watchdog.cancel()
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
        return {"ready_s": t_ready, "predict_s": t_predict, "hits": hits}

    try:
        # untimed: populate the store the way a build/deploy step would
        # (its OWN compile cache — the timed warm run must not inherit
        # replayable XLA entries, or the row would credit the wrong
        # cache)
        build = subprocess.run(
            [sys.executable, "-m", "keystone_tpu", "serve-aot-build"]
            + shape_args,
            env=child_env(
                KEYSTONE_AOT_CACHE=aot_dir,
                KEYSTONE_COMPILE_CACHE=os.path.join(workdir, "xc-build"),
            ),
            capture_output=True, text=True, timeout=900,
        )
        if build.returncode != 0:
            raise RuntimeError(
                "serving_cold_start_aot: serve-aot-build failed:\n"
                + build.stdout + build.stderr
            )
        cold = measure(["--no-cache"], child_env())
        warm = measure([], child_env(
            KEYSTONE_AOT_CACHE=aot_dir,
            KEYSTONE_COMPILE_CACHE=os.path.join(workdir, "xc-fresh"),
        ))
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    # explicit raises, not asserts (python -O must not strip the row's
    # acceptance contract)
    want_hits = lanes * len(buckets)
    if warm["hits"] < want_hits:
        raise RuntimeError(
            f"serving_cold_start_aot: warm gateway reported "
            f"{warm['hits']} AOT cache hits on /metrics, expected "
            f">= {want_hits} ({lanes} lanes x {len(buckets)} buckets) "
            "— the fast start is not attributable to the store"
        )
    speedup = cold["predict_s"] / warm["predict_s"]
    if speedup < min_speedup:
        raise RuntimeError(
            f"serving_cold_start_aot: fresh-process first-predict with "
            f"a warm AOT store was only {speedup:.2f}x faster than "
            f"without it ({warm['predict_s']:.2f}s vs "
            f"{cold['predict_s']:.2f}s); the acceptance floor is "
            f"{min_speedup:.1f}x"
        )
    emit(
        "serving_cold_start_aot",
        warm["predict_s"] * 1e3, "ms_to_first_predict",
        extra={
            "source": "fresh subprocess: exec() -> /readyz -> /predict",
            "speedup_vs_no_store": round(speedup, 2),
            "cold_first_predict_ms": round(cold["predict_s"] * 1e3, 1),
            "cold_ready_ms": round(cold["ready_s"] * 1e3, 1),
            "warm_ready_ms": round(warm["ready_s"] * 1e3, 1),
            "aot_cache_hits": int(warm["hits"]),
            "lanes": lanes,
            "buckets": list(buckets),
            "pipeline": {"d": d, "hidden": hidden, "depth": depth},
            "warm_compile_cache": "fresh empty dir (speedup is the "
                                  "serialized executables alone)",
        },
    )


def _run_chaos_experiment(
    fitted, buckets, d, *, fault_spec, rate, n_requests,
    fault_at_s, fault_for_s, settle_s, pipeline_depth=2,
    max_shed_rate=0.9, name="bench-chaos",
):
    """One chaos experiment over a full gateway: open-loop synthetic
    load, the fault armed mid-run, verdict from the invariant checker.
    Returns (verdict, report, injections)."""
    import jax.numpy as jnp

    from keystone_tpu.gateway import Gateway
    from keystone_tpu.loadgen import faults, synthesize
    from keystone_tpu.loadgen.invariants import InvariantChecker
    from keystone_tpu.loadgen.runner import (
        FaultPlan,
        InprocTarget,
        LoadGenerator,
    )

    point = fault_spec["point"]
    fired_before = faults.get_injector().fired_count(point)
    events = synthesize(
        n_requests, arrivals="poisson", rate=rate, shape=(d,), seed=11
    )
    with Gateway(
        fitted, buckets=buckets, n_lanes=2, max_delay_ms=2.0,
        pipeline_depth=pipeline_depth,
        warmup_example=jnp.zeros((d,), jnp.float32),
        name=name,
    ) as gw:
        gen = LoadGenerator(InprocTarget(gw, default_shape=(d,)))
        report = gen.run(
            events,
            faults=[FaultPlan(
                spec=fault_spec, at_s=fault_at_s, for_s=fault_for_s,
            )],
            settle_s=settle_s,
            recovery_probe_s=10.0,
        )
    verdict = InvariantChecker(
        p99_factor=1.5, recovery_within_s=10.0,
        max_shed_rate=max_shed_rate,
    ).check(report)
    injections = faults.get_injector().fired_count(point) - fired_before
    return verdict, injections


def _emit_chaos_row(emit, metric, verdict, injections, extra):
    # explicit raises, not asserts: a `python -O` run must not strip
    # the row's whole reason for existing and emit "green" unchecked
    if injections <= 0:
        raise RuntimeError(
            f"{metric}: the fault point never fired — the experiment "
            "proved nothing"
        )
    if not verdict.passed:
        raise RuntimeError(
            f"{metric}: serving invariants violated under chaos:\n"
            + verdict.to_json()
        )
    stats = verdict.stats
    pre = stats.get("pre_fault_p99_ms")
    # headline = recovered steady-state over pre-fault (the whole
    # post-window p99 rides in extra; it includes the backlog drain
    # right after the fault clears, which the recovery invariant
    # deliberately slides past)
    post = stats.get("recovered_p99_ms")
    if post is None:
        post = stats.get("post_fault_p99_ms")
    ratio = (
        round(post / pre, 3) if pre and post is not None else None
    )
    emit(
        metric, ratio, "p99_post_over_pre",
        extra={
            "verdict": "green" if verdict.passed else "red",
            "invariants": [r.name for r in verdict.invariants],
            "injections": injections,
            "requests": stats["issued"],
            "resolved": stats["resolved"],
            "untyped_failures": stats["untyped_failures"],
            "lost": stats["lost"],
            "shed_rate": stats["shed_rate"],
            "pre_fault_p99_ms": pre,
            "during_fault_p99_ms": stats.get("during_fault_p99_ms"),
            "post_fault_p99_ms": stats.get("post_fault_p99_ms"),
            "recovered_p99_ms": stats.get("recovered_p99_ms"),
            "p99_recovery_s": stats.get("p99_recovery_s"),
            "ready_recovery_s": (
                round(stats["ready_recovery_s"], 2)
                if stats.get("ready_recovery_s") is not None else None
            ),
            **extra,
        },
    )


def bench_chaos_lane_kill(
    emit, fitted, buckets: Sequence[int], d: int,
    n_requests: int = 256, rate: float = 50.0,
) -> None:
    """``serving_chaos_lane_kill`` — sustained open-loop load with one
    lane KILLED mid-window (``gateway.lane.kill`` matched to lane 0
    for 1.5 s): the pool's retry + success-corroborated health
    charging must absorb every injected failure. Asserted: zero
    untyped failures, every admitted request resolves, readiness
    holds, p99 recovers to within 1.5x pre-fault within 10 s of the
    fault clearing."""
    verdict, injections = _run_chaos_experiment(
        fitted, buckets, d,
        fault_spec={"point": "gateway.lane.kill", "match": {"lane": 0}},
        rate=rate, n_requests=n_requests,
        fault_at_s=1.5, fault_for_s=1.5, settle_s=2.0,
        name="bench-chaos-kill",
    )
    _emit_chaos_row(
        emit, "serving_chaos_lane_kill", verdict, injections,
        {"fault": "gateway.lane.kill lane=0 for 1.5s"},
    )


def bench_chaos_prep_stall(
    emit, fitted, buckets: Sequence[int], d: int,
    n_requests: int = 256, rate: float = 50.0,
    stall_ms: float = 40.0,
) -> None:
    """``serving_chaos_prep_stall`` — the pipelined lanes' host-prep
    stage stalled ``stall_ms`` per window for 1.5 s mid-run
    (``pipeline.host_prep.stall``): latency degrades and backpressure
    may shed (typed!), but nothing is lost, nothing 500s, and the
    tail recovers once the stall clears."""
    verdict, injections = _run_chaos_experiment(
        fitted, buckets, d,
        fault_spec={
            "point": "pipeline.host_prep.stall", "delay_ms": stall_ms,
        },
        rate=rate, n_requests=n_requests,
        fault_at_s=1.5, fault_for_s=1.5, settle_s=2.0,
        name="bench-chaos-stall",
    )
    _emit_chaos_row(
        emit, "serving_chaos_prep_stall", verdict, injections,
        {"fault": f"pipeline.host_prep.stall {stall_ms}ms for 1.5s"},
    )


def bench_router_failover(
    emit, fitted, buckets: Sequence[int], d: int,
    n_requests: int = 300, rate: float = 30.0,
) -> None:
    """``serving_router_failover`` — the fleet tier's acceptance row:
    a ``RouterServer`` fronting TWO in-process gateway replicas (each
    on a private registry, scraped over real HTTP), open-loop load
    through the router, and replica #1's responses black-holed for
    1.5 s mid-run (``router.replica.blackhole`` matched to its
    registration index — every answer it produces is dropped on the
    return path, the network-level equivalent of the process dying).
    The router must route around it: invariant verdict asserted
    (every admitted request resolves, typed sheds only, the router's
    ``/readyz`` holds, recovered p99 within 1.5x pre-fault), the
    injection count audited, and the headline fleet p99 computed from
    the router's own federated ``/metrics`` by merging the two
    replicas' scraped ``le`` buckets — with both replicas required to
    have actually served (a merge of one replica proves nothing)."""
    import urllib.request

    import jax.numpy as jnp

    from keystone_tpu.fleet import RouterServer
    from keystone_tpu.gateway import Gateway, GatewayServer
    from keystone_tpu.loadgen import faults, synthesize
    from keystone_tpu.loadgen.invariants import InvariantChecker
    from keystone_tpu.loadgen.runner import (
        FaultPlan,
        HttpTarget,
        LoadGenerator,
    )
    from keystone_tpu.observability.prometheus import (
        histogram_buckets,
        merge_histograms,
        quantile_from_buckets,
    )
    from keystone_tpu.observability.registry import MetricsRegistry

    point = "router.replica.blackhole"
    fired_before = faults.get_injector().fired_count(point)
    replicas = []
    router = None
    try:
        for i in range(2):
            # private registry per replica: in one process the two
            # "hosts" must not share metric series, exactly like real
            # processes wouldn't — the router only ever sees their
            # /metrics scrapes
            reg = MetricsRegistry()
            gw = Gateway(
                fitted, buckets=buckets, n_lanes=2, max_delay_ms=2.0,
                warmup_example=jnp.zeros((d,), jnp.float32),
                name=f"bench-fleet-r{i}", registry=reg,
            )
            srv = GatewayServer(gw, port=0, registry=reg).start()
            replicas.append((gw, srv))
        router = RouterServer(
            [srv.url() for _, srv in replicas],
            port=0,
            name="bench-router",
            registry=MetricsRegistry(),
            probe_interval_s=0.25,
            recovery_after_s=1.0,
        ).start()
        router.fleet.probe_once()  # don't race the first probe tick
        # rate sized for the WORST case this row runs in: replicas,
        # router, and 100+ client threads all share one CPU process
        # (GIL and all), so a saturating rate would turn the post-fault
        # backlog drain into a p99-recovery failure that has nothing
        # to do with the router. The arrival tail (10 s of traffic vs
        # a 3.5 s fault window) is what recovery is measured ON —
        # arrivals that stop at the fault's edge leave the recovery
        # invariant nothing to observe.
        events = synthesize(
            n_requests, arrivals="poisson", rate=rate, shape=(d,),
            seed=13,
        )
        # bounded outstanding for the same reason: on a small CI host
        # 128 client threads thrash the GIL against the servers and
        # the backlog's drain — not the router — becomes the tail
        gen = LoadGenerator(
            HttpTarget(router.url(), default_shape=(d,)),
            max_outstanding=32,
        )
        report = gen.run(
            events,
            faults=[FaultPlan(
                spec={"point": point, "match": {"index": 1}},
                at_s=2.0, for_s=1.5,
            )],
            settle_s=3.0,
            recovery_probe_s=10.0,
        )
        verdict = InvariantChecker(
            p99_factor=1.5, recovery_within_s=10.0, max_shed_rate=0.9,
        ).check(report)
        injections = (
            faults.get_injector().fired_count(point) - fired_before
        )
        with urllib.request.urlopen(
            router.url("/metrics"), timeout=15
        ) as resp:
            federated = resp.read().decode("utf-8")
        with urllib.request.urlopen(
            router.url("/fleetz"), timeout=15
        ) as resp:
            roster = json.loads(resp.read())
        retries = router.metrics.retry_count()
    finally:
        if router is not None:
            router.stop()
        for gw, srv in replicas:
            gw.close()
            srv.stop()
    per_replica = [
        histogram_buckets(
            federated,
            "keystone_gateway_request_latency_seconds",
            {"gateway": f"bench-fleet-r{i}"},
        )
        for i in range(2)
    ]
    served_per = [b[-1][1] if b else 0.0 for b in per_replica]
    # explicit raises, not asserts: python -O must not strip the
    # row's acceptance contract
    if min(served_per) <= 0:
        raise RuntimeError(
            "serving_router_failover: a replica served nothing "
            f"(per-replica request counts {served_per}) — the fleet "
            "number would be one replica's, not a federation"
        )
    fleet_buckets = merge_histograms(per_replica)
    fleet_p99 = quantile_from_buckets(0.99, fleet_buckets)
    if fleet_p99 is None:
        raise RuntimeError(
            "serving_router_failover: the router's federated "
            "/metrics had no latency buckets:\n" + federated
        )
    if injections <= 0:
        raise RuntimeError(
            "serving_router_failover: router.replica.blackhole never "
            "fired — the experiment proved nothing"
        )
    if not verdict.passed:
        raise RuntimeError(
            "serving_router_failover: serving invariants violated "
            "under replica loss:\n" + verdict.to_json()
        )
    stats = verdict.stats
    pre = stats.get("pre_fault_p99_ms")
    post = stats.get("recovered_p99_ms")
    if post is None:
        post = stats.get("post_fault_p99_ms")
    emit(
        "serving_router_failover",
        fleet_p99 * 1e3, "ms",
        extra={
            "source": "router's federated /metrics "
                      "(merge_histograms over per-replica le buckets)",
            "verdict": "green" if verdict.passed else "red",
            "invariants": [r.name for r in verdict.invariants],
            "fault": "router.replica.blackhole index=1 for 1.5s",
            "injections": injections,
            "router_retries": int(retries),
            "requests": stats["issued"],
            "resolved": stats["resolved"],
            "untyped_failures": stats["untyped_failures"],
            "lost": stats["lost"],
            "shed_rate": stats["shed_rate"],
            "pre_fault_p99_ms": pre,
            "during_fault_p99_ms": stats.get("during_fault_p99_ms"),
            "recovered_p99_ms": stats.get("recovered_p99_ms"),
            "p99_post_over_pre": (
                round(post / pre, 3)
                if pre and post is not None else None
            ),
            "per_replica_requests": served_per,
            "per_replica_p99_ms": [
                round(q * 1e3, 3) if q is not None else None
                for q in (
                    quantile_from_buckets(0.99, b) for b in per_replica
                )
            ],
            "fleet_states": roster.get("counts"),
        },
    )


def bench_router_trace_overhead(
    emit, fitted, buckets: Sequence[int], d: int,
    n_pairs: int = 250, max_ratio: float = 1.05,
) -> None:
    """``serving_router_trace_overhead`` — the distributed-tracing
    cost contract: the same router + replica serving the same serial
    request stream with fleet tracing OFF and ON (router.forward
    spans, W3C ``traceparent`` to the replica, the replica's full
    admit → coalesce → dispatch chain, X-Keystone-Trace echo),
    asserted ``p99(on) <= 1.05 x p99(off)``.

    Methodology (this row fights a 2-core CI host whose scheduler
    hiccups are 2-5x the latency being measured, so the estimator is
    built for it):

    - requests alternate off/on PAIRWISE (the global tracer flag is
      one attribute write), so host drift hits both distributions
      equally instead of whichever mode ran second;
    - pairs where EITHER side exceeds 3x the pooled median are
      dropped — a host stall hit that pair; the filter is symmetric
      (the whole pair goes), so it cannot favor a mode, and the drop
      count is reported in the row for audit;
    - serial closed-loop issue, because this measures per-request
      overhead, not capacity;
    - a red ratio gets ONE fresh measurement round (the smoke-chaos
      bounded-retry doctrine) before the row fails for real."""
    import urllib.request

    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.fleet import RouterServer
    from keystone_tpu.gateway import Gateway, GatewayServer
    from keystone_tpu.observability import tracing
    from keystone_tpu.observability.registry import MetricsRegistry

    tracer = tracing.get_tracer()
    was_enabled = tracer.enabled
    reg = MetricsRegistry()
    gw = Gateway(
        fitted, buckets=buckets, n_lanes=1, max_delay_ms=1.0,
        warmup_example=jnp.zeros((d,), jnp.float32),
        name="bench-trace-r0", registry=reg,
    )
    srv = GatewayServer(gw, port=0, registry=reg).start()
    # probes quieted to one-per-30s: a concurrent /metrics render on
    # a 2-core host is exactly the kind of hiccup the filter exists
    # for — don't generate it ourselves 4x/second
    router = RouterServer(
        [srv.url()], port=0, name="bench-trace-router",
        registry=MetricsRegistry(), probe_interval_s=30.0,
    ).start()
    try:
        router.fleet.probe_once()
        body = json.dumps(
            {"instances": [[0.0] * d]}
        ).encode("utf-8")

        def one() -> float:
            req = urllib.request.Request(
                router.url("/predict"), data=body,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            t0 = time.perf_counter()
            with urllib.request.urlopen(req, timeout=30) as resp:
                resp.read()
            return time.perf_counter() - t0

        def measure():
            off, on = [], []
            for _ in range(n_pairs):
                tracing.disable_tracing()
                off.append(one())
                tracing.enable_tracing()
                on.append(one())
            tracing.disable_tracing()
            a, b = np.asarray(off), np.asarray(on)
            hiccup = 3.0 * float(np.median(np.concatenate([a, b])))
            keep = (a <= hiccup) & (b <= hiccup)
            p99_off = float(np.percentile(a[keep], 99))
            p99_on = float(np.percentile(b[keep], 99))
            return (
                p99_off, p99_on, p99_on / p99_off,
                int((~keep).sum()),
            )

        for _ in range(10):  # let both paths warm before measuring
            one()
        rounds = 1
        p99_off, p99_on, ratio, dropped = measure()
        if ratio > max_ratio:
            rounds = 2
            p99_off, p99_on, ratio, dropped = measure()
    finally:
        tracer.enabled = was_enabled
        router.stop()
        gw.close()
        srv.stop()
    # explicit raise, not assert: python -O must not strip the
    # row's acceptance contract
    if ratio > max_ratio:
        raise RuntimeError(
            "serving_router_trace_overhead: tracing-on p99 "
            f"{p99_on * 1e3:.2f}ms > {max_ratio}x tracing-off p99 "
            f"{p99_off * 1e3:.2f}ms (ratio {ratio:.3f}) on both "
            "measurement rounds — the span plane is no longer "
            "hot-path-cheap"
        )
    emit(
        "serving_router_trace_overhead",
        ratio, "x",
        extra={
            "p99_off_ms": round(p99_off * 1e3, 3),
            "p99_on_ms": round(p99_on * 1e3, 3),
            "pairs": n_pairs,
            "hiccup_pairs_dropped": dropped,
            "rounds": rounds,
            "bound": f"p99_on <= {max_ratio} x p99_off",
            "verdict": "green" if ratio <= max_ratio else "red",
            "method": "pairwise-interleaved serial requests through "
                      "router + 1 HTTP replica (off/on alternating "
                      "per request; pairs with a >3x-median host "
                      "stall on either side dropped symmetrically)",
        },
    )


def bench_autoscale_ramp(
    emit, fitted, buckets: Sequence[int], d: int,
    max_replicas: int = 3,
) -> None:
    """``serving_autoscale_ramp`` — the elasticity acceptance row: a
    ``RouterServer`` + the ``keystone_tpu/autoscale/`` supervisor and
    control loop over in-process replicas (the subprocess path is the
    smoke script's; this row exercises the identical policy/
    supervisor/scraper machinery without paying a JAX import per
    replica), driven by a STEP-LOAD RAMP (``synthesize_steps``):
    a low baseline, a surge calibrated to ~3x one replica's measured
    capacity, and a drop back to baseline. Mid-surge — mid-SCALE-UP —
    the ``router.replica.partition`` chaos point severs the original
    replica's forwards for ~1.2 s.

    Asserted (raises, not asserts — ``python -O`` must not strip the
    acceptance contract):

    - the fleet SCALES OUT (>= 2 replicas seen) and back DOWN to the
      1-replica baseline once the load drops (drain-based retirement);
    - the loadgen invariant verdict is GREEN across the whole run:
      every admitted request resolves, failures are typed sheds only,
      readiness holds, p99 recovers after the partition clears;
    - the partition actually fired (a chaos leg that never fired
      proved nothing).

    Rates and the SLO threshold are CALIBRATED against a measured
    sequential baseline latency so the surge genuinely overloads one
    replica on any host speed — a fixed rate would be a no-op on a
    fast box and a massacre on a slow one. One bounded in-row retry
    (the smoke-chaos doctrine): the recovery clock races the host
    scheduler on a loaded 2-core CI box."""
    import urllib.request

    import jax.numpy as jnp

    from keystone_tpu.autoscale.controller import (
        Autoscaler,
        RouterScraper,
    )
    from keystone_tpu.autoscale.policy import PolicyConfig, PolicyEngine
    from keystone_tpu.autoscale.supervisor import (
        InprocLauncher,
        Supervisor,
    )
    from keystone_tpu.fleet import RouterServer
    from keystone_tpu.gateway import Gateway, GatewayServer
    from keystone_tpu.loadgen import faults
    from keystone_tpu.loadgen.invariants import InvariantChecker
    from keystone_tpu.loadgen.runner import (
        FaultPlan,
        HttpTarget,
        LoadGenerator,
    )
    from keystone_tpu.loadgen.trace import synthesize_steps
    from keystone_tpu.observability import tracing
    from keystone_tpu.observability.registry import MetricsRegistry

    point = "router.replica.partition"
    # requests carry a full bucket of rows so coalescing cannot
    # multiply one replica's capacity past the calibration below —
    # the surge must genuinely overload exactly one replica
    n_rows = min(buckets)

    def run_once(attempt: int):
        tracer = tracing.get_tracer()
        was_enabled = tracer.enabled
        # phase evidence (the policy's queue_wait-vs-device veto) and
        # the autoscale.decision spans both ride the tracer
        tracing.enable_tracing()
        fired_before = faults.get_injector().fired_count(point)
        router = RouterServer(
            [], port=0, name=f"bench-autoscale-{attempt}",
            registry=MetricsRegistry(),
            probe_interval_s=0.25,
            recovery_after_s=1.0,
        ).start()

        def factory(index: int):
            reg = MetricsRegistry()
            gw = Gateway(
                fitted, buckets=buckets, n_lanes=1, max_delay_ms=2.0,
                warmup_example=jnp.zeros((d,), jnp.float32),
                name=f"bench-as{attempt}-r{index}", registry=reg,
            )
            srv = GatewayServer(gw, port=0, registry=reg).start()
            return gw, srv

        supervisor = Supervisor(
            InprocLauncher(factory),
            router.url(),
            startup_timeout_s=60.0,
            drain_timeout_s=15.0,
        )
        autoscaler = None
        try:
            supervisor.scale_to(1)
            for _ in range(40):  # don't race the first probe tick
                router.fleet.probe_once()
                if any(
                    r.ready and r.healthy
                    for r in router.fleet.replicas()
                ):
                    break
                time.sleep(0.25)

            # -- calibration: one replica's sequential service time --
            body = json.dumps(
                {"instances": [[0.1] * d] * n_rows}
            ).encode("utf-8")

            def one() -> float:
                req = urllib.request.Request(
                    router.url("/predict"), data=body,
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                t0 = time.perf_counter()
                with urllib.request.urlopen(req, timeout=30) as resp:
                    resp.read()
                return time.perf_counter() - t0

            for _ in range(3):
                one()  # warm both hops
            lat = sorted(one() for _ in range(8))
            base_s = lat[len(lat) // 2]
            # the surge must EXCEED one replica's capacity on any
            # host speed: 4x the sequential rate, with the client's
            # outstanding bound (64 below) guaranteeing a deep queue
            # — and the SLO sits at 5x the unloaded baseline, far
            # under what a saturated replica's queue produces but
            # comfortably above the baseline's scheduler noise
            capacity_rps = 1.0 / max(base_s, 1e-3)
            low_rate = min(8.0, max(1.0, 0.1 * capacity_rps))
            high_rate = min(300.0, max(10.0, 4.0 * capacity_rps))
            slo_s = max(0.03, 5.0 * base_s)

            engine = PolicyEngine(PolicyConfig(
                min_replicas=1,
                max_replicas=max_replicas,
                slo_latency_s=slo_s,
                up_consecutive=2,
                down_consecutive=4,
                up_cooldown_s=2.0,
                down_cooldown_s=2.0,
                down_p99_headroom=0.5,
            ))
            autoscaler = Autoscaler(
                supervisor,
                RouterScraper(
                    router.url(), p99_window_s=3.0,
                    phase_samples_per_tick=2,
                ),
                engine,
                interval_s=0.5,
                registry=router.registry,
                name=f"bench-autoscale-{attempt}",
            ).start()

            # low 4s -> surge 10s -> low 10s; the partition severs
            # the ORIGINAL replica (index 0) mid-surge, mid-scale-up
            steps = [
                (low_rate, 4.0), (high_rate, 10.0), (low_rate, 10.0),
            ]
            events = synthesize_steps(
                steps, arrivals="poisson", shape=(d,),
                size_mix=((n_rows, 1.0),), seed=29,
            )
            gen = LoadGenerator(
                HttpTarget(router.url(), default_shape=(d,)),
                max_outstanding=64,
            )
            report = gen.run(
                events,
                faults=[FaultPlan(
                    spec={"point": point, "match": {"index": 0}},
                    at_s=9.0, for_s=1.2,
                )],
                settle_s=6.0,
                recovery_probe_s=10.0,
            )
            verdict = InvariantChecker(
                p99_factor=2.0, recovery_within_s=12.0,
                max_shed_rate=0.9,
            ).check(report)
            injections = (
                faults.get_injector().fired_count(point) - fired_before
            )

            # scale-down back to baseline: the load is gone, the
            # cold streak + cooldowns need a few more ticks
            deadline = time.perf_counter() + 25.0
            while (
                supervisor.target > 1
                and time.perf_counter() < deadline
            ):
                time.sleep(0.5)
            final_target = supervisor.target
            max_seen = autoscaler.max_replicas_seen
            decisions = [
                (d2.action, d2.reason)
                for d2 in autoscaler.decisions
                if d2.action != "hold"
            ]
            up_count = autoscaler.metrics.decision_count("scale_up")
            down_count = autoscaler.metrics.decision_count("scale_down")
        finally:
            if autoscaler is not None:
                autoscaler.stop()
            supervisor.stop()
            router.stop()
            tracer.enabled = was_enabled
        return {
            "verdict": verdict,
            "report": report,
            "injections": injections,
            "max_seen": max_seen,
            "final_target": final_target,
            "decisions": decisions,
            "up_count": up_count,
            "down_count": down_count,
            "base_ms": base_s * 1e3,
            "slo_ms": slo_s * 1e3,
            "low_rate": low_rate,
            "high_rate": high_rate,
        }

    last_error = None
    for attempt in (1, 2):
        try:
            r = run_once(attempt)
        except Exception as e:
            if attempt == 1:
                # a host stall mid-calibration (or mid-drill) gets
                # the same single fresh chance a red verdict does
                last_error = f"attempt 1 raised {type(e).__name__}: {e}"
                continue
            raise
        problems = []
        if r["injections"] <= 0:
            problems.append(
                f"{point} never fired — the chaos leg proved nothing"
            )
        if not r["verdict"].passed:
            problems.append(
                "serving invariants violated:\n" + r["verdict"].to_json()
            )
        if r["max_seen"] < 2:
            problems.append(
                f"fleet never scaled out (max {r['max_seen']} replica)"
            )
        if r["final_target"] != 1:
            problems.append(
                "fleet did not scale back down to the 1-replica "
                f"baseline (final target {r['final_target']})"
            )
        if not problems:
            break
        last_error = "; ".join(problems)
        if attempt == 1:
            # host-load flake guard: one fresh experiment, same
            # bounded-retry doctrine as the other chaos/fleet rows
            continue
        raise RuntimeError(
            f"serving_autoscale_ramp failed on both attempts: "
            f"{last_error}"
        )
    stats = r["verdict"].stats
    emit(
        "serving_autoscale_ramp",
        stats.get("recovered_p99_ms") or stats.get("post_fault_p99_ms"),
        "ms",
        extra={
            "verdict": "green",
            "invariants": [x.name for x in r["verdict"].invariants],
            "fault": f"{point} index=0 for 1.2s mid-surge",
            "injections": r["injections"],
            "max_replicas_seen": r["max_seen"],
            "final_target": r["final_target"],
            "scale_ups": r["up_count"],
            "scale_downs": r["down_count"],
            "decisions": r["decisions"],
            "calibrated_baseline_ms": round(r["base_ms"], 2),
            "slo_ms": round(r["slo_ms"], 2),
            "ramp_rps": [round(r["low_rate"], 1),
                         round(r["high_rate"], 1),
                         round(r["low_rate"], 1)],
            "requests": stats["issued"],
            "resolved": stats["resolved"],
            "untyped_failures": stats["untyped_failures"],
            "lost": stats["lost"],
            "shed_rate": stats["shed_rate"],
            "pre_fault_p99_ms": stats.get("pre_fault_p99_ms"),
            "during_fault_p99_ms": stats.get("during_fault_p99_ms"),
            "recovered_p99_ms": stats.get("recovered_p99_ms"),
        },
    )


def run_autoscale_benches(
    emit,
    d: int = 64,
    hidden: int = 256,
    depth: int = 3,
    buckets: Sequence[int] = (8, 16),
    fitted=None,
) -> None:
    """The elasticity row (~45 s of ramped load through a live
    autoscaler; run by ``bin/smoke-autoscale.sh``). Deliberately a
    smaller pipeline than the default bench shape: the row measures
    the CONTROL LOOP, and per-replica warmup compile time directly
    stretches the scale-up reaction it asserts on."""
    if fitted is None:
        fitted = build_pipeline(d, hidden, depth)
    bench_autoscale_ramp(emit, fitted, buckets, d)


def run_fleet_benches(
    emit,
    d: int = 256,
    hidden: int = 512,
    depth: int = 4,
    buckets: Sequence[int] = (8, 32, 128),
    fitted=None,
    rows: str = "all",
) -> None:
    """The fleet-tier rows (~10 s of sustained load through a real
    router + two HTTP replicas, then the tracing-overhead A/B).
    ``rows`` narrows to one row ("failover" / "trace") —
    bin/smoke-fleet.sh runs each in its OWN process so a retry of one
    row doesn't re-pay the other, and the overhead A/B measures a
    quiet process instead of the failover row's thread aftermath."""
    if fitted is None:
        fitted = build_pipeline(d, hidden, depth)
    if rows in ("all", "failover"):
        bench_router_failover(emit, fitted, buckets, d)
    if rows in ("all", "trace"):
        bench_router_trace_overhead(emit, fitted, buckets, d)


def run_featurize_benches(emit) -> None:
    """The device-side featurization A/Bs (run by
    ``bin/smoke-featurize.sh``): the demo conv-chain row (~30 s: two
    gateway warmups + three sustained passes per path) and the flagship
    SIFT+LCS→FV row (heavier featurize, fewer requests). Each row owns
    its pipeline shape — the geometry (raw uint8 bytes vs featurized
    f32 bytes) is what the H2D assertion prices, so neither inherits
    the generic bench dims."""
    bench_device_featurize(emit)
    bench_flagship_featurize(emit)


def run_zoo_benches(emit) -> None:
    """The model-zoo CSE row alone (``--zoo-only``, what
    ``bin/smoke-zoo.sh`` invokes): two flagship-featurize models
    served through one ModelZoo vs two independent gateways. Owns its
    pipeline shape — the shared prefix IS the measurement, so it
    doesn't inherit the generic bench dims."""
    bench_zoo(emit)


def run_attribution_benches(emit) -> None:
    """The attribution & drift row alone (``--attribution-only``, what
    ``bin/smoke-attribution.sh`` invokes): a two-model CSE zoo through
    a mid-run size-mixture shift, gating the ledger sum invariant, PSI
    selectivity, the re-plan audit, and the attribution-on/off p99
    ratio. Owns its (small) pipeline shape — the row builds three
    zoos for the A/B, so the generic bench dims would turn it into a
    compile benchmark."""
    bench_attribution_drift(emit)


def run_lifecycle_benches(emit) -> None:
    """The online-lifecycle row alone (``--lifecycle-only``, what
    ``bin/smoke-rollout.sh`` invokes): streaming refit → shadow →
    canary → promote under open-loop load, then a poisoned refit
    auto-rolled back. Owns its (small) pipeline shape — the drill
    runs several engine builds, so the generic bench dims would turn
    it into a compile benchmark."""
    bench_online_refit(emit)


def run_shard_benches(emit) -> None:
    """The model-axis A/B alone (``--shard-only``, what
    ``bin/smoke-shard.sh`` invokes; ~60 s of gateway warmups across
    the size sweep). Its own model shapes — the size sweep and the
    over-budget model ARE the measurement, so it doesn't inherit the
    generic bench dims."""
    bench_sharded_vs_replicated(emit)


def run_serving_benches(
    emit,
    d: int = 256,
    hidden: int = 512,
    depth: int = 4,
    buckets: Sequence[int] = (8, 32, 128),
    chaos: bool = False,
    cold_start: bool = True,
    fleet: bool = False,
    autoscale: bool = False,
    featurize: bool = False,
    shard: bool = False,
    zoo: bool = False,
    lifecycle: bool = False,
    attribution: bool = False,
) -> None:
    fitted = build_pipeline(d, hidden, depth)
    bench_cold_vs_warm(emit, fitted, buckets, d)
    bench_bucketed_throughput(emit, fitted, buckets, d)
    bench_microbatch(emit, fitted, buckets, d)
    bench_gateway(emit, fitted, buckets, d)
    bench_swap_blip(emit, fitted, buckets, d)
    bench_pipeline_overlap(emit, fitted, buckets, d)
    bench_goodput_mfu(emit, fitted, buckets, d)
    if cold_start:
        import jax

        if jax.default_backend() == "cpu":
            # cross-process row with its own (heavier) pipeline config
            # — see bench_cold_start_aot's docstring for why it
            # doesn't inherit this function's toy shape
            bench_cold_start_aot(emit)
        else:
            # the drill needs TWO live gateway processes on the
            # backend; exclusive-device backends (TPU/GPU) can't share
            # the chip with this already-initialized parent, and the
            # children are deliberately pinned so they'd fail loudly
            # rather than silently measure CPU. Skip visibly — run the
            # row from a fresh CPU process (or a host whose device is
            # free) instead of turning every device bench red.
            emit(
                "serving_cold_start_aot", None, "skipped",
                extra={
                    "skipped": True,
                    "reason": "cross-process drill needs the device "
                              "free; parent bench already holds "
                              f"{jax.default_backend()}",
                },
            )
    if chaos:
        run_chaos_benches(emit, d=d, hidden=hidden, depth=depth,
                          buckets=buckets, fitted=fitted)
    if fleet:
        run_fleet_benches(emit, d=d, hidden=hidden, depth=depth,
                          buckets=buckets, fitted=fitted)
    if featurize:
        run_featurize_benches(emit)
    if shard:
        run_shard_benches(emit)
    if zoo:
        run_zoo_benches(emit)
    if lifecycle:
        run_lifecycle_benches(emit)
    if attribution:
        run_attribution_benches(emit)
    if autoscale:
        # its own (smaller) pipeline: scale-up reaction time includes
        # per-replica warmup, which the default bench shape would
        # stretch past the drill's ramp timings
        run_autoscale_benches(emit)


def run_chaos_benches(
    emit,
    d: int = 256,
    hidden: int = 512,
    depth: int = 4,
    buckets: Sequence[int] = (8, 32, 128),
    fitted=None,
) -> None:
    """The chaos rows alone (bin/smoke-chaos.sh's entry; each row is
    a ~10 s sustained-load experiment, so they're opt-in). Callers
    that already built the bench pipeline pass it via ``fitted`` —
    a second fit + warm-compile would waste seconds for nothing."""
    if fitted is None:
        fitted = build_pipeline(d, hidden, depth)
    bench_chaos_lane_kill(emit, fitted, buckets, d)
    bench_chaos_prep_stall(emit, fitted, buckets, d)


def main(argv=None) -> int:
    """``python -m keystone_tpu serve-bench [--buckets 8,32,128] ...``"""
    import argparse

    from keystone_tpu.parallel.runtime import setup_compilation_cache

    ap = argparse.ArgumentParser(
        prog="keystone_tpu serve-bench", description=__doc__
    )
    ap.add_argument("--buckets", default="8,32,128",
                    help="comma-separated row buckets")
    ap.add_argument("--d", type=int, default=256,
                    help="feature dim of the bench pipeline")
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--depth", type=int, default=4,
                    help="number of matmul nodes in the bench pipeline")
    ap.add_argument("--no-cache", action="store_true",
                    help="run with NO persistence: skips BOTH the "
                    "persistent XLA compile cache and the AOT "
                    "serialized-executable store. The two caches "
                    "deflate a cold measurement in different ways — "
                    "the compile cache replays the XLA compile from "
                    "disk, the AOT store skips trace+compile entirely "
                    "— so the honest cold baseline disables both "
                    "(serving_cold_vs_warm_latency additionally "
                    "detaches them in-row; see bench_cold_vs_warm)")
    ap.add_argument("--aot-cache", default=None, metavar="DIR",
                    help="AOT executable store dir (default: "
                    "$KEYSTONE_AOT_CACHE, then "
                    "~/.cache/keystone_tpu/aot). Ignored under "
                    "--no-cache")
    ap.add_argument("--chaos", action="store_true",
                    help="also run the chaos rows (serving_chaos_"
                    "lane_kill / serving_chaos_prep_stall): sustained "
                    "open-loop load with a fault injected mid-run, "
                    "invariant verdict asserted (~10s each)")
    ap.add_argument("--chaos-only", action="store_true",
                    help="run ONLY the chaos rows (what "
                    "bin/smoke-chaos.sh invokes)")
    ap.add_argument("--fleet", action="store_true",
                    help="also run the fleet-tier row "
                    "(serving_router_failover): open-loop load "
                    "through the cross-host router + two in-process "
                    "HTTP replicas with one replica black-holed "
                    "mid-run, invariant verdict asserted and the "
                    "fleet p99 read from the router's federated "
                    "/metrics (~10s)")
    ap.add_argument("--fleet-only", action="store_true",
                    help="run ONLY the fleet-tier rows "
                    "(serving_router_failover + "
                    "serving_router_trace_overhead)")
    ap.add_argument("--fleet-rows", default="all",
                    choices=("all", "failover", "trace"),
                    help="with --fleet-only: narrow to one fleet row "
                    "(bin/smoke-fleet.sh runs failover and trace in "
                    "separate processes so each retries alone and "
                    "the tracing A/B measures a quiet process)")
    ap.add_argument("--featurize", action="store_true",
                    help="also run the device-side featurization row "
                    "(serving_device_featurize): the same image "
                    "featurize chain + model served host_featurize vs "
                    "device_featurize, asserting matching outputs, "
                    ">=3x fewer H2D bytes/request, and device "
                    "examples/sec >= host (~30s)")
    ap.add_argument("--featurize-only", action="store_true",
                    help="run ONLY the device-side featurization row "
                    "(what bin/smoke-featurize.sh invokes)")
    ap.add_argument("--zoo", action="store_true",
                    help="also run the model-zoo CSE row "
                    "(serving_zoo): two models sharing the flagship "
                    "featurize prefix served through one ModelZoo "
                    "(SharedPrefixEngine) vs two independent "
                    "gateways, asserting per-model output parity, "
                    "prefix compiled once per bucket, fewer device "
                    "dispatches, and >= 1.5x ensemble ex/s (~60s)")
    ap.add_argument("--zoo-only", action="store_true",
                    help="run ONLY the model-zoo CSE row (what "
                    "bin/smoke-zoo.sh invokes)")
    ap.add_argument("--lifecycle", action="store_true",
                    help="also run the online-lifecycle row "
                    "(serving_online_refit): streaming refit from "
                    "labeled feedback promoted shadow -> canary -> "
                    "swap under open-loop load with zero failed "
                    "requests asserted, then a refit poisoned via "
                    "lifecycle.refit.poison auto-rolled back by the "
                    "held-out accuracy gate within one policy tick "
                    "(~30s)")
    ap.add_argument("--lifecycle-only", action="store_true",
                    help="run ONLY the online-lifecycle row (what "
                    "bin/smoke-rollout.sh invokes)")
    ap.add_argument("--attribution", action="store_true",
                    help="also run the attribution & drift row "
                    "(serving_attribution_drift): a two-model CSE "
                    "zoo through a mid-run size-mixture shift, "
                    "asserting per-model ledger totals sum to engine "
                    "totals (<=1e-6 rel), PSI drift fires on the "
                    "shifted model only, the /driftz re-plan "
                    "recommendation is non-empty and directionally "
                    "correct, and attribution-on p99 <= 1.05x off "
                    "(~60s)")
    ap.add_argument("--attribution-only", action="store_true",
                    help="run ONLY the attribution & drift row (what "
                    "bin/smoke-attribution.sh invokes)")
    ap.add_argument("--shard", action="store_true",
                    help="also run the model-axis A/B "
                    "(serving_sharded_vs_replicated): the same model "
                    "served mesh-sharded (param_sharding over a "
                    "(1, N)-device mesh) vs N replicated lanes, "
                    "asserting output parity and that the "
                    "over-one-device-budget model serves sharded; "
                    "needs >= 2 devices (on CPU: XLA_FLAGS="
                    "--xla_force_host_platform_device_count=8)")
    ap.add_argument("--shard-only", action="store_true",
                    help="run ONLY the model-axis A/B "
                    "(what bin/smoke-shard.sh invokes)")
    ap.add_argument("--autoscale", action="store_true",
                    help="also run the elasticity row "
                    "(serving_autoscale_ramp): a step-load ramp "
                    "through a live router + autoscale control loop "
                    "over in-process replicas, with "
                    "router.replica.partition fired mid-scale-up — "
                    "scale-out, green verdict, and drain-based "
                    "scale-down all asserted (~45s)")
    ap.add_argument("--autoscale-only", action="store_true",
                    help="run ONLY the elasticity row (what "
                    "bin/smoke-autoscale.sh invokes)")
    ap.add_argument("--no-cold-start", action="store_true",
                    help="skip the serving_cold_start_aot row (it "
                    "spawns fresh gateway subprocesses and takes "
                    "~1 min; the in-process rows are unaffected)")
    ap.add_argument("--profile-dir", default=None, metavar="DIR",
                    help="wrap the whole bench run in a jax.profiler "
                    "trace written to DIR (open in Perfetto or "
                    "TensorBoard's XProf plugin) — any row can be "
                    "profiled without code edits")
    args = ap.parse_args(argv)
    if not args.no_cache:
        from keystone_tpu.parallel.runtime import setup_aot_cache

        setup_compilation_cache()
        setup_aot_cache(args.aot_cache)
    buckets = tuple(int(b) for b in args.buckets.split(","))

    def emit(metric, value, unit, vs=None, extra=None):
        row = {
            "metric": metric,
            "value": round(value, 2) if value is not None else None,
            "unit": unit,
            "vs_baseline": round(vs, 2) if vs else None,
        }
        if extra:
            row.update(extra)
        print(json.dumps(row), flush=True)

    def run():
        if args.shard_only:
            run_shard_benches(emit)
        elif args.featurize_only:
            run_featurize_benches(emit)
        elif args.zoo_only:
            run_zoo_benches(emit)
        elif args.lifecycle_only:
            run_lifecycle_benches(emit)
        elif args.attribution_only:
            run_attribution_benches(emit)
        elif args.autoscale_only:
            run_autoscale_benches(emit)
        elif args.fleet_only:
            run_fleet_benches(
                emit, d=args.d, hidden=args.hidden, depth=args.depth,
                buckets=buckets, rows=args.fleet_rows,
            )
        elif args.chaos_only:
            run_chaos_benches(
                emit, d=args.d, hidden=args.hidden, depth=args.depth,
                buckets=buckets,
            )
        else:
            run_serving_benches(
                emit, d=args.d, hidden=args.hidden, depth=args.depth,
                buckets=buckets, chaos=args.chaos,
                cold_start=not args.no_cold_start,
                fleet=args.fleet,
                autoscale=args.autoscale,
                featurize=args.featurize,
                shard=args.shard,
                zoo=args.zoo,
                lifecycle=args.lifecycle,
                attribution=args.attribution,
            )

    if args.profile_dir:
        from keystone_tpu.utils.profiling import trace

        with trace(args.profile_dir):
            run()
        print(
            json.dumps({"profile_dir": args.profile_dir}), flush=True
        )
    else:
        run()
    return 0
