"""Tracing / profiling utilities.

Reference (SURVEY.md §5): the reference's tracing is (1) the AutoCacheRule
sample profiler (workflow/auto_cache.py here), (2) ad-hoc per-phase timing
logs (e.g. KernelRidgeRegression.scala:213-221), and (3) Graphviz DOT
export of the DAG logged on every optimizer rule application.

TPU equivalents here:
- ``trace(dir)``: context manager around the JAX profiler — produces
  XPlane traces viewable in TensorBoard/XProf (the substrate-level trace
  the reference lacked).
- ``PhaseTimer``: the per-phase wall-clock logger.
- ``instrument_executor``: hooks a GraphExecutor's per-node timing
  callback to record execution wall time (the interpret-layer profile).
- DOT export lives on the Graph itself (``Graph.to_dot``), same as the
  reference's toDOTString.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import threading
import time
from typing import Deque, Dict, Iterator, Optional

import jax

logger = logging.getLogger(__name__)


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """JAX profiler trace (XPlane) around a block of pipeline work."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class PhaseTimer:
    """Accumulates named phase wall-clock times (reference: the
    kernelGen/residual/collect/localSolve/modelUpdate logs in KRR)."""

    def __init__(self, name: str = ""):
        self.name = name
        self.times: Dict[str, float] = {}
        self._published: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, phase_name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.times[phase_name] = self.times.get(phase_name, 0.0) + dt

    def summary(self) -> str:
        parts = [f"{k}: {v:.3f}s" for k, v in self.times.items()]
        prefix = f"{self.name} " if self.name else ""
        return prefix + " ".join(parts)

    def log(self) -> None:
        logger.info(self.summary())

    def publish(self, registry=None) -> None:
        """Publish accumulated phase times into a ``MetricsRegistry``
        (the global one by default) as
        ``keystone_phase_seconds_total{timer=..., phase=...}`` — how
        solver/profiler phase logs become scrapeable instead of
        stdout-only. Publishes only the delta since the last publish,
        so periodic calls from a long fit never double-count."""
        from keystone_tpu.observability.registry import get_global_registry

        reg = registry if registry is not None else get_global_registry()
        counter = reg.counter(
            "keystone_phase_seconds_total",
            "accumulated wall seconds per named phase",
            labelnames=("timer", "phase"),
        )
        for phase_name, seconds in self.times.items():
            delta = seconds - self._published.get(phase_name, 0.0)
            if delta > 0:
                counter.inc((self.name or "phase_timer", phase_name), delta)
                self._published[phase_name] = seconds


def _interp_percentile(data, p: float) -> Optional[float]:
    """Linear-interpolated percentile of ascending ``data`` (p in
    [0, 100]); the ONE implementation ``percentile()`` and
    ``snapshot()`` share so exporters can never disagree."""
    if not data:
        return None
    rank = (p / 100.0) * (len(data) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(data) - 1)
    frac = rank - lo
    return data[lo] * (1.0 - frac) + data[hi] * frac


class LatencyRecorder:
    """Thread-safe latency reservoir with percentile queries.

    Serving code records one sample per dispatch/request; the reservoir
    keeps the most recent ``window`` samples (steady-state behaviour,
    not startup transients) while count/total accumulate forever so
    rates stay exact. Percentiles sort a bounded copy — cheap at the
    default window, and never taken on the dispatch hot path.
    """

    def __init__(self, window: int = 4096):
        self._samples: Deque[float] = collections.deque(maxlen=window)
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0

    def record(self, seconds: float) -> None:
        with self._lock:
            self._samples.append(seconds)
            self.count += 1
            self.total += seconds

    def percentile(self, p: float) -> Optional[float]:
        """p in [0, 100]; None until a sample exists."""
        with self._lock:
            data = sorted(self._samples)
        return _interp_percentile(data, p)

    @property
    def p50(self) -> Optional[float]:
        return self.percentile(50.0)

    @property
    def p95(self) -> Optional[float]:
        return self.percentile(95.0)

    @property
    def p99(self) -> Optional[float]:
        return self.percentile(99.0)

    @property
    def mean(self) -> Optional[float]:
        with self._lock:
            return self.total / self.count if self.count else None

    def snapshot(self) -> Dict[str, Optional[float]]:
        """count/total/p50/p95/p99 under ONE lock acquisition — a
        mutually consistent view (separate property reads can straddle
        concurrent records; exporters and ``ServingMetrics.summary()``
        use this)."""
        with self._lock:
            count = self.count
            total = self.total
            data = sorted(self._samples)
        return {
            "count": count,
            "total": total,
            "p50": _interp_percentile(data, 50.0),
            "p95": _interp_percentile(data, 95.0),
            "p99": _interp_percentile(data, 99.0),
        }


class Counter:
    """Thread-safe monotonically increasing counter with labeled cells
    (e.g. one cell per bucket size)."""

    def __init__(self):
        self._cells: Dict = collections.defaultdict(int)
        self._lock = threading.Lock()

    def inc(self, label=None, by: int = 1) -> None:
        with self._lock:
            self._cells[label] += by

    def get(self, label=None) -> int:
        with self._lock:
            return self._cells.get(label, 0)

    @property
    def total(self) -> int:
        with self._lock:
            return sum(self._cells.values())

    def snapshot(self) -> Dict:
        with self._lock:
            return dict(self._cells)


def instrument_executor(executor) -> Dict:
    """Record per-node wall time on a GraphExecutor via its ``node_hook``
    (workflow/executor.py) — no monkey-patching; the hook also powers
    ``/tracez`` node spans. Returns the (live) dict of node -> seconds,
    accumulated as nodes execute."""
    times: Dict = {}

    def hook(graph_id, label, seconds):
        times[graph_id] = times.get(graph_id, 0.0) + seconds

    executor.node_hook = hook
    return times
