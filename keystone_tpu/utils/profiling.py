"""Tracing / profiling utilities.

Reference (SURVEY.md §5): the reference's tracing is (1) the AutoCacheRule
sample profiler (workflow/auto_cache.py here), (2) ad-hoc per-phase timing
logs (e.g. KernelRidgeRegression.scala:213-221), and (3) Graphviz DOT
export of the DAG logged on every optimizer rule application.

TPU equivalents here:
- ``trace(dir)``: context manager around the JAX profiler — produces
  XPlane traces viewable in TensorBoard/XProf (the substrate-level trace
  the reference lacked).
- ``PhaseTimer``: the per-phase wall-clock logger.
- ``instrument_executor``: monkey-patches a GraphExecutor to record
  per-node execution wall time (the interpret-layer profile).
- DOT export lives on the Graph itself (``Graph.to_dot``), same as the
  reference's toDOTString.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Dict, Iterator, Optional

import jax

logger = logging.getLogger(__name__)


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """JAX profiler trace (XPlane) around a block of pipeline work."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class PhaseTimer:
    """Accumulates named phase wall-clock times (reference: the
    kernelGen/residual/collect/localSolve/modelUpdate logs in KRR)."""

    def __init__(self, name: str = ""):
        self.name = name
        self.times: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, phase_name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.times[phase_name] = self.times.get(phase_name, 0.0) + dt

    def summary(self) -> str:
        parts = [f"{k}: {v:.3f}s" for k, v in self.times.items()]
        prefix = f"{self.name} " if self.name else ""
        return prefix + " ".join(parts)

    def log(self) -> None:
        logger.info(self.summary())


def instrument_executor(executor) -> Dict:
    """Wraps a GraphExecutor's execute() to record per-node wall time.
    Returns the (live) dict of node -> seconds."""
    times: Dict = {}
    original = executor.execute

    def timed_execute(graph_id):
        t0 = time.perf_counter()
        out = original(graph_id)
        times[graph_id] = times.get(graph_id, 0.0) + (
            time.perf_counter() - t0
        )
        return out

    executor.execute = timed_execute
    return times
