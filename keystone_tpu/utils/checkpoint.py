"""Loop-state checkpointing for long-running block solvers.

Reference: KernelRidgeRegression.scala:200-210 checkpoints the model RDDs'
lineage every 25 column blocks so a Spark executor failure doesn't replay
the whole Gauss-Seidel history. There is no lineage on TPU; the equivalent
recovery story is a periodic atomic host snapshot of the *compact* loop
state (the block models — large intermediates like the residual are
recomputed from them on resume, which is exactly what lineage truncation
buys Spark), which a re-run picks up after preemption — the common failure
mode on Cloud TPU.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional

import numpy as np


class LoopCheckpointer:
    """Cadenced atomic ``.npz`` snapshots of a solver loop's state.

    ``tick(state_fn)`` is called once per completed step; every ``every``
    steps it materializes ``state_fn()`` (a dict of arrays/scalars) and
    writes it atomically (tmp file + ``os.replace``), so a crash mid-write
    never corrupts the last good snapshot.

    ``fingerprint`` (solver config + data shape digest) is stamped into
    every snapshot; ``load`` discards a snapshot whose stamp differs — a
    re-run with a changed hyperparameter, block layout, or dataset must
    start fresh, not silently mix stale partial state into a new fit.
    """

    FP_KEY = "__fingerprint__"

    def __init__(self, path: str, every: int = 25,
                 fingerprint: Optional[str] = None):
        if every < 1:
            raise ValueError(f"checkpoint cadence must be >= 1, got {every}")
        self.path = path
        self.every = every
        self.fingerprint = fingerprint
        self._count = 0

    def tick(self, state_fn: Callable[[], Dict[str, np.ndarray]]) -> bool:
        self._count += 1
        if self._count % self.every == 0:
            self.save(state_fn())
            return True
        return False

    def save(self, state: Dict[str, np.ndarray]) -> None:
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        tmp = self.path + ".tmp"
        out = {k: np.asarray(v) for k, v in state.items()}
        if self.fingerprint is not None:
            out[self.FP_KEY] = np.frombuffer(
                self.fingerprint.encode(), np.uint8
            )
        with open(tmp, "wb") as f:
            np.savez(f, **out)
        os.replace(tmp, self.path)

    def load(self) -> Optional[Dict[str, np.ndarray]]:
        if not os.path.exists(self.path):
            return None
        try:
            with np.load(self.path, allow_pickle=False) as z:
                state = {k: z[k] for k in z.files}
        except Exception as e:  # torn write on non-atomic mounts, or a
            # pre-existing non-npz file: recovery must not crash recovery
            import logging

            logging.getLogger(__name__).warning(
                "checkpoint %s is unreadable (%s); starting fresh",
                self.path, e,
            )
            return None
        saved_fp = state.pop(self.FP_KEY, None)
        if self.fingerprint is not None:
            got = (
                bytes(saved_fp).decode() if saved_fp is not None else None
            )
            if got != self.fingerprint:
                import logging

                logging.getLogger(__name__).warning(
                    "checkpoint %s was written by a different solver "
                    "config/dataset (stamp %r != %r); starting fresh",
                    self.path, got, self.fingerprint,
                )
                return None
        return state

    def clear(self) -> None:
        for p in (self.path, self.path + ".tmp"):
            if os.path.exists(p):
                os.remove(p)


def two_level_schedule(n_outer: int, n_inner: int, start=(0, 0)):
    """Iterate a resumable (sweep, block) double loop from ``start``,
    yielding ``(outer, inner, next_start)`` — ``next_start`` is the state
    to stamp into a snapshot taken after this step completes (wraps to
    ``(outer + 1, 0)`` at the end of a sweep). Shared by every
    checkpointable block solver so the wraparound/resume-offset idioms
    live in exactly one place."""
    so, sp = start
    for outer in range(so, n_outer):
        for inner in range(sp if outer == so else 0, n_inner):
            nxt = (outer, inner + 1) if inner + 1 < n_inner \
                else (outer + 1, 0)
            yield outer, inner, nxt
