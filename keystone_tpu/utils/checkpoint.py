"""Loop-state checkpointing for long-running block solvers.

Reference: KernelRidgeRegression.scala:200-210 checkpoints the model RDDs'
lineage every 25 column blocks so a Spark executor failure doesn't replay
the whole Gauss-Seidel history. There is no lineage on TPU; the equivalent
recovery story is a periodic atomic host snapshot of the *compact* loop
state (the block models — large intermediates like the residual are
recomputed from them on resume, which is exactly what lineage truncation
buys Spark), which a re-run picks up after preemption — the common failure
mode on Cloud TPU.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional

import numpy as np


class LoopCheckpointer:
    """Cadenced atomic ``.npz`` snapshots of a solver loop's state.

    ``tick(state_fn)`` is called once per completed step; every ``every``
    steps it materializes ``state_fn()`` (a dict of arrays/scalars) and
    writes it atomically (tmp file + ``os.replace``), so a crash mid-write
    never corrupts the last good snapshot.

    ``fingerprint`` (solver config + data shape digest) is stamped into
    every snapshot; ``load`` discards a snapshot whose stamp differs — a
    re-run with a changed hyperparameter, block layout, or dataset must
    start fresh, not silently mix stale partial state into a new fit.
    """

    FP_KEY = "__fingerprint__"

    def __init__(self, path: str, every: int = 25,
                 fingerprint: Optional[str] = None):
        if every < 1:
            raise ValueError(f"checkpoint cadence must be >= 1, got {every}")
        self.path = path
        self.every = every
        self.fingerprint = fingerprint
        self._count = 0

    def tick(self, state_fn: Callable[[], Dict[str, np.ndarray]]) -> bool:
        self._count += 1
        if self._count % self.every == 0:
            self.save(state_fn())
            return True
        return False

    def save(self, state: Dict[str, np.ndarray]) -> None:
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        tmp = self.path + ".tmp"
        out = {k: np.asarray(v) for k, v in state.items()}
        if self.fingerprint is not None:
            out[self.FP_KEY] = np.frombuffer(
                self.fingerprint.encode(), np.uint8
            )
        with open(tmp, "wb") as f:
            np.savez(f, **out)
        os.replace(tmp, self.path)

    def load(self) -> Optional[Dict[str, np.ndarray]]:
        if not os.path.exists(self.path):
            return None
        try:
            with np.load(self.path, allow_pickle=False) as z:
                state = {k: z[k] for k in z.files}
        except Exception as e:  # torn write on non-atomic mounts, or a
            # pre-existing non-npz file: recovery must not crash recovery
            import logging

            logging.getLogger(__name__).warning(
                "checkpoint %s is unreadable (%s); starting fresh",
                self.path, e,
            )
            return None
        saved_fp = state.pop(self.FP_KEY, None)
        if self.fingerprint is not None:
            got = (
                bytes(saved_fp).decode() if saved_fp is not None else None
            )
            if got != self.fingerprint:
                import logging

                logging.getLogger(__name__).warning(
                    "checkpoint %s was written by a different solver "
                    "config/dataset (stamp %r != %r); starting fresh",
                    self.path, got, self.fingerprint,
                )
                return None
        return state

    def clear(self) -> None:
        for p in (self.path, self.path + ".tmp"):
            if os.path.exists(p):
                os.remove(p)


def data_probe(X, Y) -> str:
    """Cheap dataset digest for checkpoint fingerprints: full sums plus a
    few strided row sums of each operand, so a re-run on data that shares
    row 0 but differs elsewhere (re-labeled targets, shuffled tail, ...)
    invalidates the snapshot instead of silently resuming from it.

    One jitted program, f32 accumulation via ``jnp.sum(..., dtype=...)``
    (no materialized f32 copy of a possibly HBM-scale bf16 X), one host
    transfer per operand."""
    a, b = _probe_digest(X, Y)
    fmt = lambda v: ",".join(f"{p:.6e}" for p in np.asarray(v))
    return f"{fmt(a)}|{fmt(b)}"


def _probe_one(A):
    import jax.numpy as jnp

    n = A.shape[0]
    rows = [0, n // 3, (2 * n) // 3, n - 1]
    # Row-index-weighted contraction makes the digest order-SENSITIVE
    # (plain sums are permutation-invariant, and sampled rows can all
    # land outside a reordered span); einsum contracts without
    # materializing a weighted copy of a possibly HBM-scale A.
    w = (jnp.arange(n, dtype=jnp.float32) % 97.0) + 1.0
    sub = "nd,n->" if A.ndim == 2 else "n,n->"
    wsum = jnp.einsum(sub, A, w, preferred_element_type=jnp.float32)
    return jnp.stack(
        [jnp.sum(A, dtype=jnp.float32), wsum]
        + [jnp.sum(A[r], dtype=jnp.float32) for r in rows]
    )


_PROBE_JIT = None  # module-level jit: one compile cache for the process


def _probe_digest(X, Y):
    global _PROBE_JIT
    if _PROBE_JIT is None:
        import jax

        _PROBE_JIT = jax.jit(lambda X, Y: (_probe_one(X), _probe_one(Y)))
    return _PROBE_JIT(X, Y)


def two_level_schedule(n_outer: int, n_inner: int, start=(0, 0)):
    """Iterate a resumable (sweep, block) double loop from ``start``,
    yielding ``(outer, inner, next_start)`` — ``next_start`` is the state
    to stamp into a snapshot taken after this step completes (wraps to
    ``(outer + 1, 0)`` at the end of a sweep). Shared by every
    checkpointable block solver so the wraparound/resume-offset idioms
    live in exactly one place."""
    so, sp = start
    for outer in range(so, n_outer):
        for inner in range(sp if outer == so else 0, n_inner):
            nxt = (outer, inner + 1) if inner + 1 < n_inner \
                else (outer + 1, 0)
            yield outer, inner, nxt
