"""Shared f32 matmul precision policy (see README).

TPU's DEFAULT matmul precision truncates f32 operands to bf16 passes
(~1e-3 relative error). Solver math and model application request
HIGHEST for f32 inputs; bf16 inputs keep the native one-pass MXU path —
users choose speed by passing bf16 data, not by losing f32 semantics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def hi_if_f32(*arrays):
    """``precision=`` value: HIGHEST when any operand is f32."""
    return (
        jax.lax.Precision.HIGHEST
        if any(a.dtype == jnp.float32 for a in arrays)
        else None
    )


def mm(a, b):
    """a @ b under the precision policy, preserving input dtype
    semantics: any f32 operand triggers HIGHEST precision with f32
    output; when BOTH operands are bf16 (data AND model params), the
    native MXU path runs and the result stays bf16 — so keeping a whole
    pipeline in bf16 requires bf16 weights too, not just bf16 data.
    (Solver internals that need f32 accumulation from bf16 inputs use
    ``ops.learning.block_ls._f32_mm`` instead — the two helpers differ
    only in that output contract.)"""
    hp = hi_if_f32(a, b)
    if hp is None:
        return jnp.matmul(a, b)
    return jnp.matmul(
        a, b, precision=hp, preferred_element_type=jnp.float32
    )
