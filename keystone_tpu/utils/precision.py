"""Shared f32 matmul precision policy (see README).

TPU's DEFAULT matmul precision truncates f32 operands to bf16 passes
(~1e-3 relative error). Solver math and model application request
HIGHEST for f32 inputs; bf16 inputs keep the native one-pass MXU path —
users choose speed by passing bf16 data, not by losing f32 semantics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def hi_if_f32(*arrays):
    """``precision=`` value: HIGHEST when any operand is f32."""
    return (
        jax.lax.Precision.HIGHEST
        if any(a.dtype == jnp.float32 for a in arrays)
        else None
    )


def mm(a, b):
    """a @ b with f32 accumulation under the precision policy."""
    return jnp.matmul(
        a, b, precision=hi_if_f32(a, b),
        preferred_element_type=jnp.float32,
    )
