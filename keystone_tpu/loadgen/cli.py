"""``python -m keystone_tpu serve-loadgen`` — the experiment driver.

Replays a workload (recorded ``--trace`` JSONL or ``--synthetic``)
open-loop against a gateway (``--target URL``, or ``--self-gateway``
to stand one up in-process over the bench pipeline), optionally arms
a chaos timeline mid-run (``--fault``, armed over ``POST /chaosz``
for HTTP targets so the fault fires in the SERVER process), runs the
invariant checker over the result, prints the structured verdict, and
exits nonzero when the verdict is red.

``--target`` takes a fleet ROUTER's URL just as well as a single
gateway's: the router serves the same ``/predict`` / ``/readyz`` /
``/chaosz`` surface, so cross-host drills (kill a replica process
mid-load, black-hole one replica's responses via
``router.replica.blackhole``) run through the identical harness —
``bin/smoke-fleet.sh`` is exactly that.

Examples::

    # replay a recorded trace at 4x against a live gateway
    python -m keystone_tpu serve-loadgen --target http://127.0.0.1:8000 \\
        --trace requests.jsonl --speed 4

    # synthetic heavy-tail load with a lane killed mid-run, verdict
    # must be green
    python -m keystone_tpu serve-loadgen --target http://127.0.0.1:8000 \\
        --synthetic 400 --arrivals lognormal --rate 80 \\
        --fault 'gateway.lane.kill=lane:0' --fault-at 1.5 --fault-for 1.5

    # no server handy: drive an in-process gateway
    python -m keystone_tpu serve-loadgen --self-gateway --synthetic 200
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from keystone_tpu.loadgen import faults as faults_mod
from keystone_tpu.loadgen import trace as trace_mod
from keystone_tpu.loadgen.invariants import (
    InvariantChecker,
    InvariantResult,
)
from keystone_tpu.loadgen.runner import (
    FaultPlan,
    FeedbackSender,
    HttpTarget,
    InprocTarget,
    LoadGenerator,
)


def _parse_teacher(spec: str) -> dict:
    """``hidden=H,depth=N[,seed=S][,head_seed=S2]`` -> kwargs for
    ``lifecycle/teacher.teacher_labels`` (all integers)."""
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, value = part.partition("=")
        key = key.strip()
        if key not in ("hidden", "depth", "seed", "head_seed"):
            raise SystemExit(
                f"--teacher: unknown key {key!r} (want hidden/depth/"
                "seed/head_seed)"
            )
        try:
            out[key] = int(value)
        except ValueError:
            raise SystemExit(f"--teacher: {key} wants an integer")
    if "hidden" not in out or "depth" not in out:
        raise SystemExit("--teacher needs at least hidden=H,depth=N")
    return out


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="keystone_tpu serve-loadgen",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    tgt = ap.add_argument_group("target")
    tgt.add_argument("--target", default=None, metavar="URL",
                     help="base URL of a running gateway frontend")
    tgt.add_argument("--self-gateway", action="store_true",
                     help="stand up an in-process gateway over the "
                     "bench pipeline instead of --target")
    tgt.add_argument("--d", type=int, default=64,
                     help="feature dim of the --self-gateway pipeline "
                     "(and the default replay example shape)")
    tgt.add_argument("--lanes", type=int, default=2)
    tgt.add_argument("--buckets", default="4,16")

    wl = ap.add_argument_group("workload")
    wl.add_argument("--trace", default=None, metavar="FILE",
                    help="replay this --request-log JSONL recording")
    wl.add_argument("--no-collapse", action="store_true",
                    help="replay one request per recorded line instead "
                    "of collapsing per-instance lines back into their "
                    "originating POSTs")
    wl.add_argument("--synthetic", type=int, default=None, metavar="N",
                    help="synthesize N requests instead of --trace")
    wl.add_argument("--ramp", default=None, metavar="RATE:DUR,...",
                    help="synthesize a STEP/RAMP offered-load shape "
                    "instead of --trace/--synthetic: comma-separated "
                    "rate:duration_s steps (e.g. '5:4,40:8,5:6' = 4s "
                    "at 5 rps, an 8s surge at 40 rps, 6s back at 5) "
                    "— the deterministic load staircase the "
                    "autoscale/capacity drills use; --arrivals names "
                    "the within-step process")
    wl.add_argument("--arrivals", default="poisson",
                    choices=trace_mod.ARRIVALS)
    wl.add_argument("--rate", type=float, default=100.0,
                    help="mean arrival rate, requests/sec")
    wl.add_argument("--sigma", type=float, default=1.0,
                    help="lognormal arrival shape")
    wl.add_argument("--alpha", type=float, default=1.5,
                    help="pareto arrival tail index (> 1)")
    wl.add_argument("--size-mix", default="1:1.0", metavar="R:W,...",
                    help="instance-count mixture, e.g. 1:0.8,4:0.2 — "
                    "replaying a SHIFTED mixture against a planned "
                    "--zoo gateway is the drift-detector drill: "
                    "keystone_drift_score rises and /driftz ships a "
                    "re-plan recommendation")
    wl.add_argument("--deadline-ms", type=float, default=None)
    wl.add_argument("--deadline-sigma", type=float, default=0.0,
                    help="lognormal jitter on --deadline-ms")
    wl.add_argument("--seed", type=int, default=0)
    wl.add_argument("--speed", type=float, default=1.0,
                    help="replay speed factor (2 = twice as fast)")
    wl.add_argument("--settle-s", type=float, default=0.0,
                    help="keep the run open this long past the last "
                    "arrival (lets post-fault recovery be measured)")
    wl.add_argument("--max-outstanding", type=int, default=128)

    fb = ap.add_argument_group("lifecycle feedback")
    fb.add_argument("--feedback-fraction", type=float, default=0.0,
                    metavar="F",
                    help="also label this deterministic fraction of "
                    "issued payloads with the --teacher model and "
                    "POST them to the gateway's /feedback (the "
                    "online-lifecycle label stream; off the load "
                    "path, bounded queue, drop-newest). Needs "
                    "--target and --teacher")
    fb.add_argument("--teacher", default=None,
                    metavar="hidden=H,depth=N[,seed=S][,head_seed=S2]",
                    help="synthetic ground truth for --feedback-"
                    "fraction: lifecycle/teacher.teacher_labels over "
                    "the --d input shape — the demo pipeline's exact "
                    "forward math; head_seed redraws the final layer "
                    "so the served model is a STALE teacher the "
                    "streaming refit must catch up to")

    ch = ap.add_argument_group("chaos")
    ch.add_argument("--fault", action="append", default=[],
                    metavar="POINT[=k:v,...]",
                    help="arm this fault point mid-run (same grammar "
                    "as KEYSTONE_FAULTS; repeatable, paired "
                    "positionally with --fault-at/--fault-for)")
    ch.add_argument("--fault-at", action="append", type=float,
                    default=[], metavar="T",
                    help="seconds into the run to arm the matching "
                    "--fault (default 0)")
    ch.add_argument("--fault-for", action="append", type=float,
                    default=[], metavar="S",
                    help="clear the matching --fault after S seconds "
                    "(default: stays armed until the run ends)")

    inv = ap.add_argument_group("invariants")
    inv.add_argument("--p99-factor", type=float, default=1.5,
                     help="post-fault p99 must recover to within this "
                     "factor of the pre-fault p99")
    inv.add_argument("--recovery-s", type=float, default=10.0,
                     help="seconds after the fault clears within which "
                     "p99 (and readiness) must recover")
    inv.add_argument("--max-shed-rate", type=float, default=None)
    inv.add_argument("--max-p99-ms", type=float, default=None)

    out = ap.add_argument_group("output")
    out.add_argument("--report", default=None, metavar="FILE",
                     help="also write the JSON verdict here")
    out.add_argument("--no-verdict", action="store_true",
                     help="replay only; skip invariant checking (exit "
                     "0 regardless)")
    return ap


def build_workload(args) -> List[trace_mod.TraceEvent]:
    """One workload builder for every replaying CLI (``serve-loadgen``
    AND ``serve-capacity-plan``): exactly one of ``--trace FILE``,
    ``--synthetic N``, or ``--ramp RATE:DUR,...`` becomes the event
    list. Reads optional shaping flags (``sigma``/``alpha``/
    ``deadline_sigma``/``no_collapse``) off the namespace when the
    caller's parser defines them, library defaults otherwise — so the
    two CLIs can't drift apart on what a workload spec means."""
    trace = getattr(args, "trace", None)
    synthetic = getattr(args, "synthetic", None)
    ramp = getattr(args, "ramp", None)
    chosen = sum(x is not None for x in (trace, synthetic, ramp))
    if chosen != 1:
        raise SystemExit(
            "pass exactly one of --trace FILE, --synthetic N, or "
            "--ramp RATE:DUR,..."
        )
    if trace is not None:
        events = trace_mod.load_trace(
            trace, collapse=not getattr(args, "no_collapse", False)
        )
        if not events:
            raise SystemExit(
                f"--trace {trace}: no replayable records found"
            )
        return events
    shaping = dict(
        arrivals=args.arrivals,
        sigma=getattr(args, "sigma", 1.0),
        alpha=getattr(args, "alpha", 1.5),
        size_mix=trace_mod.parse_size_mix(args.size_mix),
        shape=(args.d,),
        deadline_ms=args.deadline_ms,
        deadline_sigma=getattr(args, "deadline_sigma", 0.0),
        seed=args.seed,
    )
    if ramp is not None:
        return trace_mod.synthesize_steps(
            trace_mod.parse_steps(ramp), **shaping
        )
    return trace_mod.synthesize(synthetic, rate=args.rate, **shaping)


# the historical private name (serve-loadgen's own entry point)
_build_events = build_workload


def _build_fault_plans(args) -> List[FaultPlan]:
    plans = []
    for i, clause in enumerate(args.fault):
        spec = faults_mod.parse_fault_spec(clause)
        at = args.fault_at[i] if i < len(args.fault_at) else 0.0
        dur = args.fault_for[i] if i < len(args.fault_for) else None
        plans.append(FaultPlan(spec=spec, at_s=at, for_s=dur))
    return plans


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    events = _build_events(args)
    print(
        json.dumps({"workload": trace_mod.summarize(events)}),
        flush=True,
    )

    gateway = None
    if args.self_gateway:
        import jax.numpy as jnp

        from keystone_tpu.gateway import Gateway
        from keystone_tpu.serving.bench import build_pipeline

        fitted = build_pipeline(d=args.d, hidden=args.d, depth=2)
        gateway = Gateway(
            fitted,
            buckets=tuple(int(b) for b in args.buckets.split(",")),
            n_lanes=args.lanes,
            warmup_example=jnp.zeros((args.d,), jnp.float32),
            name="loadgen",
        )
        target = InprocTarget(gateway, default_shape=(args.d,))
    elif args.target:
        target = HttpTarget(args.target, default_shape=(args.d,))
    else:
        raise SystemExit("pass --target URL or --self-gateway")
    feedback = None
    if args.feedback_fraction > 0.0:
        if not args.target:
            raise SystemExit(
                "--feedback-fraction needs --target URL (the "
                "/feedback route lives on the HTTP frontend)"
            )
        if not args.teacher:
            raise SystemExit(
                "--feedback-fraction needs --teacher "
                "hidden=H,depth=N[,seed=S][,head_seed=S2]"
            )
        from keystone_tpu.lifecycle.teacher import teacher_labels

        teacher_kw = _parse_teacher(args.teacher)
        d = args.d
        feedback = FeedbackSender(
            args.target,
            lambda xs: teacher_labels(xs, d, **teacher_kw),
            fraction=args.feedback_fraction,
        )
        target.feedback = feedback
    # env-armed faults (KEYSTONE_FAULTS) arm AFTER the gateway exists:
    # trigger points disarm instantly when nothing has registered for
    # them, so arming earlier would silently no-op gateway.swap.force
    faults_mod.arm_from_env()

    plans = _build_fault_plans(args)
    settle = args.settle_s
    if plans and settle == 0.0:
        # recovery can only be asserted on traffic that ARRIVES after
        # the fault clears; warn rather than silently under-measure
        print(
            json.dumps({
                "note": "faults armed with --settle-s 0; if the trace "
                "ends before the fault clears, recovery has no "
                "traffic to measure"
            }),
            flush=True,
        )
    gen = LoadGenerator(target, max_outstanding=args.max_outstanding)
    # snapshot lifetime fire counts so a green verdict can never mean
    # "the fault silently failed to arm/fire and nothing was tested"
    fault_points = sorted({p.spec["point"] for p in plans})
    fired_before = {p: target.fired_count(p) for p in fault_points}
    try:
        report = gen.run(
            events,
            speed=args.speed,
            faults=plans,
            recovery_probe_s=args.recovery_s,
            settle_s=settle,
        )
        fired_after = {p: target.fired_count(p) for p in fault_points}
    finally:
        if feedback is not None:
            # flush BEFORE any verdict: the lifecycle drill's asserts
            # read these counts off this one JSON line
            print(
                json.dumps({"feedback": feedback.close()}), flush=True
            )
        if gateway is not None:
            gateway.close()

    if args.no_verdict:
        print(json.dumps({"stats": report.stats()}, indent=1))
        return 0
    checker = InvariantChecker(
        p99_factor=args.p99_factor,
        recovery_within_s=args.recovery_s,
        max_shed_rate=args.max_shed_rate,
        max_p99_s=(
            args.max_p99_ms / 1e3 if args.max_p99_ms is not None else None
        ),
    )
    verdict = checker.check(report)
    for point in fault_points:
        before, after = fired_before[point], fired_after[point]
        fired = (
            None if before is None or after is None else after - before
        )
        ok = fired is None or fired > 0
        verdict.invariants.append(InvariantResult(
            "requested_fault_actually_fired", ok,
            f"{point}: "
            + (f"{fired} injection(s)" if fired is not None
               else "fire count unavailable (taken on trust)"),
        ))
        if not ok:
            # an unfired fault means the run proved nothing — red
            verdict.passed = False
        verdict.stats.setdefault("injections", {})[point] = fired
    doc = verdict.to_json(indent=1)
    print(doc, flush=True)
    if not verdict.passed and args.target:
        # a red verdict names its exemplar requests; print each known
        # trace id as a ready-to-curl /debugz URL — against a fleet
        # router that is the STITCHED cross-process tree with the
        # phase decomposition, against a lone gateway the flight
        # record / live span tree
        _print_forensic_urls(
            args.target, verdict.stats.get("exemplars") or {}
        )
    if args.report:
        with open(args.report, "w", encoding="utf-8") as f:
            f.write(doc + "\n")
    return 0 if verdict.passed else 1


def _print_forensic_urls(base_url: str, exemplars: dict) -> None:
    base = base_url.rstrip("/")
    entries = []
    worst = exemplars.get("worst_latency")
    if worst is not None:
        entries.append(("worst-latency", worst))
    entries.extend(("lost", e) for e in exemplars.get("lost", ()))
    entries.extend(("untyped", e) for e in exemplars.get("untyped", ()))
    seen = set()
    for kind, e in entries:
        tid = e.get("trace_id")
        label = f"{kind} (request #{e.get('index')})"
        if not tid:
            print(
                f"forensics: {label}: no trace id "
                "(no response reached the client)",
                flush=True,
            )
            continue
        if tid in seen:
            continue
        seen.add(tid)
        print(
            f"forensics: {label}: "
            f"curl '{base}/debugz?trace_id={tid}'",
            flush=True,
        )


if __name__ == "__main__":
    sys.exit(main())
