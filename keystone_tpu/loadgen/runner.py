"""Open-loop load generation: replay a trace against a live gateway.

MLPerf-LoadGen-style discipline (Reddi et al., *MLPerf Inference
Benchmark*): requests are issued on the GENERATOR's clock — the
recorded/synthesized inter-arrival gaps scaled by ``speed`` — never
paced by responses. A slow or melting server does not slow the
arrival process down; it accumulates outstanding requests until the
gateway's admission control sheds, which is exactly the regime the
chaos invariants are about. (A closed-loop driver would politely wait
and measure nothing but itself.)

Two targets behind one interface:

- ``HttpTarget`` — POSTs ``/predict`` to a running ``GatewayServer``;
  typed shed/expired/closed responses (429/504/503 with an
  ``overloaded`` body) classify as typed sheds, anything else
  non-2xx is an UNTYPED failure (the invariant checker's cardinal
  sin), and a transport timeout is a LOST request (an admitted future
  that never resolved — the other cardinal sin).
- ``InprocTarget`` — drives a ``Gateway`` object directly
  (``predict().result()``), same classification; this is what the
  bench rows use so ``serving_chaos_*`` needs no socket.

The ``LoadReport`` collects one ``RequestRecord`` per issued request
plus the chaos timeline (``FaultWindow``s the driver armed) and the
readiness-recovery probe result; ``loadgen/invariants.py`` turns it
into a verdict."""

from __future__ import annotations

import dataclasses
import itertools
import json
import logging
import queue
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from keystone_tpu.loadgen.trace import TraceEvent
from keystone_tpu.observability.tracing import TRACE_RESPONSE_HEADER

logger = logging.getLogger(__name__)

# statuses a record can end in; "lost" = no terminal outcome within
# the wait bound — the invariant checker fails the run on any of them
TYPED_SHED_REASONS = (
    "queue_full", "slo_pressure", "deadline", "expired", "closed",
)

# wait past the request's own deadline before a request is declared
# lost (generous: a lost future should be the server's bug, never the
# client's impatience)
LOST_SLACK_S = 30.0

# the gateway's server-side ceiling for waiting on one prediction
# (gateway/http.py RESULT_TIMEOUT_S): the HTTP client's lost-bound
# must EXCEED it, or a request the server eventually resolves with a
# typed answer gets misclassified as lost
SERVER_RESULT_BOUND_S = 60.0


@dataclasses.dataclass
class RequestRecord:
    """One issued request's terminal outcome."""

    index: int
    t_send: float                 # seconds from run start (actual)
    t_sched: float                # seconds from run start (scheduled)
    status: str                   # ok | shed | error | lost
    n_rows: int = 1
    latency_s: Optional[float] = None
    code: Optional[int] = None    # HTTP status (http target only)
    reason: Optional[str] = None  # typed shed reason / error detail
    untyped: bool = False         # True for non-typed failures
    # the server's X-Keystone-Trace echo (success AND typed shed):
    # the record's handle into /debugz?trace_id= forensics — what the
    # verdict's exemplars surface for the worst/lost/untyped requests
    trace_id: Optional[str] = None

    @property
    def behind_s(self) -> float:
        """How late the open-loop scheduler issued this request."""
        return self.t_send - self.t_sched


@dataclasses.dataclass
class FaultWindow:
    """One chaos interval the driver armed (run-relative seconds)."""

    point: str
    t_arm: float
    t_clear: Optional[float] = None
    spec: Optional[Dict[str, Any]] = None


class LoadReport:
    """Everything one experiment produced: per-request records, the
    chaos timeline, and the post-fault readiness probe."""

    def __init__(self):
        self.records: List[RequestRecord] = []
        self.fault_windows: List[FaultWindow] = []
        self.duration_s: float = 0.0
        self.issued: int = 0
        # seconds from the LAST fault clearing to /readyz green again;
        # None = never recovered within the probe bound (or no probe)
        self.ready_recovery_s: Optional[float] = None
        self.ready_probed: bool = False
        self._lock = threading.Lock()

    def add(self, rec: RequestRecord) -> None:
        with self._lock:
            self.records.append(rec)

    def by_status(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for r in self.records:
            counts[r.status] = counts.get(r.status, 0) + 1
        return counts

    def latencies(
        self,
        t_min: float = 0.0,
        t_max: float = float("inf"),
        status: str = "ok",
    ) -> List[float]:
        """Latencies of ``status`` requests SENT in [t_min, t_max)."""
        return [
            r.latency_s
            for r in self.records
            if r.status == status
            and r.latency_s is not None
            and t_min <= r.t_send < t_max
        ]

    def p99(
        self, t_min: float = 0.0, t_max: float = float("inf")
    ) -> Optional[float]:
        xs = self.latencies(t_min, t_max)
        if not xs:
            return None
        return float(np.percentile(xs, 99))

    def stats(self) -> Dict[str, Any]:
        by = self.by_status()
        total = len(self.records)
        shed = by.get("shed", 0)
        return {
            "issued": self.issued,
            "resolved": total,
            "by_status": by,
            "untyped_failures": sum(1 for r in self.records if r.untyped),
            "lost": by.get("lost", 0),
            "shed_rate": round(shed / total, 4) if total else None,
            "duration_s": round(self.duration_s, 3),
            "max_behind_ms": round(
                max((r.behind_s for r in self.records), default=0.0)
                * 1e3, 2,
            ),
            "fault_windows": [
                dataclasses.asdict(w) for w in self.fault_windows
            ],
            "ready_recovery_s": self.ready_recovery_s,
        }


def _payload_for(event: TraceEvent, default_shape) -> np.ndarray:
    """Deterministic request data: (n_rows, *shape) standard normal,
    seeded by the event's index-ish identity (its timestamp bits) so a
    replay issues identical bytes."""
    shape = tuple(event.shape) if event.shape else tuple(default_shape)
    seed = int(abs(event.ts) * 1e6) & 0x7FFFFFFF
    rng = np.random.default_rng(seed)
    return rng.standard_normal(
        (event.n_rows,) + shape
    ).astype(np.float32)


class FeedbackSender:
    """Labeled-feedback side channel for lifecycle drills: a sampled
    fraction of the payloads the generator POSTs also get labeled by
    a ``labeler`` (e.g. ``lifecycle/teacher.teacher_labels``) and
    POSTed to the gateway's ``/feedback`` — off the load path, on one
    background thread, with a bounded drop-newest queue so a slow
    labeler or a melting server can never backpressure the open-loop
    arrival clock. Sampling is the same deterministic integer-part
    arithmetic as the canary router: ``fraction`` of offers, evenly
    spaced, no RNG."""

    def __init__(
        self,
        base_url: str,
        labeler,
        fraction: float = 0.25,
        max_queue: int = 64,
        timeout_s: float = 30.0,
    ):
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0, 1], got {fraction}")
        self.base_url = base_url.rstrip("/")
        self._labeler = labeler
        self.fraction = float(fraction)
        self.timeout_s = float(timeout_s)
        self._q: queue.Queue = queue.Queue(maxsize=max_queue)
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._sent = 0  # guarded-by: _lock
        self._dropped = 0  # guarded-by: _lock
        self._errors = 0  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._drain,
            name="keystone-loadgen-feedback",
            daemon=True,
        )
        self._thread.start()

    def offer(self, xs: np.ndarray) -> None:
        """Maybe-enqueue one request's instances (called on the issue
        path — MUST stay O(1) and non-blocking)."""
        seq = next(self._seq)
        f = self.fraction
        if f <= 0.0 or int((seq + 1) * f) <= int(seq * f):
            return
        try:
            self._q.put_nowait(xs)
        except queue.Full:
            with self._lock:
                self._dropped += 1

    def _drain(self) -> None:
        while not (self._stop.is_set() and self._q.empty()):
            try:
                xs = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                ys = np.asarray(self._labeler(xs))
                body = json.dumps(
                    {"instances": xs.tolist(), "labels": ys.tolist()}
                ).encode("utf-8")
                req = urllib.request.Request(
                    self.base_url + "/feedback",
                    data=body,
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with urllib.request.urlopen(
                    req, timeout=self.timeout_s
                ) as resp:
                    resp.read()
                with self._lock:
                    self._sent += int(xs.shape[0])
            except Exception:
                with self._lock:
                    self._errors += 1

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "sent": self._sent,
                "dropped": self._dropped,
                "errors": self._errors,
            }

    def close(self, timeout: float = 15.0) -> Dict[str, int]:
        """Flush the queue, stop the thread, return final stats."""
        self._stop.set()
        self._thread.join(timeout)
        return self.stats()


class HttpTarget:
    """POST /predict (or /predict/<model> for events carrying a model
    id) against a live gateway frontend. ``feedback`` (a
    ``FeedbackSender``) mirrors a sampled fraction of payloads to
    POST /feedback as labeled examples — the lifecycle drill's
    traffic-correlated label stream."""

    def __init__(
        self,
        base_url: str,
        default_shape: Sequence[int] = (8,),
        feedback: Optional[FeedbackSender] = None,
    ):
        self.base_url = base_url.rstrip("/")
        self.default_shape = tuple(default_shape)
        self.feedback = feedback

    def send(self, event: TraceEvent) -> RequestRecord:
        # index/t_* are stamped by the generator; this fills the rest
        xs = _payload_for(event, self.default_shape)
        if self.feedback is not None:
            self.feedback.offer(xs)
        doc: Dict[str, Any] = {"instances": xs.tolist()}
        if event.deadline_ms is not None:
            doc["deadline_ms"] = event.deadline_ms
        body = json.dumps(doc).encode("utf-8")
        # outlast the server's own result bound plus slack: "lost"
        # must mean the SERVER never answered, not that this client
        # hung up first
        timeout = SERVER_RESULT_BOUND_S + 15.0 + (
            event.deadline_ms / 1e3 if event.deadline_ms else 0.0
        )
        path = (
            "/predict/" + event.model if event.model else "/predict"
        )
        req = urllib.request.Request(
            self.base_url + path,
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        t0 = time.perf_counter()
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                resp.read()
                latency = time.perf_counter() - t0
                return RequestRecord(
                    0, 0.0, 0.0, "ok", n_rows=event.n_rows,
                    latency_s=latency, code=resp.status,
                    trace_id=resp.headers.get(TRACE_RESPONSE_HEADER),
                )
        except urllib.error.HTTPError as e:
            latency = time.perf_counter() - t0
            try:
                err = json.loads(e.read() or b"{}")
            except ValueError:
                err = {}
            reason = err.get("reason") or err.get("error")
            typed = (
                e.code in (429, 503, 504)
                and err.get("error") == "overloaded"
                and err.get("reason") in TYPED_SHED_REASONS
            )
            return RequestRecord(
                0, 0.0, 0.0, "shed" if typed else "error",
                n_rows=event.n_rows, latency_s=latency, code=e.code,
                reason=reason, untyped=not typed,
                # typed sheds carry the trace header too — by design:
                # a shed client needs the forensic handle MOST
                trace_id=e.headers.get(TRACE_RESPONSE_HEADER),
            )
        except Exception as e:
            # transport timeout / connection drop: the request was
            # issued and never got a terminal answer — a LOST request
            return RequestRecord(
                0, 0.0, 0.0, "lost", n_rows=event.n_rows,
                reason=f"{type(e).__name__}: {e}",
            )

    def ready(self) -> bool:
        try:
            with urllib.request.urlopen(
                self.base_url + "/readyz", timeout=5
            ) as resp:
                return resp.status == 200
        except Exception:
            return False

    def arm_fault(self, spec: Dict[str, Any]) -> None:
        """Arm a fault point IN THE SERVER PROCESS via POST /chaosz."""
        self._chaosz({"arm": spec})

    def disarm_fault(self, point: str) -> None:
        self._chaosz({"disarm": point})

    def fired_count(self, point: str) -> Optional[int]:
        """Lifetime fire count of ``point`` in the server process
        (the did-the-fault-actually-fire audit); None if /chaosz is
        unreachable."""
        try:
            with urllib.request.urlopen(
                self.base_url + "/chaosz", timeout=10
            ) as resp:
                doc = json.loads(resp.read())
            return int(doc.get("fired_total", {}).get(point, 0))
        except Exception:
            return None

    def _chaosz(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        req = urllib.request.Request(
            self.base_url + "/chaosz",
            data=json.dumps(doc).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())


class InprocTarget:
    """Drive a ``Gateway`` object directly (the bench rows' path)."""

    def __init__(self, gateway, default_shape: Sequence[int] = (8,)):
        self.gateway = gateway
        self.default_shape = tuple(default_shape)

    def send(self, event: TraceEvent) -> RequestRecord:
        from concurrent.futures import TimeoutError as _FutTimeout

        from keystone_tpu.gateway.admission import Overloaded

        xs = _payload_for(event, self.default_shape)
        timeout = LOST_SLACK_S + (
            event.deadline_ms / 1e3 if event.deadline_ms else 0.0
        )
        t0 = time.perf_counter()
        futures = []
        # mirror the HTTP header capture: the admission layer rides
        # each future's trace id; the first instance's id stands for
        # the request in the verdict's exemplars
        def _tid():
            return next(
                (
                    tid
                    for tid in (
                        getattr(f, "trace_id", None) for f in futures
                    )
                    if tid
                ),
                None,
            )

        try:
            for row in xs:
                futures.append(
                    self.gateway.predict(
                        row, deadline_ms=event.deadline_ms
                    )
                )
            for f in futures:
                f.result(timeout=timeout)
        except Overloaded as e:
            for f in futures:
                f.cancel()
            return RequestRecord(
                0, 0.0, 0.0, "shed", n_rows=event.n_rows,
                latency_s=time.perf_counter() - t0, reason=e.reason,
                trace_id=_tid(),
            )
        except (_FutTimeout, TimeoutError):
            for f in futures:
                f.cancel()
            return RequestRecord(
                0, 0.0, 0.0, "lost", n_rows=event.n_rows,
                reason=f"future unresolved after {timeout:.0f}s",
                trace_id=_tid(),
            )
        except Exception as e:
            for f in futures:
                f.cancel()
            return RequestRecord(
                0, 0.0, 0.0, "error", n_rows=event.n_rows,
                latency_s=time.perf_counter() - t0,
                reason=f"{type(e).__name__}: {e}", untyped=True,
                trace_id=_tid(),
            )
        return RequestRecord(
            0, 0.0, 0.0, "ok", n_rows=event.n_rows,
            latency_s=time.perf_counter() - t0, trace_id=_tid(),
        )

    def ready(self) -> bool:
        return bool(self.gateway.ready)

    def arm_fault(self, spec: Dict[str, Any]) -> None:
        from keystone_tpu.loadgen import faults

        spec = dict(spec)
        point = spec.pop("point")
        faults.arm(point, **spec)

    def disarm_fault(self, point: str) -> None:
        from keystone_tpu.loadgen import faults

        faults.disarm(point)

    def fired_count(self, point: str) -> Optional[int]:
        from keystone_tpu.loadgen import faults

        return faults.get_injector().fired_count(point)


@dataclasses.dataclass
class FaultPlan:
    """Arm ``spec`` at ``at_s`` into the run, clear after ``for_s``.
    The spec's own ``for_s`` is set too, so the server self-disarms
    even if the driver dies mid-experiment."""

    spec: Dict[str, Any]
    at_s: float
    for_s: Optional[float] = None


class LoadGenerator:
    """Replay events open-loop against one target.

    ``max_outstanding`` bounds the in-flight worker threads — NOT a
    pacing mechanism: when the bound is hit the scheduler still holds
    the arrival clock and records how far behind it fell
    (``behind_s`` per record, ``max_behind_ms`` in the stats), so a
    saturated run is visible instead of silently closed-loop."""

    def __init__(self, target, max_outstanding: int = 128):
        self.target = target
        self.max_outstanding = max_outstanding
        self._sem = threading.Semaphore(max_outstanding)

    def run(
        self,
        events: Sequence[TraceEvent],
        *,
        speed: float = 1.0,
        faults: Sequence[FaultPlan] = (),
        recovery_probe_s: float = 10.0,
        settle_s: float = 0.0,
    ) -> LoadReport:
        """Issue every event at ``event.ts / speed`` on the run clock,
        arming/clearing the ``faults`` timeline as it passes; after
        the last response (or loss) resolves, probe readiness
        recovery for up to ``recovery_probe_s``. ``settle_s`` extends
        the run past the last arrival (open-loop tail: late responses
        still count)."""
        if speed <= 0:
            raise ValueError(f"speed must be > 0, got {speed}")
        report = LoadReport()
        plans = sorted(faults, key=lambda p: p.at_s)
        threads: List[threading.Thread] = []
        t0 = time.perf_counter()
        plan_i = 0
        for i, ev in enumerate(events):
            sched = ev.ts / speed
            # chaos due before the next issue: sleep to each plan's OWN
            # instant first — arming at the head of a long inter-arrival
            # gap would fire (and possibly for_s-expire) the fault long
            # before the requested at_s
            while plan_i < len(plans) and plans[plan_i].at_s <= sched:
                wait = plans[plan_i].at_s - (time.perf_counter() - t0)
                if wait > 0:
                    time.sleep(wait)
                self._arm(plans[plan_i], t0, report)
                plan_i += 1
            wait = sched - (time.perf_counter() - t0)
            if wait > 0:
                time.sleep(wait)
            self._sem.acquire()
            t_send = time.perf_counter() - t0
            th = threading.Thread(
                target=self._issue,
                args=(i, ev, t_send, sched, report),
                name=f"keystone-loadgen-{i}",
                daemon=True,
            )
            report.issued += 1
            th.start()
            threads.append(th)
        # chaos scheduled past the last arrival still runs
        for plan in plans[plan_i:]:
            wait = plan.at_s - (time.perf_counter() - t0)
            if wait > 0:
                time.sleep(wait)
            self._arm(plan, t0, report)
        if settle_s > 0:
            time.sleep(settle_s)
        for th in threads:
            th.join(timeout=SERVER_RESULT_BOUND_S + LOST_SLACK_S + 60.0)
        # clear any fault the timeline left armed, stamping t_clear
        self._clear_all(t0, report)
        report.duration_s = time.perf_counter() - t0
        self._probe_recovery(t0, report, recovery_probe_s)
        return report

    # -- internals ---------------------------------------------------------

    def _issue(
        self,
        index: int,
        ev: TraceEvent,
        t_send: float,
        t_sched: float,
        report: LoadReport,
    ) -> None:
        try:
            rec = self.target.send(ev)
        except Exception as e:  # a target bug must not strand the run
            logger.exception("loadgen target.send failed")
            rec = RequestRecord(
                0, 0.0, 0.0, "error",
                reason=f"target raised {type(e).__name__}: {e}",
                untyped=True,
            )
        finally:
            self._sem.release()
        rec.index = index
        rec.t_send = t_send
        rec.t_sched = t_sched
        report.add(rec)

    def _arm(
        self, plan: FaultPlan, t0: float, report: LoadReport
    ) -> None:
        spec = dict(plan.spec)
        if plan.for_s is not None:
            # the server self-disarms even if this driver dies
            spec.setdefault("for_s", plan.for_s)
        now = time.perf_counter() - t0
        logger.info("chaos: arming %s at t=%.2fs", spec, now)
        try:
            self.target.arm_fault(spec)
        except Exception:
            logger.exception("chaos arm failed for %s", spec)
            return
        # the clear time may come from EITHER the plan or a for_s
        # inside the spec clause itself; missing both means "armed
        # until the run ends" and _clear_all stamps it. Getting this
        # wrong shifts the recovery window the invariants measure.
        duration = (
            plan.for_s if plan.for_s is not None else spec.get("for_s")
        )
        report.fault_windows.append(
            FaultWindow(
                point=spec["point"], t_arm=now,
                t_clear=(now + duration) if duration else None,
                spec=spec,
            )
        )

    def _clear_all(self, t0: float, report: LoadReport) -> None:
        now = time.perf_counter() - t0
        for w in report.fault_windows:
            if w.t_clear is None or w.t_clear > now:
                try:
                    self.target.disarm_fault(w.point)
                except Exception:
                    logger.exception("chaos disarm failed for %s", w.point)
                w.t_clear = now

    def _probe_recovery(
        self, t0: float, report: LoadReport, bound_s: float
    ) -> None:
        if not report.fault_windows or bound_s <= 0:
            return
        report.ready_probed = True
        cleared = max(w.t_clear for w in report.fault_windows)
        # probe at least once even when the run tail already consumed
        # the bound (recovery may have happened while we drained)
        deadline = max(
            t0 + cleared + bound_s, time.perf_counter() + 0.5
        )
        while True:
            if self.target.ready():
                # an upper bound: ready may have flipped back earlier,
                # we only observe it at probe time
                report.ready_recovery_s = max(
                    0.0, (time.perf_counter() - t0) - cleared
                )
                return
            if time.perf_counter() >= deadline:
                break
            time.sleep(0.1)
        report.ready_recovery_s = None  # never recovered in bound


__all__ = [
    "FaultPlan",
    "FaultWindow",
    "FeedbackSender",
    "HttpTarget",
    "InprocTarget",
    "LoadGenerator",
    "LoadReport",
    "RequestRecord",
    "TYPED_SHED_REASONS",
]
