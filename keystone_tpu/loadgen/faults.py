"""Fault injection plane: named fault points compiled into the hot
paths as default-off no-ops.

Chaos-engineering discipline (Basiri et al., *Chaos Engineering*, IEEE
Software 2016): the faults a production serving plane must absorb —
a lane dying mid-window, a stalled host-prep stage, a black-holed
telemetry collector, a forced engine swap under peak — are injected
deliberately, at named points, under an experiment harness that
asserts the system's invariants while they fire. The points live in
the REAL hot paths (``gateway/pool.py``, ``serving/engine.py``,
``serving/pipeline.py``, ``observability/otlp.py``,
``gateway/lifecycle.py``) so an experiment exercises exactly the code
traffic exercises — no parallel "test mode" dispatch.

Cost contract: an UNARMED injector is a no-op on the hot path — one
attribute read and one falsy check (``fire`` returns before touching
any spec state, allocating nothing); the tier-1 suite asserts this
with a counting stub, and the bench family asserts the
``serving_gateway_p99`` / ``serving_pipeline_overlap`` numbers are
unchanged with the points compiled in.

Arming, three ways (all land in the same process-global registry):

- **code** — ``faults.arm("gateway.lane.kill", match={"lane": 0},
  count=8)``;
- **env** — ``KEYSTONE_FAULTS="pipeline.host_prep.stall=delay_ms:50
  gateway.lane.kill=lane:0,count:8"`` parsed by ``arm_from_env()``
  (the serving CLIs call it at startup);
- **HTTP** — ``POST /chaosz`` on the gateway frontend
  (``gateway/http.py``), the experiment driver's remote arm/disarm.

A spec can bound its own blast radius: ``count`` (auto-disarm after N
fires), ``for_s`` (auto-disarm on a wall clock), and ``match`` (fire
only when the call site's context matches, e.g. one lane of a pool).
Every fire counts on ``keystone_fault_injections_total{point}`` so an
experiment is auditable from the same ``/metrics`` scrape as the
symptoms it causes.

Fault points are *interpreted by their call sites*: an error point
raises ``FaultInjected``, a stall point sleeps ``delay_ms``, a
blackhole point drops a batch, and a **trigger** point
(``gateway.swap.force``) invokes callbacks registered by the component
(arming it IS the event). The catalog below is the contract the
``/chaosz`` route validates against.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

# the wired points: name -> (kind, where/what). /chaosz validates arms
# against this catalog; the injector itself accepts any name so tests
# and future subsystems can add points without touching this module.
FAULT_POINTS: Dict[str, str] = {
    "gateway.lane.kill": (
        "error @ gateway/pool.py Lane.submit — requests routed to the "
        "matched lane raise mid-flight; the pool's retry + health "
        "machinery must absorb it (match: lane=<index>)"
    ),
    "pipeline.host_prep.stall": (
        "stall @ serving/pipeline.py host-prep stage — the stage "
        "sleeps delay_ms per window, backing pressure up through the "
        "bounded queues into admission (match: engine=<name>)"
    ),
    "engine.dispatch.error": (
        "error @ serving/engine.py compute_staged — the compiled "
        "bucket dispatch raises, failing the whole window "
        "(match: engine=<name>)"
    ),
    "otlp.export.blackhole": (
        "drop @ observability/otlp.py — span batches are dropped "
        "instead of POSTed, simulating a dead collector with zero "
        "connect/timeout cost"
    ),
    "gateway.swap.force": (
        "trigger @ gateway/lifecycle.py — arming forces one live "
        "engine swap (rebucket force=True) on a background thread "
        "(match: gateway=<name>)"
    ),
    "router.replica.blackhole": (
        "drop @ fleet/router.py _forward — the fleet router drops "
        "the matched replica's /predict responses after the replica "
        "did the work (a return-path partition); the router's "
        "retry-on-another-replica + replica health machinery must "
        "absorb it (match: replica=<host:port> or index=<registration "
        "order>)"
    ),
    "router.replica.partition": (
        "error @ fleet/router.py _forward — the router<->replica "
        "link is severed BEFORE the forward dials (the matched "
        "replica never sees the request; the request-path complement "
        "of router.replica.blackhole's return-path drop). The "
        "router's retry-on-another-replica + replica health must "
        "absorb it like a connection refusal — the autoscale drill "
        "partitions a replica mid-scale-up and the loadgen verdict "
        "must stay green (match: replica=<host:port> or "
        "index=<registration order>)"
    ),
    "lifecycle.refit.poison": (
        "corrupt @ lifecycle/refit.py RefitAccumulator — one "
        "accumulated feedback chunk's targets are scaled to garbage "
        "BEFORE they fold into the normal equations (the held-out "
        "buffer stays clean), so the next solved candidate is wrong; "
        "the lifecycle's accuracy gate must catch it on the held-out "
        "comparison and auto-roll the candidate back within one "
        "policy tick (match: model=<id>)"
    ),
    "router.trace.drop": (
        "drop @ fleet/router.py _predict — the W3C traceparent "
        "header is stripped off the matched forward, so the replica "
        "never sees the router's trace id and mints its own; serving "
        "must be unaffected and the router's /debugz stitch must "
        "degrade to a partial router-side tree counted on "
        "keystone_trace_stitch_partial_total (match: "
        "replica=<host:port> or index=<registration order>)"
    ),
}

# points whose semantics are "arming IS the event" (no inline call
# site consults them): one-shot per arm, never left armed — a
# lingering trigger spec would pin the hot-path gate True with
# nothing to fire
TRIGGER_POINTS = frozenset({"gateway.swap.force"})


class FaultInjected(RuntimeError):
    """The typed error an armed error-mode fault point raises. Carries
    the point name so forensics can tell injected faults from real
    ones; to the request plane it is deliberately indistinguishable
    from any other lane/engine failure (that is the experiment)."""

    def __init__(self, point: str, **ctx: Any):
        self.point = point
        self.ctx = ctx
        detail = " ".join(f"{k}={v}" for k, v in sorted(ctx.items()))
        super().__init__(
            f"injected fault {point}" + (f" ({detail})" if detail else "")
        )


@dataclasses.dataclass
class FaultSpec:
    """One armed fault point (see module docstring for semantics)."""

    point: str
    count: Optional[int] = None     # max fires; None = until disarmed
    delay_ms: float = 0.0           # stall points sleep this long
    for_s: Optional[float] = None   # auto-disarm this long after arming
    match: Optional[Dict[str, Any]] = None  # ctx filter (subset match)
    armed_t: float = 0.0            # perf_counter at arm time
    fired: int = 0

    def expired(self, now: float) -> bool:
        return (
            self.for_s is not None and now - self.armed_t > self.for_s
        )

    def matches(self, ctx: Optional[Dict[str, Any]]) -> bool:
        if not self.match:
            return True
        if not ctx:
            return False
        return all(ctx.get(k) == v for k, v in self.match.items())

    def status(self) -> Dict[str, Any]:
        doc: Dict[str, Any] = {"point": self.point, "fired": self.fired}
        if self.count is not None:
            doc["count"] = self.count
        if self.delay_ms:
            doc["delay_ms"] = self.delay_ms
        if self.for_s is not None:
            doc["for_s"] = self.for_s
            doc["remaining_s"] = round(
                max(0.0, self.for_s - (time.perf_counter() - self.armed_t)),
                3,
            )
        if self.match:
            doc["match"] = dict(self.match)
        return doc


class FaultInjector:
    """Process-global registry of armed fault points.

    The hot-path contract lives in ``fire()``: with nothing armed it is
    one attribute read and a falsy return — no lock, no dict lookup, no
    allocation. Everything slower (spec resolution, expiry, match,
    counting) happens in ``_fire_slow`` only while at least one point
    is armed."""

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        self._specs: Dict[str, FaultSpec] = {}  # guarded-by: _lock
        # point -> [(fn, ctx)]: components register trigger callbacks
        # (e.g. the gateway's forced-swap); arming the point invokes
        # them on a background thread
        self._triggers: Dict[str, List] = {}  # guarded-by: _lock
        # total fires per point, kept across disarms (the /chaosz
        # "fired" audit; the Prometheus counter is the scrape surface)
        self._fired: Dict[str, int] = {}  # guarded-by: _lock
        # the hot-path gate: READ unlocked by design (one attribute
        # load per call site); every WRITE goes through _lock
        self.armed = False  # guarded-by: _lock
        self._registry = registry
        self._counter = None  # lazy: first arm touches the registry

    # -- hot path ----------------------------------------------------------

    def fire(
        self, point: str, ctx: Optional[Dict[str, Any]] = None
    ) -> Optional[FaultSpec]:
        """Ask whether ``point`` should fire. Returns the armed spec
        (the call site interprets it — raise, sleep ``delay_ms``,
        drop) or None. The unarmed path is the no-op contract."""
        if not self.armed:
            return None
        return self._fire_slow(point, ctx)

    def _fire_slow(
        self, point: str, ctx: Optional[Dict[str, Any]]
    ) -> Optional[FaultSpec]:
        with self._lock:
            spec = self._specs.get(point)
            if spec is None:
                return None
            if spec.expired(time.perf_counter()):
                self._disarm_locked(point)
                return None
            if not spec.matches(ctx):
                return None
            spec.fired += 1
            self._fired[point] = self._fired.get(point, 0) + 1
            if spec.count is not None and spec.fired >= spec.count:
                self._disarm_locked(point)
            counter = self._counter
        if counter is not None:
            counter.inc((point,))
        logger.info("fault point %s fired (ctx=%s)", point, ctx)
        return spec

    # -- arming ------------------------------------------------------------

    def _ensure_counter(self):
        if self._counter is None:
            if self._registry is None:
                from keystone_tpu.observability.registry import (
                    get_global_registry,
                )

                self._registry = get_global_registry()
            self._counter = self._registry.counter(
                "keystone_fault_injections_total",
                "chaos fault-point fires, by point",
                ("point",),
            )
        return self._counter

    def arm(
        self,
        point: str,
        *,
        count: Optional[int] = None,
        delay_ms: float = 0.0,
        for_s: Optional[float] = None,
        match: Optional[Dict[str, Any]] = None,
    ) -> FaultSpec:
        """Arm one point (re-arming replaces the spec). Trigger points
        invoke their registered callbacks once, on a daemon thread."""
        if count is not None and count < 1:
            raise ValueError(f"count must be >= 1, got {count}")
        if delay_ms < 0:
            raise ValueError(f"delay_ms must be >= 0, got {delay_ms}")
        spec = FaultSpec(
            point=point, count=count, delay_ms=float(delay_ms),
            for_s=for_s, match=dict(match) if match else None,
            armed_t=time.perf_counter(),
        )
        self._ensure_counter()
        with self._lock:
            self._specs[point] = spec
            self.armed = True
            triggers = list(self._triggers.get(point, ()))
        logger.warning("fault point %s ARMED: %s", point, spec.status())
        to_run = [
            (fn, ctx) for fn, ctx in triggers if spec.matches(ctx)
        ]
        one_shot = bool(triggers) or point in TRIGGER_POINTS
        if one_shot and not to_run:
            # a trigger point with nothing to run (no component
            # registered, or the match excluded every registration):
            # disarm NOW — leaving it armed would pin the hot-path
            # gate forever with nothing to fire
            logger.warning(
                "fault point %s armed but no registered trigger "
                "matched; disarming", point,
            )
            self.disarm(point)
            return spec
        if to_run:

            def run_triggers():
                for fn, ctx in to_run:
                    fired = self._fire_slow(point, ctx)
                    if fired is None:
                        continue  # count/for_s exhausted mid-loop
                    try:
                        fn(fired)
                    except Exception:
                        logger.exception(
                            "fault trigger for %s failed", point
                        )
                # trigger points are one-shot per arm: the event has
                # happened, so the spec auto-disarms — a lingering
                # trigger spec would pin the hot-path gate True (and
                # the injector lock onto every request) forever.
                # Disarm only OUR spec: a re-arm that raced this
                # thread owns the slot now and must not be cancelled.
                with self._lock:
                    if self._specs.get(point) is spec:
                        self._disarm_locked(point)

            threading.Thread(
                target=run_triggers,
                name=f"keystone-chaos-{point}",
                daemon=True,
            ).start()
        return spec

    def _disarm_locked(self, point: str) -> bool:
        existed = self._specs.pop(point, None) is not None
        if not self._specs:
            self.armed = False
        return existed

    def disarm(self, point: str) -> bool:
        with self._lock:
            existed = self._disarm_locked(point)
        if existed:
            logger.warning("fault point %s disarmed", point)
        return existed

    def disarm_all(self) -> None:
        with self._lock:
            self._specs.clear()
            self.armed = False

    # -- triggers (component-registered chaos actions) ---------------------

    def register_trigger(
        self,
        point: str,
        fn: Callable[[FaultSpec], None],
        ctx: Optional[Dict[str, Any]] = None,
    ) -> Callable[[], None]:
        """Register ``fn`` to run when ``point`` is armed (subject to
        the spec's ``match`` against ``ctx``). Returns an unregister
        callable — components MUST call it on close, or a retired
        instance keeps receiving chaos."""
        entry = (fn, dict(ctx) if ctx else None)
        with self._lock:
            self._triggers.setdefault(point, []).append(entry)

        def unregister() -> None:
            with self._lock:
                entries = self._triggers.get(point, [])
                if entry in entries:
                    entries.remove(entry)
                if not entries:
                    self._triggers.pop(point, None)

        return unregister

    # -- introspection (the /chaosz surface) -------------------------------

    def status(self) -> Dict[str, Any]:
        with self._lock:
            # expire lazily so the surface never shows a dead spec
            now = time.perf_counter()
            for point in [
                p for p, s in self._specs.items() if s.expired(now)
            ]:
                self._disarm_locked(point)
            return {
                "armed": {
                    p: s.status() for p, s in sorted(self._specs.items())
                },
                "fired_total": dict(sorted(self._fired.items())),
                "points": dict(FAULT_POINTS),
            }

    def fired_count(self, point: str) -> int:
        with self._lock:
            return self._fired.get(point, 0)


# -- the process-global injector (what the wired hot paths consult) --------

_INJECTOR = FaultInjector()


def get_injector() -> FaultInjector:
    return _INJECTOR


def armed() -> bool:
    """The hot-path GATE: call sites check this before building a ctx
    dict, so the unarmed path allocates nothing at all —
    ``if faults.armed() and faults.fire(point, {...}):``."""
    return _INJECTOR.armed


def fire(
    point: str, ctx: Optional[Dict[str, Any]] = None
) -> Optional[FaultSpec]:
    """The hot-path check the wired call sites use (delegates — the
    gate logic lives in ``FaultInjector.fire`` alone). Unarmed: one
    attribute read, returns None."""
    return _INJECTOR.fire(point, ctx)


def arm(point: str, **kwargs: Any) -> FaultSpec:
    return _INJECTOR.arm(point, **kwargs)


def disarm(point: str) -> bool:
    return _INJECTOR.disarm(point)


def disarm_all() -> None:
    _INJECTOR.disarm_all()


# -- env arming ------------------------------------------------------------

_SPEC_KEYS = ("count", "delay_ms", "for_s")


def parse_fault_spec(clause: str) -> Dict[str, Any]:
    """One ``point[=k:v[,k:v...]]`` clause -> arm() kwargs (plus
    ``point``). Keys outside count/delay_ms/for_s become ``match``
    entries; match values parse as int when they look like one."""
    clause = clause.strip()
    if not clause:
        raise ValueError("empty fault clause")
    point, _, argstr = clause.partition("=")
    point = point.strip()
    kwargs: Dict[str, Any] = {"point": point}
    match: Dict[str, Any] = {}
    if argstr.strip():
        for pair in argstr.split(","):
            key, sep, val = pair.partition(":")
            key, val = key.strip(), val.strip()
            if not sep or not key:
                raise ValueError(
                    f"bad fault arg {pair!r} in {clause!r} "
                    "(want key:value)"
                )
            if key == "count":
                kwargs["count"] = int(val)
            elif key == "delay_ms":
                kwargs["delay_ms"] = float(val)
            elif key == "for_s":
                kwargs["for_s"] = float(val)
            else:
                try:
                    match[key] = int(val)
                except ValueError:
                    match[key] = val
    if match:
        kwargs["match"] = match
    return kwargs


def arm_from_env(environ=None) -> List[FaultSpec]:
    """Parse ``KEYSTONE_FAULTS`` (whitespace-separated clauses, see
    ``parse_fault_spec``) and arm each point on the global injector.
    The serving CLIs call this at startup; absent/empty env is a
    no-op."""
    import os

    env = environ if environ is not None else os.environ
    raw = env.get("KEYSTONE_FAULTS", "").strip()
    if not raw:
        return []
    specs = []
    for clause in raw.split():
        kwargs = parse_fault_spec(clause)
        point = kwargs.pop("point")
        specs.append(_INJECTOR.arm(point, **kwargs))
    return specs


__all__ = [
    "FAULT_POINTS",
    "FaultInjected",
    "FaultInjector",
    "FaultSpec",
    "arm",
    "arm_from_env",
    "disarm",
    "disarm_all",
    "fire",
    "get_injector",
    "parse_fault_spec",
]
