"""Request traces: parse the gateway's ``--request-log`` and
synthesize open-loop workloads.

**Recorded traces.** The gateway frontend emits one structured JSON
line per ``/predict`` instance (``gateway/http.py _log_request``);
since this subsystem landed, each line also carries ``n_rows`` (how
many instances rode the originating POST), ``shape`` (that instance's
example shape) and ``deadline_ms`` — the fields a replayer needs to
reconstruct the request, not just observe its outcome.
``parse_request_log`` tolerates the old format (ts/status/latency_ms/
lane/trace_id only): such lines replay as single-instance requests of
a caller-chosen default shape. ``collapse_posts`` folds the
one-line-per-instance records back into one event per POST (runs of
``n_rows`` adjacent lines sharing shape/deadline/timestamp), so a
replay issues the same requests the clients did rather than one POST
per instance.

**Synthetic workloads.** Open-loop arrival processes in the MLPerf
Inference LoadGen tradition (Reddi et al.): requests are issued on the
generator's clock, never paced by responses, so overload actually
overloads. Arrivals: ``poisson`` (exponential gaps — the memoryless
baseline), ``lognormal`` and ``pareto`` (heavy-tail burstiness, the
production shape padding/batching decisions must survive). Request
sizes draw from an explicit mixture (``size_mix``), deadlines from a
fixed value with optional lognormal jitter. Everything is seeded —
the same spec replays bit-identically."""

from __future__ import annotations

import dataclasses
import json
import logging
import math
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

# lines from one POST land within this window; collapse_posts uses it
# to stop a run that merely LOOKS contiguous (same shape/deadline) but
# came from requests seconds apart
_POST_WINDOW_S = 0.05


@dataclasses.dataclass
class TraceEvent:
    """One replayable request: issue ``n_rows`` instances of ``shape``
    at ``ts`` (seconds; relative once normalized) with ``deadline_ms``.
    The recorded-outcome fields (status/latency/lane/trace id) ride
    along for analysis but don't drive the replay."""

    ts: float
    n_rows: int = 1
    shape: Optional[Tuple[int, ...]] = None
    deadline_ms: Optional[float] = None
    status: Optional[int] = None
    latency_ms: Optional[float] = None
    lane: Optional[int] = None
    trace_id: Optional[str] = None
    post_seq: Optional[Any] = None  # shared by lines of one POST
    # (opaque id — a "nonce-counter" string from the gateway)
    # fleet-tier fields (serve-router --request-log): which replica
    # served the POST and how many forward attempts it took — ride
    # along for analysis, don't drive the replay
    replica: Optional[str] = None
    attempts: Optional[int] = None
    # zoo mode: the named model that served the instance (None on the
    # bare single-model route). This one DOES drive the replay — the
    # HTTP target POSTs /predict/<model> when set, so a recorded
    # multi-model mix replays against the same per-model lanes
    model: Optional[str] = None


def parse_request_log_line(line: str) -> Optional[TraceEvent]:
    """One ``--request-log`` line -> event, or None for non-record
    lines (startup banners, blank lines, foreign log output)."""
    line = line.strip()
    if not line.startswith("{"):
        return None
    try:
        doc = json.loads(line)
    except ValueError:
        return None
    if not isinstance(doc, dict) or "ts" not in doc:
        return None
    if doc.get("path") not in (None, "/predict"):
        return None
    shape = doc.get("shape")
    if shape is not None:
        try:
            shape = tuple(int(s) for s in shape)
        except (TypeError, ValueError):
            shape = None
    try:
        return TraceEvent(
            ts=float(doc["ts"]),
            # old-format lines (pre-loadgen) have none of these three:
            # a 1-instance default-shape event is the degraded replay
            n_rows=int(doc.get("n_rows", 1)),
            shape=shape,
            deadline_ms=doc.get("deadline_ms"),
            status=doc.get("status"),
            latency_ms=doc.get("latency_ms"),
            lane=doc.get("lane"),
            trace_id=doc.get("trace_id"),
            post_seq=doc.get("post_seq"),
            replica=doc.get("replica"),
            attempts=doc.get("attempts"),
            model=doc.get("model"),
        )
    except (TypeError, ValueError):
        return None


def parse_request_log(lines: Iterable[str]) -> List[TraceEvent]:
    """Every parseable record line, one event per line (per recorded
    instance). Feed through ``collapse_posts`` to restore per-POST
    granularity for replay."""
    events = []
    for line in lines:
        ev = parse_request_log_line(line)
        if ev is not None:
            events.append(ev)
    return events


def collapse_posts(events: Sequence[TraceEvent]) -> List[TraceEvent]:
    """Fold per-instance lines back into per-POST events, one event
    of ``n_rows`` instances per POST. Lines carrying a ``post_seq``
    (every line since this subsystem landed) dedupe by that id — the
    robust path, immune to concurrent handler threads interleaving
    their lines in the file. Lines WITHOUT a post_seq (hand-authored
    or foreign traces that state ``n_rows`` but no id) fall back to
    adjacency: a run of up to ``n_rows`` neighboring lines sharing
    (n_rows, shape, deadline_ms) within one post window. Shed/error
    POSTs logged a single line and still collapse to one full-size
    event — the replay reissues the whole request, which is the
    point."""
    out: List[TraceEvent] = []
    seen_seq = set()
    i = 0
    n = len(events)
    while i < n:
        head = events[i]
        if head.post_seq is not None:
            if head.post_seq not in seen_seq:
                seen_seq.add(head.post_seq)
                out.append(head)
            i += 1
            continue
        run = 1
        while (
            run < head.n_rows
            and i + run < n
            and events[i + run].post_seq is None
            and events[i + run].n_rows == head.n_rows
            and events[i + run].shape == head.shape
            and events[i + run].deadline_ms == head.deadline_ms
            and events[i + run].model == head.model
            and events[i + run].ts - head.ts <= _POST_WINDOW_S
        ):
            run += 1
        out.append(head)
        i += run
    return out


def load_trace(path: str, collapse: bool = True) -> List[TraceEvent]:
    """Parse a ``--request-log`` JSONL file into replayable events
    (per-POST by default), timestamps normalized to start at 0.
    ``collapse=False`` replays ONE single-instance request per
    recorded line — n_rows is reset to 1, because keeping the
    per-POST count on every one of its per-instance lines would
    multiply the offered load by n_rows."""
    with open(path, "r", encoding="utf-8") as f:
        events = parse_request_log(f)
    if collapse:
        events = collapse_posts(events)
    else:
        events = [
            dataclasses.replace(e, n_rows=1) for e in events
        ]
    return normalize(events)


def normalize(events: Sequence[TraceEvent]) -> List[TraceEvent]:
    """Sort by timestamp and rebase so the first event is at t=0 (the
    replayer's clock is relative)."""
    events = sorted(events, key=lambda e: e.ts)
    if not events:
        return []
    t0 = events[0].ts
    return [dataclasses.replace(e, ts=e.ts - t0) for e in events]


# -- synthetic workloads ---------------------------------------------------

ARRIVALS = ("poisson", "lognormal", "pareto", "uniform")


def _inter_arrivals(
    rng: np.random.Generator,
    n: int,
    arrivals: str,
    rate: float,
    sigma: float,
    alpha: float,
) -> np.ndarray:
    """``n`` gaps with mean 1/rate under the named process."""
    mean_gap = 1.0 / rate
    if arrivals == "poisson":
        return rng.exponential(mean_gap, n)
    if arrivals == "lognormal":
        # E[LN(mu, sigma)] = exp(mu + sigma^2/2) = mean_gap
        mu = np.log(mean_gap) - sigma * sigma / 2.0
        return rng.lognormal(mu, sigma, n)
    if arrivals == "pareto":
        if alpha <= 1.0:
            raise ValueError(
                f"pareto arrivals need alpha > 1 for a finite mean "
                f"gap, got {alpha}"
            )
        # Lomax+shift: gap = xm * (1 + Pareto(alpha)); E = xm*alpha/(alpha-1)
        xm = mean_gap * (alpha - 1.0) / alpha
        return xm * (1.0 + rng.pareto(alpha, n))
    if arrivals == "uniform":
        return np.full(n, mean_gap)
    raise ValueError(
        f"unknown arrival process {arrivals!r} (have {ARRIVALS})"
    )


def synthesize(
    n_requests: int,
    *,
    arrivals: str = "poisson",
    rate: float = 100.0,
    size_mix: Sequence[Tuple[int, float]] = ((1, 1.0),),
    shape: Sequence[int] = (8,),
    deadline_ms: Optional[float] = None,
    deadline_sigma: float = 0.0,
    sigma: float = 1.0,
    alpha: float = 1.5,
    seed: int = 0,
) -> List[TraceEvent]:
    """``n_requests`` synthetic events: arrival gaps from the named
    process at ``rate`` req/s, per-request instance counts drawn from
    ``size_mix`` ((n_rows, weight) pairs), a fixed per-example
    ``shape``, and deadlines of ``deadline_ms`` with optional
    lognormal jitter (``deadline_sigma``). Deterministic per seed."""
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if rate <= 0:
        raise ValueError(f"rate must be > 0, got {rate}")
    rng = np.random.default_rng(seed)
    gaps = _inter_arrivals(rng, n_requests, arrivals, rate, sigma, alpha)
    ts = np.concatenate([[0.0], np.cumsum(gaps[:-1])])
    return _events_at(
        rng, ts, size_mix, shape, deadline_ms, deadline_sigma
    )


def _events_at(
    rng: np.random.Generator,
    ts: np.ndarray,
    size_mix: Sequence[Tuple[int, float]],
    shape: Sequence[int],
    deadline_ms: Optional[float],
    deadline_sigma: float,
) -> List[TraceEvent]:
    """Dress arrival instants with sizes/deadlines — the shared tail
    of ``synthesize`` and ``synthesize_steps``."""
    n_requests = len(ts)
    sizes = np.asarray([int(s) for s, _ in size_mix])
    weights = np.asarray([float(w) for _, w in size_mix], np.float64)
    if (weights <= 0).any():
        raise ValueError(f"size_mix weights must be > 0: {list(size_mix)}")
    weights = weights / weights.sum()
    n_rows = rng.choice(sizes, size=n_requests, p=weights)
    deadlines: List[Optional[float]] = [deadline_ms] * n_requests
    if deadline_ms is not None and deadline_sigma > 0:
        mu = np.log(deadline_ms) - deadline_sigma**2 / 2.0
        deadlines = [
            float(d)
            for d in rng.lognormal(mu, deadline_sigma, n_requests)
        ]
    return [
        TraceEvent(
            ts=float(ts[i]),
            n_rows=int(n_rows[i]),
            shape=tuple(int(s) for s in shape),
            deadline_ms=deadlines[i],
        )
        for i in range(n_requests)
    ]


def synthesize_steps(
    steps: Sequence[Tuple[float, float]],
    *,
    arrivals: str = "poisson",
    size_mix: Sequence[Tuple[int, float]] = ((1, 1.0),),
    shape: Sequence[int] = (8,),
    deadline_ms: Optional[float] = None,
    deadline_sigma: float = 0.0,
    sigma: float = 1.0,
    alpha: float = 1.5,
    seed: int = 0,
) -> List[TraceEvent]:
    """A STEP/RAMP offered-load shape: ``steps`` is ``[(rate,
    duration_s), ...]`` and each step issues arrivals from the named
    process at its own rate for its own duration — the deterministic
    load staircase the scale-out drills and the capacity planner
    script against a fleet (a ramp is just many small steps). A
    ``(0, duration)`` step is a silence — the idle tail a scale-down
    drill needs. Deterministic per seed, like ``synthesize``.

    The open-loop replayer treats the result identically to any
    other trace: arrivals on the generator's clock, never paced by
    responses, so the high step genuinely overloads an under-scaled
    fleet."""
    if not steps:
        raise ValueError("synthesize_steps needs at least one step")
    expected = sum(
        float(rate) * float(dur) for rate, dur in steps
        if math.isfinite(float(rate)) and math.isfinite(float(dur))
    )
    if expected > 2_000_000:
        # --synthetic bounds the event count explicitly; the
        # staircase must too — a typo'd rate must fail loud, not
        # allocate the host away before the replay starts
        raise ValueError(
            f"steps {list(steps)} expect ~{expected:.0f} arrivals; "
            "bound the workload under 2e6 events"
        )
    rng = np.random.default_rng(seed)
    ts: List[float] = []
    t0 = 0.0
    for rate, duration_s in steps:
        rate, duration_s = float(rate), float(duration_s)
        if not math.isfinite(duration_s) or duration_s <= 0:
            raise ValueError(
                f"step durations must be finite and > 0, got "
                f"{duration_s}"
            )
        if not math.isfinite(rate) or rate < 0:
            raise ValueError(
                f"step rates must be finite and >= 0, got {rate}"
            )
        if rate > 0:
            # draw in generously-sized batches until the step is
            # covered (heavy-tail processes can exhaust a single
            # batch before the step's clock runs out) — the sequence
            # of draws is still seeded-deterministic
            expect = max(1, int(rate * duration_s))
            draw = expect + max(8, int(4 * math.sqrt(expect)))
            t = t0
            end = t0 + duration_s
            while t < end:
                gaps = _inter_arrivals(
                    rng, draw, arrivals, rate, sigma, alpha
                )
                for gap in gaps:
                    t += float(gap)
                    if t >= end:
                        break
                    ts.append(t)
        t0 += duration_s
    if not ts:
        raise ValueError(
            f"steps {list(steps)} produced no arrivals (rates too "
            "low for their durations)"
        )
    return _events_at(
        rng,
        np.asarray(ts),
        size_mix,
        shape,
        deadline_ms,
        deadline_sigma,
    )


def parse_steps(spec: str) -> List[Tuple[float, float]]:
    """CLI step spec ``"rate:duration,..."`` (e.g. ``"5:4,40:8,5:6"``:
    4 s at 5 rps, 8 s at 40 rps, 6 s back at 5 rps) ->
    ``[(rate, duration_s), ...]`` for ``synthesize_steps``."""
    steps = []
    for part in spec.split(","):
        rate, sep, duration = part.partition(":")
        if not sep:
            raise ValueError(
                f"bad step entry {part!r} (want rate:duration_s)"
            )
        steps.append((float(rate), float(duration)))
    if not steps:
        raise ValueError("empty step spec")
    return steps


def parse_size_mix(spec: str) -> List[Tuple[int, float]]:
    """CLI mixture spec ``"1:0.8,4:0.15,16:0.05"`` ->
    [(n_rows, weight), ...]."""
    mix = []
    for part in spec.split(","):
        rows, sep, weight = part.partition(":")
        if not sep:
            raise ValueError(
                f"bad size-mix entry {part!r} (want rows:weight)"
            )
        mix.append((int(rows), float(weight)))
    if not mix:
        raise ValueError("empty size mix")
    return mix


def summarize(events: Sequence[TraceEvent]) -> Dict[str, Any]:
    """Quick shape-of-the-workload stats (the CLI prints this before a
    run so an operator can sanity-check a trace)."""
    if not events:
        return {"requests": 0}
    gaps = np.diff([e.ts for e in events])
    rows = np.asarray([e.n_rows for e in events])
    return {
        "requests": len(events),
        "duration_s": round(float(events[-1].ts - events[0].ts), 3),
        "instances": int(rows.sum()),
        "mean_gap_ms": (
            round(float(gaps.mean()) * 1e3, 3) if len(gaps) else None
        ),
        "p99_gap_ms": (
            round(float(np.percentile(gaps, 99)) * 1e3, 3)
            if len(gaps) else None
        ),
        "size_counts": {
            str(int(s)): int((rows == s).sum()) for s in np.unique(rows)
        },
        "with_deadline": int(
            sum(1 for e in events if e.deadline_ms is not None)
        ),
    }


__all__ = [
    "ARRIVALS",
    "TraceEvent",
    "collapse_posts",
    "load_trace",
    "normalize",
    "parse_request_log",
    "parse_request_log_line",
    "parse_size_mix",
    "parse_steps",
    "summarize",
    "synthesize",
    "synthesize_steps",
]
