"""Invariant checking: turn a chaos experiment into a verdict.

The robustness claims PRs 3–6 made in prose become machine-checked
assertions over a ``LoadReport``:

- **every admitted request resolves** — zero ``lost`` records. A
  future that never resolves is the worst serving bug there is: the
  client hangs, the SLO clock keeps running, and no counter shows it.
- **failures are typed sheds only** — zero untyped failures. Under
  chaos the gateway may 429/503/504 with a typed ``Overloaded``
  reason (that IS the design), but a naked 500 (or an injected fault
  escaping to a caller) means the retry/health plane leaked.
- **readiness recovers** — after the last fault clears, ``/readyz``
  must go green again within the probe bound (the runner measures it;
  this checks it happened).
- **p99 recovers** — tail latency of traffic sent after the fault
  cleared must return to within ``p99_factor`` × the pre-fault p99
  (plus a small absolute slack so microsecond baselines don't turn
  scheduler jitter into a red verdict) within ``recovery_within_s``.
  The checker slides the window start across the recovery bound and
  reports the earliest second at which the tail is back in bounds.
- **shed rate bounded** (optional) — the experiment's declared
  shed-rate ceiling.
- **p99 bounded** (optional) — an absolute tail ceiling over the
  whole run.

A checker is only trustworthy if it can fail: the tier-1 suite feeds
it stub gateways that lose futures, return untyped 500s, and never
recover readiness, and asserts each produces a red verdict."""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional

from keystone_tpu.loadgen.runner import LoadReport

# absolute slack added to the p99 recovery bound: a 2 ms pre-fault
# baseline must not fail the 1.5x rule over 1 ms of scheduler noise
DEFAULT_P99_SLACK_S = 0.005


@dataclasses.dataclass
class InvariantResult:
    name: str
    passed: bool
    detail: str

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Verdict:
    passed: bool
    invariants: List[InvariantResult]
    stats: Dict[str, Any]

    def as_dict(self) -> Dict[str, Any]:
        return {
            "passed": self.passed,
            "invariants": [r.as_dict() for r in self.invariants],
            "stats": self.stats,
        }

    def to_json(self, indent: Optional[int] = 1) -> str:
        return json.dumps(self.as_dict(), indent=indent)

    def failures(self) -> List[InvariantResult]:
        return [r for r in self.invariants if not r.passed]


class InvariantChecker:
    """Declared bounds for one experiment; ``check`` renders the
    verdict. Bounds are per-experiment state (not per-call args) so a
    bench row / CLI invocation states its contract once, up front."""

    def __init__(
        self,
        *,
        p99_factor: float = 1.5,
        p99_slack_s: float = DEFAULT_P99_SLACK_S,
        recovery_within_s: float = 10.0,
        max_shed_rate: Optional[float] = None,
        max_p99_s: Optional[float] = None,
        require_readiness_recovery: bool = True,
    ):
        self.p99_factor = float(p99_factor)
        self.p99_slack_s = float(p99_slack_s)
        self.recovery_within_s = float(recovery_within_s)
        self.max_shed_rate = max_shed_rate
        self.max_p99_s = max_p99_s
        self.require_readiness_recovery = require_readiness_recovery

    def check(self, report: LoadReport) -> Verdict:
        results = [
            self._all_resolved(report),
            self._typed_only(report),
        ]
        if report.fault_windows:
            if self.require_readiness_recovery:
                results.append(self._readiness(report))
            results.append(self._p99_recovery(report))
        if self.max_shed_rate is not None:
            results.append(self._shed_rate(report))
        if self.max_p99_s is not None:
            results.append(self._p99_bound(report))
        stats = report.stats()
        stats.update(self._recovery_stats(report))
        stats["exemplars"] = self._exemplars(report)
        return Verdict(
            passed=all(r.passed for r in results),
            invariants=results,
            stats=stats,
        )

    # -- forensic exemplars --------------------------------------------------

    @staticmethod
    def _exemplars(report: LoadReport, limit: int = 16) -> Dict[str, Any]:
        """Trace ids a human (or the CLI) can chase into
        ``/debugz?trace_id=``: the worst-latency success plus every
        lost and untyped request (capped) — a red verdict names the
        exact requests that broke it, not just counts. Lost requests
        usually have no trace id (no response came back); they are
        listed anyway so the verdict shows what IS unattributable."""

        def entry(r) -> Dict[str, Any]:
            return {
                "index": r.index,
                "trace_id": r.trace_id,
                "latency_ms": (
                    round(r.latency_s * 1e3, 3)
                    if r.latency_s is not None else None
                ),
                "code": r.code,
                "reason": r.reason,
            }

        oks = [
            r for r in report.records
            if r.status == "ok" and r.latency_s is not None
        ]
        worst = max(oks, key=lambda r: r.latency_s) if oks else None
        lost = [r for r in report.records if r.status == "lost"]
        untyped = [r for r in report.records if r.untyped]
        return {
            "worst_latency": entry(worst) if worst is not None else None,
            "lost": [entry(r) for r in lost[:limit]],
            "untyped": [entry(r) for r in untyped[:limit]],
        }

    # -- the invariants ----------------------------------------------------

    def _all_resolved(self, report: LoadReport) -> InvariantResult:
        lost = [r for r in report.records if r.status == "lost"]
        unaccounted = report.issued - len(report.records)
        ok = not lost and unaccounted == 0
        detail = (
            f"{report.issued} issued, {len(report.records)} resolved, "
            f"{len(lost)} lost"
        )
        if unaccounted:
            detail += f", {unaccounted} vanished without a record"
        if lost:
            detail += (
                "; first: " + (lost[0].reason or "no terminal outcome")
            )
        return InvariantResult("every_admitted_request_resolves", ok, detail)

    def _typed_only(self, report: LoadReport) -> InvariantResult:
        untyped = [r for r in report.records if r.untyped]
        detail = f"{len(untyped)} untyped failures"
        if untyped:
            first = untyped[0]
            detail += (
                f"; first: status={first.status} code={first.code} "
                f"reason={first.reason!r}"
            )
        return InvariantResult(
            "failures_are_typed_sheds_only", not untyped, detail
        )

    def _readiness(self, report: LoadReport) -> InvariantResult:
        if not report.ready_probed:
            return InvariantResult(
                "readiness_recovers_after_fault", False,
                "fault windows ran but readiness was never probed",
            )
        ok = report.ready_recovery_s is not None
        detail = (
            f"/readyz green {report.ready_recovery_s:.2f}s after the "
            "last fault cleared (observed upper bound)"
            if ok
            else "/readyz never recovered within the probe bound"
        )
        return InvariantResult("readiness_recovers_after_fault", ok, detail)

    def _p99_recovery(self, report: LoadReport) -> InvariantResult:
        fault_start = min(w.t_arm for w in report.fault_windows)
        cleared = max(
            w.t_clear if w.t_clear is not None else w.t_arm
            for w in report.fault_windows
        )
        pre = report.p99(0.0, fault_start)
        if pre is None:
            return InvariantResult(
                "p99_recovers_after_fault", False,
                "no pre-fault completions to baseline against "
                "(arm the fault later into the run)",
            )
        bound = pre * self.p99_factor + self.p99_slack_s
        rec_at = self._recovery_second(report, cleared, bound)
        if rec_at is None:
            post = report.p99(cleared + self.recovery_within_s)
            return InvariantResult(
                "p99_recovers_after_fault", False,
                f"p99 never returned under {bound * 1e3:.1f}ms "
                f"({self.p99_factor}x pre-fault {pre * 1e3:.1f}ms "
                f"+ slack) within "
                f"{self.recovery_within_s:.0f}s of the fault "
                f"clearing; tail-window p99 "
                + (f"{post * 1e3:.1f}ms" if post is not None else "n/a"),
            )
        post = report.p99(cleared + rec_at)
        return InvariantResult(
            "p99_recovers_after_fault", True,
            f"p99 {post * 1e3:.1f}ms within {rec_at:.0f}s of the fault "
            f"clearing (bound {bound * 1e3:.1f}ms = "
            f"{self.p99_factor}x pre-fault {pre * 1e3:.1f}ms "
            f"+ {self.p99_slack_s * 1e3:.0f}ms slack)",
        )

    def _recovery_second(
        self, report: LoadReport, cleared: float, bound: float
    ) -> Optional[float]:
        """Earliest whole second k <= recovery_within_s such that the
        p99 of ok-requests SENT after cleared+k is within bound (and
        at least one such request exists)."""
        k = 0.0
        while k <= self.recovery_within_s:
            p99 = report.p99(cleared + k)
            if p99 is not None and p99 <= bound:
                return k
            k += 1.0
        return None

    def _recovery_stats(self, report: LoadReport) -> Dict[str, Any]:
        if not report.fault_windows:
            return {}
        fault_start = min(w.t_arm for w in report.fault_windows)
        cleared = max(
            w.t_clear if w.t_clear is not None else w.t_arm
            for w in report.fault_windows
        )
        pre = report.p99(0.0, fault_start)
        during = report.p99(fault_start, cleared)
        post = report.p99(cleared)
        stats = {
            "pre_fault_p99_ms": (
                round(pre * 1e3, 3) if pre is not None else None
            ),
            "during_fault_p99_ms": (
                round(during * 1e3, 3) if during is not None else None
            ),
            "post_fault_p99_ms": (
                round(post * 1e3, 3) if post is not None else None
            ),
            "p99_recovery_s": None,
            "recovered_p99_ms": None,
        }
        if pre is not None:
            # the whole-post-window p99 above includes the backlog
            # drain right after the fault clears; the RECOVERED number
            # (from the earliest in-bounds second the recovery
            # invariant found) is the steady-state the row reports
            bound = pre * self.p99_factor + self.p99_slack_s
            rec_at = self._recovery_second(report, cleared, bound)
            if rec_at is not None:
                recovered = report.p99(cleared + rec_at)
                stats["p99_recovery_s"] = rec_at
                stats["recovered_p99_ms"] = round(recovered * 1e3, 3)
        return stats

    def _shed_rate(self, report: LoadReport) -> InvariantResult:
        total = len(report.records)
        shed = report.by_status().get("shed", 0)
        rate = shed / total if total else 0.0
        ok = rate <= self.max_shed_rate
        return InvariantResult(
            "shed_rate_bounded", ok,
            f"shed {shed}/{total} ({rate:.1%}) vs bound "
            f"{self.max_shed_rate:.1%}",
        )

    def _p99_bound(self, report: LoadReport) -> InvariantResult:
        p99 = report.p99()
        if p99 is None:
            return InvariantResult(
                "p99_bounded", False, "no successful requests to measure"
            )
        ok = p99 <= self.max_p99_s
        return InvariantResult(
            "p99_bounded", ok,
            f"whole-run p99 {p99 * 1e3:.1f}ms vs bound "
            f"{self.max_p99_s * 1e3:.1f}ms",
        )


__all__ = [
    "DEFAULT_P99_SLACK_S",
    "InvariantChecker",
    "InvariantResult",
    "Verdict",
]
