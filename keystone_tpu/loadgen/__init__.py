"""Trace-driven load generation + chaos harness.

The experiment DRIVER the serving stack was missing: PRs 1–6 built
admission control, replica lanes, pipelined dispatch, SLO forensics,
and device-truth observability — all *observers*; this package
generates the traffic and the failures they observe, then asserts the
stack's invariants held:

- ``trace`` — parse the gateway's ``--request-log`` JSONL into
  replayable events; synthesize open-loop workloads (Poisson /
  heavy-tail lognormal / Pareto arrivals, request-size mixtures,
  deadline distributions).
- ``runner`` — MLPerf-LoadGen-style open-loop replay against a live
  gateway (HTTP or in-process), preserving recorded inter-arrival
  gaps with a ``--speed`` factor, arming a chaos timeline as it runs.
- ``faults`` — the process-global ``FaultInjector``: named fault
  points compiled into the hot paths as default-off no-ops
  (``gateway.lane.kill``, ``pipeline.host_prep.stall``,
  ``engine.dispatch.error``, ``otlp.export.blackhole``,
  ``gateway.swap.force``), armable via code, ``KEYSTONE_FAULTS`` env,
  or ``POST /chaosz``.
- ``invariants`` — the verdict: every admitted request resolves,
  failures are typed sheds only, readiness and p99 recover after the
  fault clears, shed rate stays in bounds.

``python -m keystone_tpu serve-loadgen`` is the CLI
(``loadgen/cli.py``); ``serving/bench.py``'s ``serving_chaos_*`` rows
and ``bin/smoke-chaos.sh`` drive the same APIs in CI.

Import weight: the serving hot paths (``gateway/pool.py``,
``serving/engine.py``, ``serving/pipeline.py``,
``observability/otlp.py``) import this package for ``faults`` alone,
so only ``faults`` loads eagerly — the driver half (trace parsing,
the runner, the checker, the CLI) resolves lazily via module
``__getattr__`` and never rides along into a serving process that
doesn't use it.
"""

from keystone_tpu.loadgen import faults
from keystone_tpu.loadgen.faults import (
    FAULT_POINTS,
    FaultInjected,
    FaultInjector,
    FaultSpec,
)

# lazy attribute -> owning submodule (the driver half of the package)
_LAZY = {
    "trace": None,
    "runner": None,
    "invariants": None,
    "cli": None,
    "TraceEvent": "trace",
    "collapse_posts": "trace",
    "load_trace": "trace",
    "parse_request_log": "trace",
    "synthesize": "trace",
    "FaultPlan": "runner",
    "HttpTarget": "runner",
    "InprocTarget": "runner",
    "LoadGenerator": "runner",
    "LoadReport": "runner",
    "RequestRecord": "runner",
    "InvariantChecker": "invariants",
    "InvariantResult": "invariants",
    "Verdict": "invariants",
}

__all__ = sorted(
    ["FAULT_POINTS", "FaultInjected", "FaultInjector", "FaultSpec",
     "faults"] + list(_LAZY)
)


def __getattr__(name):
    target = _LAZY.get(name, "missing")
    if target == "missing":
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        )
    import importlib

    module = importlib.import_module(
        f"keystone_tpu.loadgen.{target or name}"
    )
    return module if target is None else getattr(module, name)
