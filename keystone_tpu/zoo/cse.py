"""Cross-model featurize CSE: compute shared prefixes once per window.

KeystoneML's rule engine deduplicates common subexpressions across a
training DAG; the serving-plane analogue is co-hosted models whose
fused featurize chains are the SAME chain. Detection is by content,
not by name: two models share a prefix iff their featurize pipelines'
``pipeline_token``s — the SHA-256 digest of operator classes, wiring,
and every parameter array — are equal (``featurize_groups``). That is
exactly the fingerprint the AOT store trusts to keep one model's
executable from serving another's predictions, so it is also the
proof two prefixes compute the same function.

``SharedPrefixEngine`` then hosts one whole group behind one engine:
a single per-bucket XLA program computes ``feat = featurize(raw)``
ONCE and fans the activations out to every member's head —

    {model_a: head_a(feat), model_b: head_b(feat), ...}

Dict outputs ride the existing window plumbing untouched: the
``MicroBatcher`` tree-slices each row out of the batched output, so
every request's future resolves to a per-model dict and the zoo picks
(or fans out) from it. The engine's own compile/dispatch counters are
the measurement seam the ``serving_zoo`` bench row gates on: one
trace per bucket and one dispatch per window for the whole group,
where solo hosting pays one of each PER MODEL.

The AOT executable store is deliberately OFF here (``aot_store=None``
forced): ``CompiledPipeline.warmup`` fingerprints ``self.pipeline``,
which for a multi-head program is only the primary head — a stored
entry under that token could later serve a plain single-model engine.
Shared-prefix programs recompile per process (or replay from the
persistent XLA compile cache) until the fingerprint covers head sets.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

from keystone_tpu.observability import device as device_obs
from keystone_tpu.observability.attribution import RowClaimQueue
from keystone_tpu.serving.engine import CompiledPipeline
from keystone_tpu.serving.featurize import featurize_token

logger = logging.getLogger(__name__)


def featurize_groups(
    featurizers: Dict[str, Any]
) -> List[Tuple[str, ...]]:
    """Group model ids by identical featurize ``pipeline_token``.
    ``featurizers`` maps model id -> fitted featurize pipeline (models
    without one simply aren't candidates — pass only those that have
    one). Returns sorted id tuples, groups of one included: the caller
    decides that only len >= 2 groups earn a shared engine."""
    by_token: Dict[str, List[str]] = {}
    for model_id in sorted(featurizers):
        fitted = featurizers[model_id]
        try:
            token = featurize_token(fitted)
        except Exception:
            # an unfingerprintable chain can't PROVE it equals another,
            # so it never shares — same absent-not-broken posture as
            # the AOT store
            logger.info(
                "cse: featurize of %s not fingerprintable; hosting "
                "solo", model_id, exc_info=True,
            )
            token = f"_unhashable:{model_id}"
        by_token.setdefault(token, []).append(model_id)
    return sorted(
        tuple(ids) for ids in by_token.values()
    )


class SharedPrefixEngine(CompiledPipeline):
    """One engine serving a whole CSE group. ``heads`` maps model id
    -> fitted head pipeline; ``featurize`` is the group's (verified
    identical) fused prefix. Outputs are dicts keyed by model id, one
    entry per head, from one fused program per bucket."""

    def __init__(
        self,
        featurize,
        heads: Dict[str, Any],
        buckets: Sequence[int],
        **kwargs,
    ):
        if featurize is None:
            raise ValueError(
                "SharedPrefixEngine needs the shared featurize prefix"
            )
        if len(heads) < 1:
            raise ValueError("need at least one head")
        # deterministic head order: the traced program's output dict
        # (and therefore its cost model and any serialized form) must
        # not depend on dict insertion order at the call site
        self.heads = {mid: heads[mid] for mid in sorted(heads)}
        kwargs.pop("aot_store", None)  # see module docstring
        # param sharding binds ONE pipeline's params; the multi-head
        # program would need a per-head binder — host sharded models
        # solo instead of silently sharding only the primary head
        if kwargs.get("param_sharding"):
            raise ValueError(
                "SharedPrefixEngine does not compose with "
                "param_sharding; host sharded models solo"
            )
        super().__init__(
            pipeline=next(iter(self.heads.values())),
            buckets=buckets,
            featurize=featurize,
            aot_store=None,
            **kwargs,
        )
        # -- per-model attribution inputs (observability/attribution) --
        # row claims enqueued at submit time (by the zoo, or directly
        # when the engine is driven standalone), drained FIFO per
        # dispatched window; the zoo replaces this with a UNIT-level
        # queue shared across lanes
        self.claims = RowClaimQueue()
        # bucket -> (prefix_flops, {model: head_flops}): the fair-split
        # cost inputs, extracted best-effort at warmup
        self._split_costs: Dict[int, Tuple[float, Dict[str, float]]] = {}

    # -- attribution seams -------------------------------------------------

    def claim_rows(self, model_id: str, rows: float) -> None:
        """Declare that ``rows`` of upcoming window traffic belong to
        ``model_id``."""
        self.claims.claim(model_id, rows)

    def drain_claims(self, n_valid: float) -> Dict[str, float]:
        """Consume claims covering ``n_valid`` dispatched rows ->
        ``{model: rows}`` (see ``RowClaimQueue.drain``)."""
        return self.claims.drain(n_valid)

    def split_cost_model(
        self, bucket: int
    ) -> Optional[Tuple[float, Dict[str, float]]]:
        """``(prefix_flops, {model: head_flops})`` for one bucket
        program, or None where extraction failed (the binding degrades
        to pure row-share splitting)."""
        return self._split_costs.get(bucket)

    def _register_cost_model(
        self, bucket: int, fn, staged, want_executable: bool = False
    ):
        """On top of the whole-program cost model, extract the SPLIT
        one: the shared prefix lowered alone vs each head lowered over
        the prefix's output aval. Same best-effort contract — a backend
        reporting nothing leaves the split absent and attribution
        degrades to row share."""
        compiled = super()._register_cost_model(
            bucket, fn, staged, want_executable=want_executable
        )
        try:
            feat_run = self.featurize._batch_run
            prefix_model = device_obs.compiled_cost_model(
                jax.jit(feat_run).lower(staged)
            )
            prefix_flops = float(prefix_model.get("flops") or 0.0)
            feat_aval = jax.eval_shape(feat_run, staged)
            head_flops: Dict[str, float] = {}
            for mid, head in self.heads.items():
                head_model = device_obs.compiled_cost_model(
                    jax.jit(head._batch_run).lower(feat_aval)
                )
                head_flops[mid] = float(head_model.get("flops") or 0.0)
            if prefix_flops > 0 and any(head_flops.values()):
                self._split_costs[bucket] = (prefix_flops, head_flops)
        except Exception:
            logger.debug(
                "no split cost model for shared bucket %d", bucket,
                exc_info=True,
            )
        return compiled

    def _make_jit(self, bucket: int):
        feat_run = self.featurize._batch_run
        runs = {
            mid: head._batch_run for mid, head in self.heads.items()
        }
        metrics = self.metrics

        def staged(arr):
            # one trace-count per XLA compile of the whole group's
            # program — the bench's compile-counter gate reads this
            metrics.record_trace(bucket)
            feat = feat_run(arr)
            # the shared prefix is computed ONCE; every head consumes
            # the same activations inside the same program, so XLA can
            # fuse across all head boundaries too
            return {mid: run(feat) for mid, run in runs.items()}

        return jax.jit(
            staged, donate_argnums=(0,) if self.donate else ()
        )


__all__ = ["SharedPrefixEngine", "featurize_groups"]
