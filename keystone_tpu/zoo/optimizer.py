"""The placement optimizer: cost models + histograms -> PlacementPlan.

KeystoneML's planner chooses physical operators for a logical DAG from
cost models; this is the serving-plane analogue. Inputs per model
(one ``ModelProfile``):

- the observed (or expected) request-size histogram — what
  ``serving/autoscale.suggest_buckets`` turns into the padding-minimal
  bucket set;
- the per-bucket XLA cost models the engines extract at warmup
  (``ServingMetrics.cost_models``: modeled FLOPs per bucket program) —
  the demand weight that decides who gets spare lanes;
- ``params_nbytes`` — what one REPLICATED engine must hold per chip
  (``serving/sharding.params_nbytes``), checked against the per-chip
  HBM budget for the replicated-vs-mesh-sharded decision (the same
  check the PR 15 bench row hand-flagged).

Everything here is PURE and deterministic: same profiles + same budget
-> byte-identical plan, no jax, no device, no clock. The live side
(``ModelZoo.profiles()``) assembles profiles from running gateways;
``serve-gateway --zoo spec.json --optimize`` plans from the spec's
``expected_sizes`` hints before the first request arrives, and
``/planz`` reports this plan next to each pool's actual shape.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Mapping, Optional, Sequence, Tuple

from keystone_tpu.serving.autoscale import (
    predicted_efficiency,
    suggest_buckets,
)

# fraction of the per-chip HBM the planner lets ONE model's replicated
# params claim — headroom for activations, staging buffers, and the
# other co-hosted models
DEFAULT_PARAM_FRACTION = 0.8


@dataclasses.dataclass(frozen=True)
class ModelProfile:
    """One model's planning inputs. ``fallback_buckets`` serve when the
    histogram is empty (a cold model has no traffic to plan from)."""

    model_id: str
    histogram: Mapping[int, int] = dataclasses.field(
        default_factory=dict
    )
    cost_models: Mapping[int, Mapping[str, float]] = dataclasses.field(
        default_factory=dict
    )
    params_nbytes: int = 0
    fallback_buckets: Tuple[int, ...] = (8, 32, 128)
    pinned: bool = False


@dataclasses.dataclass(frozen=True)
class ChipBudget:
    """The hardware envelope the plan must fit. ``hbm_bytes`` is one
    chip's usable HBM (``observability/device.chip_hbm_bytes``, or
    ``$KEYSTONE_CHIP_HBM_BYTES``); None disables the sharding decision
    rather than fabricating a limit. ``lane_budget`` caps total lanes
    across the zoo (None = 2 per model, the single-model default)."""

    hbm_bytes: Optional[int] = None
    n_chips: int = 1
    lane_budget: Optional[int] = None
    param_fraction: float = DEFAULT_PARAM_FRACTION


@dataclasses.dataclass(frozen=True)
class ModelPlacement:
    model_id: str
    buckets: Tuple[int, ...]
    lanes: int
    sharded: bool
    params_nbytes: int
    demand_share: float
    predicted_efficiency: Optional[float]
    reason: str

    def to_dict(self) -> Dict[str, Any]:
        return {
            "model": self.model_id,
            "buckets": list(self.buckets),
            "lanes": self.lanes,
            "sharded": self.sharded,
            "params_nbytes": self.params_nbytes,
            "demand_share": round(self.demand_share, 4),
            "predicted_efficiency": (
                round(self.predicted_efficiency, 4)
                if self.predicted_efficiency is not None else None
            ),
            "reason": self.reason,
        }


@dataclasses.dataclass(frozen=True)
class PlacementPlan:
    placements: Tuple[ModelPlacement, ...]
    lane_budget: int
    hbm_budget_bytes: Optional[int]

    def placement_for(self, model_id: str) -> Optional[ModelPlacement]:
        for p in self.placements:
            if p.model_id == model_id:
                return p
        return None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "lane_budget": self.lane_budget,
            "hbm_budget_bytes": self.hbm_budget_bytes,
            "placements": [p.to_dict() for p in self.placements],
        }


def _flops_per_row(profile: ModelProfile) -> float:
    """Demand weight from the measured cost models: modeled FLOPs of
    the smallest bucket program divided by its rows. Falls back to 1.0
    (equal weight) when no cost model exists yet — a cold zoo plans on
    histogram mass alone."""
    best = None
    for bucket in sorted(profile.cost_models):
        flops = profile.cost_models[bucket].get("flops")
        if flops and bucket > 0:
            best = float(flops) / float(bucket)
            break
    return best if best is not None else 1.0


def _demand(profile: ModelProfile) -> float:
    """Row-weighted compute demand: histogram rows x modeled FLOPs per
    row. An empty histogram contributes the per-row weight alone, so a
    cold model still claims a share instead of zero."""
    rows = sum(
        int(size) * int(count)
        for size, count in profile.histogram.items()
    )
    return max(rows, 1) * _flops_per_row(profile)


def plan_placement(
    profiles: Sequence[ModelProfile],
    budget: ChipBudget,
    *,
    k: Optional[int] = None,
    max_bucket: Optional[int] = None,
) -> PlacementPlan:
    """The planner. Per model:

    - **buckets**: ``suggest_buckets`` (exact DP) over the histogram,
      capped at ``max_bucket`` (default: the model's largest fallback
      bucket); the fallback list verbatim when no histogram exists;
    - **replicated vs mesh-sharded**: sharded iff the replicated
      params exceed ``param_fraction`` of one chip's HBM AND the
      budget has a model axis to shard over (``n_chips > 1``) — the
      PR 15 decision, made from numbers instead of a flag. A sharded
      model gets ONE lane (each lane places its own param copy, so
      extra lanes would multiply HBM, not throughput);
    - **lanes**: the remaining lane budget split over replicated
      models proportional to demand (histogram rows x modeled
      FLOPs/row) by largest remainder — floor 1 per model, ties by
      model id, so the output is deterministic.

    Models are planned in sorted-id order and the result is a pure
    function of (profiles, budget, k, max_bucket)."""
    ordered = sorted(profiles, key=lambda p: p.model_id)
    if len({p.model_id for p in ordered}) != len(ordered):
        raise ValueError("duplicate model ids in profiles")
    lane_budget = (
        int(budget.lane_budget)
        if budget.lane_budget is not None
        else 2 * len(ordered)
    )
    if ordered and lane_budget < len(ordered):
        raise ValueError(
            f"lane budget {lane_budget} cannot give each of "
            f"{len(ordered)} models a lane"
        )
    param_budget = (
        int(budget.hbm_bytes * budget.param_fraction)
        if budget.hbm_bytes is not None else None
    )

    # -- per-model bucket choice + sharding decision -----------------------
    chosen: Dict[str, Dict[str, Any]] = {}
    for prof in ordered:
        cap = max_bucket or (
            max(prof.fallback_buckets)
            if prof.fallback_buckets else None
        )
        if prof.histogram:
            want_k = k if k is not None else max(
                1, len(prof.fallback_buckets)
            )
            buckets = suggest_buckets(
                prof.histogram, want_k, max_bucket=cap
            )
            eff = predicted_efficiency(prof.histogram, buckets)
        else:
            buckets = tuple(prof.fallback_buckets)
            eff = None
        over = (
            param_budget is not None
            and prof.params_nbytes > param_budget
        )
        if over and budget.n_chips > 1:
            sharded = True
            reason = (
                f"params {prof.params_nbytes}B exceed "
                f"{param_budget}B per-chip budget: mesh-sharded over "
                f"{budget.n_chips} chips, one lane"
            )
        elif over:
            sharded = False
            reason = (
                f"params {prof.params_nbytes}B exceed "
                f"{param_budget}B per-chip budget but n_chips=1: "
                "replicated (no model axis to shard over)"
            )
        else:
            sharded = False
            reason = (
                "params fit the per-chip budget: replicated"
                if param_budget is not None
                else "no HBM budget known: replicated"
            )
        chosen[prof.model_id] = {
            "buckets": buckets, "eff": eff,
            "sharded": sharded, "reason": reason,
        }

    # -- lane allocation over the shared budget ----------------------------
    sharded_ids = [
        p.model_id for p in ordered if chosen[p.model_id]["sharded"]
    ]
    replicated = [
        p for p in ordered if not chosen[p.model_id]["sharded"]
    ]
    spare = lane_budget - len(sharded_ids) - len(replicated)
    lanes: Dict[str, int] = {mid: 1 for mid in sharded_ids}
    lanes.update({p.model_id: 1 for p in replicated})
    demands = {p.model_id: _demand(p) for p in ordered}
    total_rep_demand = sum(demands[p.model_id] for p in replicated)
    if spare > 0 and replicated and total_rep_demand > 0:
        shares = [
            (
                p.model_id,
                spare * demands[p.model_id] / total_rep_demand,
            )
            for p in replicated
        ]
        granted = 0
        for mid, share in shares:
            lanes[mid] += int(share)
            granted += int(share)
        # largest remainder, ties broken by id: deterministic
        remainders = sorted(
            shares,
            key=lambda s: (-(s[1] - int(s[1])), s[0]),
        )
        for mid, _ in remainders[: spare - granted]:
            lanes[mid] += 1

    total_demand = sum(demands.values()) or 1.0
    placements = tuple(
        ModelPlacement(
            model_id=p.model_id,
            buckets=chosen[p.model_id]["buckets"],
            lanes=lanes[p.model_id],
            sharded=chosen[p.model_id]["sharded"],
            params_nbytes=int(p.params_nbytes),
            demand_share=demands[p.model_id] / total_demand,
            predicted_efficiency=chosen[p.model_id]["eff"],
            reason=chosen[p.model_id]["reason"],
        )
        for p in ordered
    )
    return PlacementPlan(
        placements=placements,
        lane_budget=lane_budget,
        hbm_budget_bytes=budget.hbm_bytes,
    )


def diff_plans(
    old: Optional[PlacementPlan], new: PlacementPlan
) -> Dict[str, Dict[str, Any]]:
    """What the ``new`` plan would CHANGE relative to ``old`` — the
    ``/driftz`` recommendation payload. Per model whose placement
    differs, each changed field as ``{"from": ..., "to": ...}``; models
    present on one side only diff against None. Pure like the planner:
    an empty dict means the re-plan confirmed the applied placement."""
    old_by = (
        {p.model_id: p for p in old.placements} if old is not None else {}
    )
    new_by = {p.model_id: p for p in new.placements}
    out: Dict[str, Dict[str, Any]] = {}
    for mid in sorted(set(old_by) | set(new_by)):
        a, b = old_by.get(mid), new_by.get(mid)
        if a is None or b is None:
            out[mid] = {
                "placement": {
                    "from": a.to_dict() if a is not None else None,
                    "to": b.to_dict() if b is not None else None,
                }
            }
            continue
        changes: Dict[str, Any] = {}
        for field, fa, fb in (
            ("buckets", list(a.buckets), list(b.buckets)),
            ("lanes", a.lanes, b.lanes),
            ("sharded", a.sharded, b.sharded),
        ):
            if fa != fb:
                changes[field] = {"from": fa, "to": fb}
        if changes:
            out[mid] = changes
    return out


__all__ = [
    "ChipBudget",
    "DEFAULT_PARAM_FRACTION",
    "ModelPlacement",
    "ModelProfile",
    "PlacementPlan",
    "diff_plans",
    "plan_placement",
]
