"""ModelSpec + ModelRegistry: the zoo's naming plane.

A ``ModelSpec`` is everything the zoo needs to host one named model:
a build callable (deferred — params materialize when the spec is
first hosted, not when the registry is assembled), bucket list, lane
count, SLO, optional device-side featurize and param sharding, and
the placement hints the optimizer reads (an expected request-size
histogram, pinning). The ``ModelRegistry`` is an insertion-ordered,
duplicate-rejecting id -> spec map with one DEFAULT model (bare
``/predict`` keeps serving it, so a single-model deployment upgrades
to a zoo without breaking its clients).

``load_zoo_spec`` parses the JSON file ``serve-gateway --zoo`` takes:

    {"models": [
        {"name": "alpha", "d": 64, "hidden": 128, "depth": 2,
         "seed": 1, "buckets": [8, 32], "lanes": 2, "default": true,
         "pinned": true, "slo_latency_ms": 250,
         "expected_sizes": {"1": 500, "8": 120}},
        {"name": "beta-flagship", "device_featurize": "flagship",
         "img": 34, "hidden": 64, "depth": 2, "buckets": [4, 8]}
    ]}

Each entry builds the same demo pipelines the bench/CLI stack already
serves (``serving/bench.build_pipeline``, ``serving/featurize``);
real deployments register their own fitted pipelines through the
Python API instead of the JSON shorthand.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import numpy as np

# a model id rides in URL paths (/predict/<model>), Prometheus label
# values, and AOT store namespaces — one conservative charset covers
# all three
_ID_RE = re.compile(r"[A-Za-z0-9][A-Za-z0-9_.-]{0,63}")


class UnknownModel(KeyError):
    """A model id the registry doesn't know. Carries the registered
    ids so the HTTP layer can return the typed 404 body without a
    second registry round-trip."""

    def __init__(self, model_id: str, registered: Tuple[str, ...]):
        self.model_id = model_id
        self.registered = tuple(registered)
        super().__init__(
            f"unknown model {model_id!r} (registered: "
            f"{', '.join(registered) or 'none'})"
        )


@dataclasses.dataclass(eq=False)
class BuiltModel:
    """What ``ModelSpec.build()`` returns: the fitted model head and
    (optionally) the fitted featurize chain fused in front of it. One
    callable returns both because they couple — the head's input dim
    IS the featurizer's output dim."""

    fitted: Any
    featurize: Any = None


@dataclasses.dataclass(eq=False)
class ModelSpec:
    """One named model's hosting contract.

    ``build`` runs when the model first pages in (and only then —
    registering a 100-model zoo must not materialize 100 parameter
    sets). ``expected_sizes`` seeds the placement optimizer before any
    live histogram exists; ``pinned`` exempts the model from LRU
    eviction AND its AOT entries from store GC."""

    model_id: str
    build: Callable[[], BuiltModel]
    buckets: Tuple[int, ...] = (8, 32, 128)
    lanes: int = 2
    input_dtype: Any = np.float32
    warmup_example: Any = None
    param_sharding: Any = None
    slo_latency_s: Optional[float] = None
    max_delay_ms: float = 5.0
    pipeline_depth: int = 2
    pinned: bool = False
    default: bool = False
    expected_sizes: Dict[int, int] = dataclasses.field(
        default_factory=dict
    )

    def __post_init__(self):
        if not _ID_RE.fullmatch(self.model_id or ""):
            raise ValueError(
                f"model id {self.model_id!r} must match "
                f"{_ID_RE.pattern} (it names URL routes, metric "
                "labels, and AOT namespaces)"
            )
        self.buckets = tuple(sorted(set(int(b) for b in self.buckets)))
        if not self.buckets or any(b <= 0 for b in self.buckets):
            raise ValueError(
                f"model {self.model_id}: buckets must be positive, "
                f"got {self.buckets}"
            )
        if self.lanes < 1:
            raise ValueError(
                f"model {self.model_id}: need at least one lane"
            )
        self.expected_sizes = {
            int(k): int(v) for k, v in self.expected_sizes.items()
        }


class ModelRegistry:
    """Insertion-ordered id -> ``ModelSpec`` map. The DEFAULT model —
    the first spec flagged ``default=True``, else the first registered
    — is what bare ``/predict`` serves."""

    def __init__(self, specs: Tuple[ModelSpec, ...] = ()):
        self._specs: Dict[str, ModelSpec] = {}
        for spec in specs:
            self.register(spec)

    def register(self, spec: ModelSpec) -> ModelSpec:
        if spec.model_id in self._specs:
            raise ValueError(
                f"model {spec.model_id!r} already registered"
            )
        if spec.default and any(
            s.default for s in self._specs.values()
        ):
            raise ValueError(
                f"model {spec.model_id!r}: a default model is already "
                "registered"
            )
        self._specs[spec.model_id] = spec
        return spec

    def get(self, model_id: str) -> ModelSpec:
        spec = self._specs.get(model_id)
        if spec is None:
            raise UnknownModel(model_id, self.ids())
        return spec

    def ids(self) -> Tuple[str, ...]:
        return tuple(self._specs)

    @property
    def default_id(self) -> Optional[str]:
        for spec in self._specs.values():
            if spec.default:
                return spec.model_id
        return next(iter(self._specs), None)

    def __len__(self) -> int:
        return len(self._specs)

    def __iter__(self) -> Iterator[ModelSpec]:
        return iter(self._specs.values())

    def __contains__(self, model_id: str) -> bool:
        return model_id in self._specs


# -- the serve-gateway --zoo JSON format -----------------------------------

def _entry_to_spec(entry: Dict[str, Any]) -> ModelSpec:
    import jax.numpy as jnp

    name = entry.get("name")
    if not name:
        raise ValueError(f"zoo spec entry missing 'name': {entry}")
    d = int(entry.get("d", 64))
    hidden = int(entry.get("hidden", 128))
    depth = int(entry.get("depth", 2))
    seed = int(entry.get("seed", 0))
    feat_kind = entry.get("device_featurize")
    img = int(entry.get("img", 16))
    if feat_kind not in (None, "demo", "flagship"):
        raise ValueError(
            f"model {name}: device_featurize must be 'demo' or "
            f"'flagship', got {feat_kind!r}"
        )

    def build() -> BuiltModel:
        # deferred imports: assembling a registry must not initialize
        # jax; params materialize at page-in
        from keystone_tpu.serving.bench import build_pipeline
        from keystone_tpu.serving.featurize import (
            build_featurize_pipeline,
            build_flagship_featurize_pipeline,
        )

        featurize = None
        model_d = d
        if feat_kind == "demo":
            featurize, model_d = build_featurize_pipeline(img=img)
        elif feat_kind == "flagship":
            featurize, model_d = build_flagship_featurize_pipeline(
                img=img
            )
        fitted = build_pipeline(
            d=model_d, hidden=hidden, depth=depth, seed=seed
        )
        return BuiltModel(fitted=fitted, featurize=featurize)

    if feat_kind is not None:
        warmup = jnp.zeros((img, img, 3), jnp.uint8)
        input_dtype = np.uint8
    else:
        warmup = jnp.zeros((d,), jnp.float32)
        input_dtype = np.float32
    slo_ms = entry.get("slo_latency_ms")
    return ModelSpec(
        model_id=str(name),
        build=build,
        buckets=tuple(entry.get("buckets", (8, 32, 128))),
        lanes=int(entry.get("lanes", 2)),
        input_dtype=input_dtype,
        warmup_example=warmup,
        param_sharding=(
            True if entry.get("shard_model") else None
        ),
        slo_latency_s=(
            float(slo_ms) / 1e3 if slo_ms is not None else None
        ),
        max_delay_ms=float(entry.get("max_delay_ms", 5.0)),
        pipeline_depth=int(entry.get("pipeline_depth", 2)),
        pinned=bool(entry.get("pinned", False)),
        default=bool(entry.get("default", False)),
        expected_sizes=dict(entry.get("expected_sizes", {})),
    )


def load_zoo_spec(path: str) -> ModelRegistry:
    """Parse a ``--zoo`` JSON spec file into a ``ModelRegistry``."""
    with open(path) as f:
        doc = json.load(f)
    models = doc.get("models")
    if not models:
        raise ValueError(f"zoo spec {path}: no 'models' entries")
    reg = ModelRegistry()
    for entry in models:
        reg.register(_entry_to_spec(entry))
    return reg


__all__ = [
    "BuiltModel",
    "ModelRegistry",
    "ModelSpec",
    "UnknownModel",
    "load_zoo_spec",
]
