"""ModelZoo: one process hosting many named models.

Each model (or cross-model CSE group — see ``zoo/cse.py``) is hosted
as one **unit**: a full ``Gateway`` (admission -> lanes -> micro-batch
-> engines) under the model's own name, its own bucket list, SLO, and
a per-model **AOT store namespace** (``aot.namespaced_store(model_id)``
— two models never share a cache slot, and the store GC accounts each
namespace separately). Lifecycle:

- **page-in** — a cold model's first request (or an explicit
  ``host()``) builds its artifacts and gateway OUTSIDE the zoo's
  resident lock — the same build-outside-lock discipline as the warm
  pool — and publishes the unit atomically; concurrent requesters
  wait on the build instead of duplicating it.
- **LRU resident cap** — ``max_resident`` bounds how many models hold
  compiled engines + device residency at once; exceeding it evicts
  the least-recently-used unpinned unit, whose gateway DRAINS ON A
  BACKGROUND THREAD — paging model B in never stalls model A's
  in-flight windows, and vice versa.
- **pinning** — ``ModelSpec.pinned`` exempts a model from eviction
  (and seeds the AOT GC's pinned set).
- **cross-model CSE** — models hosted together whose featurize
  ``pipeline_token``s match are fused into ONE shared-prefix unit:
  one engine computes the prefix once per window and fans activations
  to every member head (grouping is decided per ``host()`` call — a
  later solo page-in doesn't silently re-plumb a running unit).

Zoo-level metrics ride the ``model`` label:
``keystone_zoo_resident{model}``, ``keystone_zoo_pageins_total{model}``,
``keystone_zoo_evictions_total{model}`` — next to each unit's normal
gateway/engine families under its own gateway name.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from concurrent.futures import CancelledError, Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

from keystone_tpu.gateway.lifecycle import Gateway
from keystone_tpu.observability.attribution import (
    AttributionLedger,
    EngineAttribution,
    RowClaimQueue,
    attribution_document,
)
from keystone_tpu.observability.drift import DriftDetector
from keystone_tpu.serving import aot as aot_lib
from keystone_tpu.zoo.cse import SharedPrefixEngine, featurize_groups
from keystone_tpu.zoo.optimizer import (
    ChipBudget,
    ModelProfile,
    PlacementPlan,
    diff_plans,
    plan_placement,
)
from keystone_tpu.zoo.registry import (
    BuiltModel,
    ModelRegistry,
    ModelSpec,
    UnknownModel,
)

logger = logging.getLogger(__name__)


def _chain(parent: Future, fn) -> Future:
    """A future resolving to ``fn(parent.result())`` — how a shared
    unit's per-model view is carved out of its dict output. Cancelling
    the view is best-effort only (the underlying window request keeps
    its slot, same as any coalesced request)."""
    out: Future = Future()

    def done(f: Future) -> None:
        try:
            result = f.result()
        except CancelledError:
            out.cancel()
        except Exception as e:
            try:
                out.set_exception(e)
            except Exception:
                pass  # view cancelled concurrently
        else:
            try:
                out.set_result(fn(result))
            except Exception as e:
                try:
                    out.set_exception(e)
                except Exception:
                    pass

    parent.add_done_callback(done)
    return out


class _Unit:
    """One hosted gateway serving one model or one CSE group."""

    def __init__(
        self,
        ids: Tuple[str, ...],
        gateway: Gateway,
        shared: bool,
        pinned: bool,
        claims: Optional[RowClaimQueue] = None,
    ):
        self.ids = ids
        self.gateway = gateway
        self.shared = shared
        self.pinned = pinned
        # shared units: the unit-level row-claim queue every lane
        # engine's attribution binding drains from
        self.claims = claims
        # LRU stamp. The owning ModelZoo holds ITS lock around every
        # touch()/read — the lock lives on the zoo, not this unit, so
        # the contract is prose rather than a guarded-by annotation.
        self.last_used = time.monotonic()

    def touch(self) -> None:
        self.last_used = time.monotonic()


class ModelZoo:
    """The multi-model host. ``registry`` names the models; ``plan``
    (a ``PlacementPlan``) overrides each spec's buckets/lanes/sharding
    with the optimizer's choices; ``max_resident`` caps how many
    models hold engines at once (None = all); ``cse=False`` disables
    shared-prefix fusion (every model solo)."""

    def __init__(
        self,
        registry: ModelRegistry,
        *,
        max_resident: Optional[int] = None,
        plan: Optional[PlacementPlan] = None,
        cse: bool = True,
        aot_namespaces: bool = True,
        metrics_registry=None,
    ):
        if len(registry) == 0:
            raise ValueError("zoo needs at least one model spec")
        if max_resident is not None and max_resident < 1:
            raise ValueError("max_resident must be >= 1")
        self.registry = registry
        self.plan = plan
        self.max_resident = max_resident
        self._cse = cse
        self._aot_namespaces = aot_namespaces
        self._lock = threading.Lock()
        self._units: Dict[Tuple[str, ...], _Unit] = {}
        self._by_model: Dict[str, _Unit] = {}  # guarded-by: _lock
        self._building: Dict[str, threading.Event] = {}
        self._artifacts: Dict[str, BuiltModel] = {}
        self._artifacts_lock = threading.Lock()
        self._closed = False
        # optional per-model online-lifecycle plane (attach_lifecycle)
        self.lifecycle = None
        # the budget the applied plan was planned under (apply_plan /
        # --optimize) — what the drift audit re-plans against
        self.plan_budget: Optional[ChipBudget] = None
        from keystone_tpu.observability.registry import (
            get_global_registry,
        )

        reg = (
            metrics_registry if metrics_registry is not None
            else get_global_registry()
        )
        # the attribution & drift plane: every unit's engines charge
        # the per-model cost ledger (keystone_attr_*{model}), and the
        # drift detector scores live request-size mixtures against the
        # applied plan's baselines (keystone_drift_score{model})
        self.attribution = AttributionLedger()
        self.attribution.register(reg)
        self.drift = DriftDetector()
        self.drift.register(reg)
        self._resident_g = reg.gauge(
            "keystone_zoo_resident",
            "1 when the model currently holds compiled engines "
            "(paged in), 0 after eviction",
            ("model",),
        )
        self._pageins_c = reg.counter(
            "keystone_zoo_pageins_total",
            "cold-model page-ins (gateway build + warm through the "
            "build-outside-lock path)",
            ("model",),
        )
        self._evictions_c = reg.counter(
            "keystone_zoo_evictions_total",
            "LRU resident-cap evictions (the gateway drains on a "
            "background thread)",
            ("model",),
        )
        for model_id in registry.ids():
            self._resident_g.set(0.0, (model_id,))

    # -- artifacts ---------------------------------------------------------

    def _built(self, model_id: str) -> BuiltModel:
        """Build (once) and cache a model's fitted artifacts. Params
        on host are the cheap half; engines/compiles are what the
        resident cap governs."""
        with self._artifacts_lock:
            built = self._artifacts.get(model_id)
            if built is None:
                spec = self.registry.get(model_id)
                built = spec.build()
                self._artifacts[model_id] = built
            return built

    # -- hosting -----------------------------------------------------------

    def host(
        self, model_ids: Optional[Sequence[str]] = None
    ) -> List[Tuple[str, ...]]:
        """Page in a set of models together (default: every registered
        model). Models paged in by the same call are CSE-grouped —
        identical featurize tokens fuse into one shared-prefix unit.
        Returns the hosted unit id-tuples."""
        want = [
            mid for mid in (model_ids or self.registry.ids())
            if mid not in self._by_model
        ]
        for mid in want:
            self.registry.get(mid)  # raise UnknownModel before building
        groups: List[Tuple[str, ...]] = []
        if self._cse and len(want) > 1:
            featurizers = {}
            for mid in want:
                built = self._built(mid)
                if built.featurize is not None:
                    featurizers[mid] = built.featurize
            grouped = set()
            for group in featurize_groups(featurizers):
                if len(group) >= 2:
                    groups.append(group)
                    grouped.update(group)
            groups.extend(
                (mid,) for mid in want if mid not in grouped
            )
        else:
            groups = [(mid,) for mid in want]
        hosted = []
        for group in groups:
            hosted.append(self._ensure_resident(group[0], group))
        return [u.ids for u in hosted]

    def gateway_for(self, model_id: str) -> Gateway:
        """The model's live gateway (pages it in solo if cold)."""
        return self._ensure_resident(model_id).gateway

    def resolve(
        self, model_id: Optional[str] = None
    ) -> Tuple[str, ModelSpec]:
        """Route-time lookup: the effective model id (default when
        None) and its spec. Raises ``UnknownModel`` with the
        registered ids — the HTTP layer's typed-404 payload."""
        mid = model_id or self.registry.default_id
        return mid, self.registry.get(mid)

    def _ensure_resident(
        self,
        model_id: str,
        group: Optional[Tuple[str, ...]] = None,
    ) -> _Unit:
        while True:
            with self._lock:
                if self._closed:
                    raise RuntimeError("ModelZoo is closed")
                unit = self._by_model.get(model_id)
                if unit is not None:
                    unit.touch()
                    return unit
                ev = self._building.get(model_id)
                if ev is None:
                    ev = threading.Event()
                    for mid in group or (model_id,):
                        self._building[mid] = ev
                    builder = True
                else:
                    builder = False
            if not builder:
                # another request is building this model: wait for the
                # publish instead of compiling a duplicate generation
                ev.wait()
                continue
            try:
                unit = self._build_unit(group or (model_id,))
                with self._lock:
                    self._units[unit.ids] = unit
                    for mid in unit.ids:
                        self._by_model[mid] = unit
                for mid in unit.ids:
                    self._pageins_c.inc((mid,))
                    self._resident_g.set(1.0, (mid,))
                logger.info(
                    "zoo: paged in %s (%s)",
                    "+".join(unit.ids),
                    "shared-prefix" if unit.shared else "solo",
                )
                self._enforce_cap(keep=unit)
                return unit
            finally:
                with self._lock:
                    for mid in group or (model_id,):
                        if self._building.get(mid) is ev:
                            del self._building[mid]
                ev.set()

    def _placement_kwargs(self, spec: ModelSpec) -> Dict[str, Any]:
        """Spec hosting parameters, overridden by the optimizer's plan
        when one was applied."""
        buckets = spec.buckets
        lanes = spec.lanes
        param_sharding = spec.param_sharding
        if self.plan is not None:
            placement = self.plan.placement_for(spec.model_id)
            if placement is not None:
                buckets = placement.buckets
                lanes = placement.lanes
                if placement.sharded and param_sharding is None:
                    # the plan's budget check says replicated params
                    # don't fit: shard with the default rule set
                    param_sharding = True
                elif not placement.sharded:
                    param_sharding = None
        return {
            "buckets": buckets,
            "lanes": lanes,
            "param_sharding": param_sharding,
        }

    def _aot_store_for(self, model_id: str):
        if not self._aot_namespaces:
            return "auto"
        store = aot_lib.namespaced_store(model_id)
        # None (no store dir configured) must mean OFF, not "auto" —
        # auto would fall back to the process store and put two
        # models' entries in one undifferentiated namespace
        return store if store is not None else None

    def _build_unit(self, ids: Tuple[str, ...]) -> _Unit:
        """Build one unit's gateway — engines compiled and warmed —
        entirely outside the zoo's resident lock."""
        specs = [self.registry.get(mid) for mid in ids]
        pinned = any(s.pinned for s in specs)
        if len(ids) == 1:
            spec = specs[0]
            built = self._built(spec.model_id)
            place = self._placement_kwargs(spec)
            gw = Gateway(
                built.fitted,
                buckets=place["buckets"],
                n_lanes=place["lanes"],
                max_delay_ms=spec.max_delay_ms,
                warmup_example=spec.warmup_example,
                pipeline_depth=spec.pipeline_depth,
                device_featurize=built.featurize,
                param_sharding=place["param_sharding"],
                aot_store=self._aot_store_for(spec.model_id),
                name=spec.model_id,
                slo_latency_s=spec.slo_latency_s,
            )
            for lane in gw.pool.lanes:
                lane.engine.metrics.attach_attribution(
                    EngineAttribution(self.attribution, ids)
                )
            return _Unit(ids, gw, shared=False, pinned=pinned)
        # -- shared-prefix unit (CSE group) ----------------------------
        builts = {mid: self._built(mid) for mid in ids}
        featurize = builts[ids[0]].featurize
        heads = {mid: b.fitted for mid, b in builts.items()}
        # the group serves every member's traffic: union buckets, the
        # widest lane ask, the tightest SLO and coalesce delay
        buckets = tuple(sorted(set(
            b
            for s in specs
            for b in self._placement_kwargs(s)["buckets"]
        )))
        lanes = max(
            self._placement_kwargs(s)["lanes"] for s in specs
        )
        slos = [
            s.slo_latency_s for s in specs
            if s.slo_latency_s is not None
        ]
        name = "+".join(ids)

        def engine_factory(eng_buckets):
            def factory(lane_name: str):
                return SharedPrefixEngine(
                    featurize, heads, eng_buckets, name=lane_name
                )

            return factory

        gw = Gateway(
            heads[ids[0]],
            buckets=buckets,
            n_lanes=lanes,
            max_delay_ms=min(s.max_delay_ms for s in specs),
            warmup_example=specs[0].warmup_example,
            pipeline_depth=min(s.pipeline_depth for s in specs),
            engine_factory=engine_factory,
            name=name,
            slo_latency_s=min(slos) if slos else None,
        )
        # fair-split attribution: one UNIT-level claim queue shared by
        # every lane, each lane's shared engine bound with its own
        # prefix/head split cost model
        claims = RowClaimQueue()
        for lane in gw.pool.lanes:
            engine = lane.engine
            split_fn = getattr(engine, "split_cost_model", None)
            lane.engine.metrics.attach_attribution(
                EngineAttribution(
                    self.attribution, ids,
                    shares_fn=claims.drain,
                    split_cost_fn=split_fn,
                )
            )
        return _Unit(ids, gw, shared=True, pinned=pinned, claims=claims)

    # -- LRU eviction ------------------------------------------------------

    def _enforce_cap(self, keep: Optional[_Unit] = None) -> None:
        if self.max_resident is None:
            return
        to_evict: List[_Unit] = []
        with self._lock:
            resident = sum(len(u.ids) for u in self._units.values())
            candidates = sorted(
                (
                    u for u in self._units.values()
                    if not u.pinned and u is not keep
                ),
                key=lambda u: u.last_used,
            )
            for unit in candidates:
                if resident <= self.max_resident:
                    break
                del self._units[unit.ids]
                for mid in unit.ids:
                    del self._by_model[mid]
                resident -= len(unit.ids)
                to_evict.append(unit)
        for unit in to_evict:
            for mid in unit.ids:
                self._evictions_c.inc((mid,))
                self._resident_g.set(0.0, (mid,))
            logger.info(
                "zoo: evicting %s (LRU over max_resident=%d)",
                "+".join(unit.ids), self.max_resident,
            )
            # drain on a background thread: eviction is bookkeeping
            # for the pager, and model B's page-in must never block on
            # model A's in-flight windows
            threading.Thread(
                target=unit.gateway.close,
                name=f"keystone-zoo-evict-{unit.ids[0]}",
                daemon=True,
            ).start()

    def evict(self, model_id: str) -> bool:
        """Explicitly drop one model's unit (drains in background).
        Pinned models evict too when asked by name — the pin guards
        against LRU pressure, not operators."""
        with self._lock:
            unit = self._by_model.get(model_id)
            if unit is None:
                return False
            del self._units[unit.ids]
            for mid in unit.ids:
                del self._by_model[mid]
        for mid in unit.ids:
            self._evictions_c.inc((mid,))
            self._resident_g.set(0.0, (mid,))
        threading.Thread(
            target=unit.gateway.close,
            name=f"keystone-zoo-evict-{unit.ids[0]}",
            daemon=True,
        ).start()
        return True

    # -- serving -----------------------------------------------------------

    def predict(
        self,
        example: Any,
        model_id: Optional[str] = None,
        deadline_ms: Optional[float] = None,
        trace_id: Optional[str] = None,
    ) -> Future:
        """Admit one example to one model (the default when
        ``model_id`` is None). Resolves to THAT model's output — a
        shared-prefix unit's dict result is carved down to the
        requested member. Raises ``UnknownModel`` / ``Overloaded``
        synchronously like ``Gateway.predict``."""
        mid, _spec = self.resolve(model_id)
        unit = self._ensure_resident(mid)
        if unit.shared and unit.claims is not None:
            # claim BEFORE submit: the window this example coalesces
            # into drains its membership from the same FIFO
            unit.claims.claim(mid, 1)
        fut = unit.gateway.predict(
            example, deadline_ms=deadline_ms, trace_id=trace_id
        )
        if not unit.shared:
            return fut
        return _chain(fut, lambda out: out[mid])

    def predict_many(
        self,
        example: Any,
        model_ids: Optional[Sequence[str]] = None,
        deadline_ms: Optional[float] = None,
    ) -> Future:
        """Fan one example out to several models (default: all) —
        resolves to ``{model_id: output}``. The example must be a
        valid input for EVERY target model (fan-out is an ensemble of
        same-schema models, not a broadcast across unrelated ones).
        Models co-hosted in one shared-prefix unit cost ONE window
        slot and one featurize; solo models are admitted
        independently and the results are joined. This is the
        ensemble/shadow path the CSE plane optimizes."""
        want = tuple(model_ids or self.registry.ids())
        for mid in want:
            self.registry.get(mid)
        by_unit: Dict[Tuple[str, ...], List[str]] = {}
        for mid in want:
            unit = self._ensure_resident(mid)
            by_unit.setdefault(unit.ids, []).append(mid)
        parts: List[Tuple[List[str], bool, Future]] = []
        for unit_ids, members in by_unit.items():
            unit = self._units.get(unit_ids) or self._by_model[
                members[0]
            ]
            if unit.shared and unit.claims is not None:
                # one window row serves every requested member: its
                # ownership splits evenly among them (the shared
                # prefix's fair-split input)
                share = 1.0 / len(members)
                for mid in members:
                    unit.claims.claim(mid, share)
            fut = unit.gateway.predict(
                example, deadline_ms=deadline_ms
            )
            parts.append((members, unit.shared, fut))
        out: Future = Future()
        combined: Dict[str, Any] = {}
        pending = [len(parts)]
        plock = threading.Lock()

        def arm(members: List[str], shared: bool):
            def done(f: Future) -> None:
                try:
                    result = f.result()
                except Exception as e:
                    try:
                        out.set_exception(e)
                    except Exception:
                        pass
                    return
                with plock:
                    for mid in members:
                        combined[mid] = (
                            result[mid] if shared else result
                        )
                    pending[0] -= 1
                    finished = pending[0] == 0
                if finished:
                    try:
                        out.set_result(dict(combined))
                    except Exception:
                        pass

            return done

        for members, shared, fut in parts:
            fut.add_done_callback(arm(members, shared))
        return out

    @property
    def ready(self) -> bool:
        """At least one unit resident and every resident unit
        admitting — the zoo-level ``/readyz`` signal."""
        with self._lock:
            units = list(self._units.values())
        return bool(units) and all(u.gateway.ready for u in units)

    def total_load(self) -> int:
        """Queued + in-lane requests across every resident unit — the
        zoo's ``X-Keystone-Load`` routing-load number."""
        with self._lock:
            units = list(self._units.values())
        return sum(
            u.gateway.admission.queue_depth
            + u.gateway.pool.total_load()
            for u in units
        )

    def rebucket(self, force: bool = False) -> Dict[str, bool]:
        """One lifecycle iteration on every resident unit (``/swap``
        in zoo mode). Returns ``{unit-name: swapped}``."""
        with self._lock:
            units = list(self._units.values())
        return {
            "+".join(u.ids): u.gateway.rebucket(force=force)
            for u in units
        }

    # -- attribution & drift plane -----------------------------------------

    def apply_plan(
        self,
        plan: PlacementPlan,
        budget: Optional[ChipBudget] = None,
        profiles: Optional[Sequence[ModelProfile]] = None,
    ) -> None:
        """Adopt a placement plan: future page-ins use its placements,
        the budget is retained for the drift audit's re-plan, and each
        planning profile's histogram is pinned as that model's DRIFT
        BASELINE — the distribution the plan assumed, which is exactly
        what live traffic is scored against."""
        self.plan = plan
        self.plan_budget = budget
        for prof in profiles or ():
            if prof.histogram:
                self.drift.set_baseline(prof.model_id, prof.histogram)

    def observe_request(
        self, model_id: Optional[str], size: int
    ) -> None:
        """Feed one live request's row count into the drift detector
        (the HTTP frontend calls this per /predict request with its
        instance count; benches call it directly)."""
        try:
            mid, _spec = self.resolve(model_id)
        except UnknownModel:
            return
        self.drift.observe(mid, size)

    def live_profiles(self) -> List[ModelProfile]:
        """Planning profiles with each histogram replaced by the drift
        window's LIVE one (where live observations exist) — the
        re-plan-on-drift input."""
        out = []
        for prof in self.profiles():
            live = self.drift.live_histogram(prof.model_id)
            if live:
                prof = dataclasses.replace(prof, histogram=live)
            out.append(prof)
        return out

    def _refresh_staging(self) -> None:
        """Point-in-time per-model staging bytes: each unit's live
        host staging pools (absent until a pipelined lane ran), split
        evenly over the unit's members."""
        with self._lock:
            units = list(self._units.values())
        seen = set()
        for unit in units:
            total = 0
            have = False
            for lane in unit.gateway.pool.lanes:
                nbytes = lane.engine.metrics.staging_bytes
                if nbytes is not None:
                    total += nbytes
                    have = True
            share = total / len(unit.ids) if have else None
            for mid in unit.ids:
                self.attribution.set_staging_bytes(mid, share)
                seen.add(mid)
        for mid in self.registry.ids():
            if mid not in seen:
                self.attribution.set_staging_bytes(mid, None)

    def attributionz(self, top_k: int = 10) -> Dict[str, Any]:
        """The ``GET /attributionz`` document: the per-model ledger
        with device-seconds shares, normalized unit cost, and the
        top-k spender table."""
        self._refresh_staging()
        return attribution_document(self.attribution, top_k=top_k)

    def driftz(self) -> Dict[str, Any]:
        """The ``GET /driftz`` document: per-model PSI scores plus —
        once any model crossed the threshold and a plan is applied —
        the audit: ``plan_placement`` re-run on the LIVE profiles and
        the diff of what would change. Recommendation-only; nothing is
        auto-applied."""
        doc = self.drift.document()
        doc["plan_applied"] = self.plan is not None
        recommendation = None
        if doc["drifted"] and self.plan is not None:
            budget = self.plan_budget or ChipBudget(
                hbm_bytes=self.plan.hbm_budget_bytes,
                lane_budget=self.plan.lane_budget,
            )
            try:
                proposed = plan_placement(self.live_profiles(), budget)
                recommendation = {
                    "note": (
                        "recommendation only — re-plan is never "
                        "auto-applied"
                    ),
                    "changes": diff_plans(self.plan, proposed),
                    "proposed_plan": proposed.to_dict(),
                }
            except Exception as e:  # audit must not 500 /driftz
                recommendation = {"error": str(e)}
        doc["recommendation"] = recommendation
        return doc

    # -- planning inputs + status ------------------------------------------

    def profiles(self, build: bool = False) -> List[ModelProfile]:
        """Assemble the optimizer's inputs from live state: observed
        request-size histograms and warmup-extracted cost models for
        resident models, the spec's ``expected_sizes`` hint otherwise.
        ``params_nbytes`` is measured off built artifacts
        (``build=True`` forces building cold models' params — what
        ``--optimize`` does at plan time)."""
        from keystone_tpu.serving.sharding import (
            named_params,
            params_nbytes,
        )

        profiles = []
        for spec in self.registry:
            with self._lock:
                unit = self._by_model.get(spec.model_id)
            hist: Dict[int, int] = dict(spec.expected_sizes)
            cost: Dict[int, Dict[str, float]] = {}
            if unit is not None:
                live = unit.gateway.observed_sizes()
                if live:
                    hist = live
                for lane in unit.gateway.pool.lanes:
                    for b, m in lane.engine.metrics.cost_models.items():
                        cost.setdefault(b, dict(m))
            nbytes = 0
            if build or spec.model_id in self._artifacts:
                try:
                    fitted = self._built(spec.model_id).fitted
                    nbytes = params_nbytes(named_params(fitted))
                except Exception:
                    logger.info(
                        "zoo: could not size %s params",
                        spec.model_id, exc_info=True,
                    )
            profiles.append(
                ModelProfile(
                    model_id=spec.model_id,
                    histogram=hist,
                    cost_models=cost,
                    params_nbytes=nbytes,
                    fallback_buckets=spec.buckets,
                    pinned=spec.pinned,
                )
            )
        return profiles

    def planz(self) -> Dict[str, Any]:
        """The ``/planz`` document: the applied plan (None when the
        zoo runs on spec flags) next to every model's ACTUAL shape —
        resident or cold, lanes/buckets served, shared-prefix
        membership."""
        with self._lock:
            units = {u.ids: u for u in self._units.values()}
        actual: Dict[str, Any] = {}
        for spec in self.registry:
            row: Dict[str, Any] = {
                "resident": False,
                "pinned": spec.pinned,
                "spec_buckets": list(spec.buckets),
                "spec_lanes": spec.lanes,
            }
            for ids, unit in units.items():
                if spec.model_id in ids:
                    row.update(
                        resident=True,
                        shared_with=[
                            m for m in ids if m != spec.model_id
                        ],
                        **unit.gateway.pool.status(),
                    )
                    break
            actual[spec.model_id] = row
        return {
            "default_model": self.registry.default_id,
            "max_resident": self.max_resident,
            "plan": (
                self.plan.to_dict() if self.plan is not None else None
            ),
            "actual": actual,
        }

    # -- online lifecycle --------------------------------------------------

    def attach_lifecycle(self, manager) -> None:
        """Adopt a ``LifecycleManager`` whose controllers drive this
        zoo's per-model gateways. The HTTP frontend resolves its
        lifecycle surface (``/feedback/<model>``, ``/lifecyclez``)
        through this attribute in zoo mode, so per-model streaming
        refit works identically with many resident models. NOTE:
        controllers only work over SOLO units — a model in a
        cross-model CSE group serves through a shared engine the
        lifecycle cannot rebuild from one fitted
        (``Gateway.swap_model`` raises on those)."""
        self.lifecycle = manager

    def lifecycle_status(self) -> Optional[Dict[str, Any]]:
        """The attached manager's ``/lifecyclez`` document (None when
        no lifecycle plane is attached)."""
        return (
            self.lifecycle.status()
            if self.lifecycle is not None else None
        )

    # -- shutdown ----------------------------------------------------------

    def close(self, timeout: Optional[float] = 10.0) -> None:
        """Drain every unit concurrently (one slow model must not
        serialize the others' drains behind it)."""
        if self.lifecycle is not None:
            # the refit/tick plane dies first: a tick mid-drain would
            # race swap_model against the unit drains below
            try:
                self.lifecycle.close()
            except Exception:
                logger.exception("zoo lifecycle close failed")
        with self._lock:
            if self._closed:
                units = []
            else:
                self._closed = True
                units = list(self._units.values())
                self._units.clear()
                self._by_model.clear()
        threads = [
            threading.Thread(
                target=u.gateway.close, kwargs={"timeout": timeout},
                name=f"keystone-zoo-close-{u.ids[0]}", daemon=True,
            )
            for u in units
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout)
        for u in units:
            for mid in u.ids:
                self._resident_g.set(0.0, (mid,))

    def __enter__(self) -> "ModelZoo":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


__all__ = ["ModelZoo", "UnknownModel"]
