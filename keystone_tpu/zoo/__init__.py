"""Model-zoo serving plane: many named models behind one port.

The paper's query-optimizer ideas pointed at serving (ROADMAP
"multi-model serving with a cost-based placement optimizer"):

- ``zoo/registry.py`` — ``ModelSpec``/``ModelRegistry``: named model
  specs (pipeline factory, buckets, lanes, SLO, optional featurize/
  sharding) plus the JSON spec format ``serve-gateway --zoo`` loads.
- ``zoo/host.py`` — ``ModelZoo``: hosts one ``Gateway`` per model (or
  per CSE group) with per-model AOT store namespaces, LRU resident-set
  paging with pinning, and ``model``-labeled zoo metrics.
- ``zoo/optimizer.py`` — the pure placement planner: per-bucket XLA
  cost models + request-size histograms + the per-chip HBM budget in,
  ``PlacementPlan`` (buckets / lanes / replicated-vs-sharded) out.
- ``zoo/cse.py`` — cross-model featurize CSE: co-hosted models whose
  fused featurize chains carry identical ``pipeline_token``s share ONE
  multi-head engine that computes the prefix once per window.
"""

from keystone_tpu.zoo.cse import SharedPrefixEngine, featurize_groups
from keystone_tpu.zoo.host import ModelZoo
from keystone_tpu.zoo.optimizer import (
    ChipBudget,
    ModelPlacement,
    ModelProfile,
    PlacementPlan,
    plan_placement,
)
from keystone_tpu.zoo.registry import (
    BuiltModel,
    ModelRegistry,
    ModelSpec,
    UnknownModel,
    load_zoo_spec,
)

__all__ = [
    "BuiltModel",
    "ChipBudget",
    "ModelPlacement",
    "ModelProfile",
    "ModelRegistry",
    "ModelSpec",
    "ModelZoo",
    "PlacementPlan",
    "SharedPrefixEngine",
    "UnknownModel",
    "featurize_groups",
    "load_zoo_spec",
    "plan_placement",
]
