"""Compare two bench rounds: ``python -m keystone_tpu bench-diff
A.json B.json``.

Both inputs are bench-round artifacts — either the JSONL the bench
binaries print (one ``{"metric":..., "value":..., "unit":...}`` object
per line) or a JSON array of those rows (the driver's
``BENCH_r{N}.json``). The diff walks the headline metric of every row
present in BOTH rounds and flags regressions beyond a per-row
tolerance, exiting nonzero when any row regressed (or vanished) — the
CI shape: ``bin/bench-diff last-green.json this-round.json``.

Direction is inferred from the row's ``unit``: latency-like units
(``ms``, ``s``, ``seconds``) regress UPWARD, rate-like units
(``examples/sec``, ``x``, ``rate``, ``tflops``, efficiency/fraction
units) regress DOWNWARD, and units this table can't classify are
reported but never gated (a diff that guessed directions would
manufacture red rounds). Tolerance resolution per row: an explicit
``--set metric=tol`` override, else the row's own ``"tolerance"``
field when the emitter embedded one, else ``--tolerance`` when given,
else the unit class's default (latency rows jitter more than counter
rows and get more slack).

stdlib-only by design, like ``analysis/``: the diff must run in CI
hooks without paying the jax import.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

# unit -> (direction, default tolerance); direction is which way a
# REGRESSION moves: "up" = bigger is worse, "down" = smaller is worse
_LOWER_IS_BETTER = {
    "ms": 0.15,  # p99/latency rows: scheduler jitter needs slack
    "s": 0.15,
    "seconds": 0.15,
    "ms_to_first_predict": 0.15,
    "psi": 0.25,  # drift scores wander with the sampled mixture
    "bytes": 0.05,
}
_HIGHER_IS_BETTER = {
    "examples/sec": 0.10,
    "imgs/sec": 0.10,
    "examples/sec/chip": 0.10,
    "x": 0.10,  # speedups
    "rate": 0.05,
    "tflops": 0.10,
    "padding_efficiency": 0.05,
    "fraction": 0.05,
    "accuracy": 0.02,
}


def classify(unit: str) -> Optional[Tuple[str, float]]:
    """``(direction, default_tolerance)`` for a unit, or None when the
    unit carries no comparable direction (``skipped``, ad-hoc units)."""
    if unit in _LOWER_IS_BETTER:
        return "up", _LOWER_IS_BETTER[unit]
    if unit in _HIGHER_IS_BETTER:
        return "down", _HIGHER_IS_BETTER[unit]
    return None


def load_rows(path: str) -> Dict[str, Dict]:
    """One row per metric from a bench artifact: JSONL, a JSON array,
    or ``{"rows": [...]}``. Later duplicates of a metric are ignored —
    same rule as the emitters' one-row-per-metric guard."""
    with open(path) as fh:
        text = fh.read()
    rows: List[Dict] = []
    stripped = text.lstrip()
    if stripped.startswith("[") or stripped.startswith("{"):
        try:
            doc = json.loads(text)
        except json.JSONDecodeError:
            doc = None
        if isinstance(doc, list):
            rows = [r for r in doc if isinstance(r, dict)]
        elif isinstance(doc, dict) and isinstance(doc.get("rows"), list):
            rows = [r for r in doc["rows"] if isinstance(r, dict)]
    if not rows:  # JSONL (possibly with non-JSON log lines interleaved)
        for line in text.splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(row, dict):
                rows.append(row)
    out: Dict[str, Dict] = {}
    for row in rows:
        metric = row.get("metric")
        if isinstance(metric, str) and metric not in out:
            out[metric] = row
    return out


def diff_rows(
    old: Dict[str, Dict],
    new: Dict[str, Dict],
    *,
    tolerance: Optional[float] = None,
    overrides: Optional[Dict[str, float]] = None,
) -> List[Dict]:
    """One verdict entry per metric seen in either round, sorted with
    regressions first."""
    overrides = overrides or {}
    entries: List[Dict] = []
    for metric in sorted(set(old) | set(new)):
        a, b = old.get(metric), new.get(metric)
        entry: Dict = {"metric": metric}
        if a is None:
            entry.update(verdict="new", new=b.get("value"),
                         unit=b.get("unit"))
            entries.append(entry)
            continue
        if b is None:
            entry.update(verdict="vanished", old=a.get(
                "value"), unit=a.get("unit"))
            entries.append(entry)
            continue
        va, vb = a.get("value"), b.get("value")
        unit = b.get("unit") or a.get("unit") or ""
        entry.update(old=va, new=vb, unit=unit)
        if a.get("skipped") or b.get("skipped") or va is None or vb is None:
            entry["verdict"] = "skipped"
            entries.append(entry)
            continue
        cls = classify(unit)
        if cls is None:
            entry["verdict"] = "uncomparable"
            entries.append(entry)
            continue
        direction, default_tol = cls
        tol = overrides.get(metric)
        if tol is None:
            for row in (b, a):
                if isinstance(row.get("tolerance"), (int, float)):
                    tol = float(row["tolerance"])
                    break
        if tol is None:
            tol = tolerance if tolerance is not None else default_tol
        entry["tolerance"] = tol
        if va == 0:
            change = 0.0 if vb == 0 else float("inf")
        else:
            change = (vb - va) / abs(va)
        entry["change"] = (
            round(change, 4) if change != float("inf") else None
        )
        worse = change > tol if direction == "up" else change < -tol
        better = change < -tol if direction == "up" else change > tol
        entry["verdict"] = (
            "regressed" if worse else "improved" if better else "ok"
        )
        entries.append(entry)
    order = {"regressed": 0, "vanished": 1}
    entries.sort(key=lambda e: (order.get(e["verdict"], 2), e["metric"]))
    return entries


def _format(entry: Dict) -> str:
    mark = {
        "regressed": "REGRESSED", "vanished": "VANISHED",
        "improved": "improved", "ok": "ok", "new": "new",
        "skipped": "skipped", "uncomparable": "?",
    }[entry["verdict"]]
    parts = [f"{mark:9s} {entry['metric']}"]
    if "old" in entry and "new" in entry:
        parts.append(f"{entry.get('old')} -> {entry.get('new')} "
                     f"{entry.get('unit', '')}")
    elif "new" in entry:
        parts.append(f"{entry.get('new')} {entry.get('unit', '')}")
    elif "old" in entry:
        parts.append(f"was {entry.get('old')} {entry.get('unit', '')}")
    if entry.get("change") is not None:
        parts.append(f"({entry['change'] * 100:+.1f}% vs "
                     f"tol {entry['tolerance'] * 100:.0f}%)")
    return "  ".join(parts)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="keystone_tpu bench-diff", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("old", help="baseline bench round (JSON/JSONL)")
    ap.add_argument("new", help="candidate bench round (JSON/JSONL)")
    ap.add_argument("--tolerance", type=float, default=None,
                    metavar="FRAC",
                    help="uniform relative tolerance for every row "
                    "(default: per-unit-class defaults; latency rows "
                    "0.15, rate rows 0.10, counters 0.05)")
    ap.add_argument("--set", action="append", default=[],
                    metavar="METRIC=FRAC", dest="sets",
                    help="per-metric tolerance override (repeatable)")
    ap.add_argument("--allow-missing", action="store_true",
                    help="a metric present in OLD but absent from NEW "
                    "is reported but does not fail the diff (for "
                    "rounds that ran different row subsets)")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict entries as one JSON "
                    "document instead of the table")
    args = ap.parse_args(argv)

    overrides: Dict[str, float] = {}
    for spec in args.sets:
        metric, _, tol = spec.partition("=")
        try:
            overrides[metric] = float(tol)
        except ValueError:
            ap.error(f"--set wants METRIC=FRAC, got {spec!r}")

    try:
        old = load_rows(args.old)
        new = load_rows(args.new)
    except OSError as e:
        print(f"bench-diff: {e}", file=sys.stderr)
        return 2
    if not old:
        print(f"bench-diff: no bench rows in {args.old}",
              file=sys.stderr)
        return 2

    entries = diff_rows(
        old, new, tolerance=args.tolerance, overrides=overrides
    )
    failing = [
        e for e in entries
        if e["verdict"] == "regressed"
        or (e["verdict"] == "vanished" and not args.allow_missing)
    ]
    if args.json:
        print(json.dumps(
            {"entries": entries,
             "regressions": [e["metric"] for e in failing]},
            indent=1,
        ))
    else:
        for entry in entries:
            print(_format(entry))
        print(
            f"{len(entries)} metrics compared, "
            f"{len(failing)} regression(s)"
        )
    return 1 if failing else 0


if __name__ == "__main__":
    raise SystemExit(main())
