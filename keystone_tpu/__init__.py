"""keystone-tpu: a TPU-native large-scale ML pipeline framework.

A from-scratch re-design of the capabilities of KeystoneML
(reference: /root/reference, Scala/Spark) on JAX/XLA/Pallas:

- Typed ``Transformer``/``Estimator`` nodes compose into a lazy dataflow DAG
  (an immutable ``Graph`` IR), a Catalyst-style rule engine optimizes the DAG
  (CSE, dead-branch elimination, cost-model solver selection, profile-driven
  auto-caching), and a memoizing executor runs it.
- Instead of Spark RDDs, data lives in ``Dataset``: pytrees of arrays with a
  leading example axis, shardable over a ``jax.sharding.Mesh``; instead of
  Spark shuffle/treeReduce, communication is XLA collectives over ICI/DCN.
- Solvers (block coordinate descent, L-BFGS, TSQR PCA, kernel ridge) are
  single staged XLA programs over the mesh rather than driver-coordinated
  loops of cluster jobs.
"""

__version__ = "0.1.0"

from keystone_tpu.workflow import (  # noqa: F401
    Estimator,
    FunctionNode,
    LabelEstimator,
    Pipeline,
    Transformer,
)
from keystone_tpu.parallel.dataset import Dataset  # noqa: F401
