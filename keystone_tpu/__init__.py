"""keystone-tpu: a TPU-native large-scale ML pipeline framework.

A from-scratch re-design of the capabilities of KeystoneML
(reference: /root/reference, Scala/Spark) on JAX/XLA/Pallas:

- Typed ``Transformer``/``Estimator`` nodes compose into a lazy dataflow DAG
  (an immutable ``Graph`` IR), a Catalyst-style rule engine optimizes the DAG
  (CSE, dead-branch elimination, cost-model solver selection, profile-driven
  auto-caching), and a memoizing executor runs it.
- Instead of Spark RDDs, data lives in ``Dataset``: pytrees of arrays with a
  leading example axis, shardable over a ``jax.sharding.Mesh``; instead of
  Spark shuffle/treeReduce, communication is XLA collectives over ICI/DCN.
- Solvers (block coordinate descent, L-BFGS, TSQR PCA, kernel ridge) are
  single staged XLA programs over the mesh rather than driver-coordinated
  loops of cluster jobs.
"""

__version__ = "0.1.0"

# LAZY re-exports (PEP 562): the eager form imported jax at package
# import, which made ANY submodule import pay the multi-second jax
# startup — including the streaming loader's spawn decode workers,
# which must stay jax-free (loaders/streaming.py). Attribute access
# still works exactly as before: ``from keystone_tpu import Pipeline``.
_EXPORTS = {
    "Estimator": "keystone_tpu.workflow",
    "FunctionNode": "keystone_tpu.workflow",
    "LabelEstimator": "keystone_tpu.workflow",
    "Pipeline": "keystone_tpu.workflow",
    "Transformer": "keystone_tpu.workflow",
    "Dataset": "keystone_tpu.parallel.dataset",
    "CompiledPipeline": "keystone_tpu.serving",
    "MicroBatcher": "keystone_tpu.serving",
    "ServingMetrics": "keystone_tpu.serving",
}


from keystone_tpu._lazy import make_getattr

__getattr__ = make_getattr(__name__, _EXPORTS)


def __dir__():
    return sorted(list(globals()) + list(_EXPORTS))
