"""The remaining CIFAR applications: LinearPixels, RandomCifar,
RandomPatchCifarKernel, and the augmented RandomPatchCifar variants.

Reference: pipelines/images/cifar/{LinearPixels.scala:20,
RandomCifar.scala:21, RandomPatchCifarKernel.scala:20,
RandomPatchCifarAugmented.scala:33}.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from keystone_tpu.evaluation import (
    AugmentedExamplesEvaluator,
    MulticlassClassifierEvaluator,
)
from keystone_tpu.loaders.cifar import LabeledImages
from keystone_tpu.ops.images import (
    CenterCornerPatcher,
    Convolver,
    GrayScaler,
    ImageVectorizer,
    Pooler,
    RandomPatcher,
    SymmetricRectifier,
)
from keystone_tpu.ops.learning import (
    BlockLeastSquaresEstimator,
    LinearMapEstimator,
)
from keystone_tpu.ops.learning.kernel import (
    GaussianKernelGenerator,
    KernelRidgeRegression,
)
from keystone_tpu.ops.stats import StandardScaler
from keystone_tpu.ops.util.cacher import Cacher
from keystone_tpu.ops.util.nodes import ClassLabelIndicators, MaxClassifier
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.pipelines.images.random_patch_cifar import (
    RandomCifarConfig,
    build_filters,
)
from keystone_tpu.workflow.api import Pipeline

NUM_CLASSES = 10
IMAGE_SIZE = 32
NUM_CHANNELS = 3


def linear_pixels(train: LabeledImages, test: LabeledImages):
    """GrayScaler -> vectorize -> exact least squares -> argmax
    (reference: LinearPixels.scala:20)."""
    labels = ClassLabelIndicators(NUM_CLASSES)(train.labels)
    pipeline = (
        GrayScaler()
        .and_then(ImageVectorizer())
        .and_then(LinearMapEstimator(), train.images, labels)
        .and_then(MaxClassifier())
    )
    metrics = MulticlassClassifierEvaluator(NUM_CLASSES).evaluate(
        pipeline(test.images), test.labels
    )
    return pipeline, metrics


def random_cifar(
    train: LabeledImages,
    test: LabeledImages,
    num_filters: int = 100,
    patch_size: int = 6,
    pool_size: int = 14,
    pool_stride: int = 13,
    alpha: float = 0.25,
    lam: float = 10.0,
    seed: int = 0,
):
    """Random GAUSSIAN filters (no whitening) conv features
    (reference: RandomCifar.scala:21)."""
    rng = np.random.default_rng(seed)
    filters = jnp.asarray(
        rng.standard_normal(
            (num_filters, patch_size * patch_size * NUM_CHANNELS)
        ).astype(np.float32)
    )
    labels = ClassLabelIndicators(NUM_CLASSES)(train.labels)
    pipeline = (
        Convolver(
            filters, IMAGE_SIZE, IMAGE_SIZE, NUM_CHANNELS,
            normalize_patches=True,
        )
        .and_then(SymmetricRectifier(alpha=alpha))
        .and_then(Pooler(pool_stride, pool_size))
        .and_then(ImageVectorizer())
        .and_then(Cacher())
        .and_then(StandardScaler(), train.images)
        .and_then(Cacher())
        .and_then(LinearMapEstimator(lam=lam), train.images, labels)
        .and_then(MaxClassifier())
    )
    metrics = MulticlassClassifierEvaluator(NUM_CLASSES).evaluate(
        pipeline(test.images), test.labels
    )
    return pipeline, metrics


@dataclasses.dataclass
class RandomCifarKernelConfig(RandomCifarConfig):
    gamma: float = 2e-5
    block_size: int = 512
    num_epochs: int = 1


def random_patch_cifar_kernel(
    train: LabeledImages, test: LabeledImages, conf: RandomCifarKernelConfig
):
    """Same featurization as RandomPatchCifar, solved by kernel ridge
    regression (reference: RandomPatchCifarKernel.scala:20,55-90)."""
    filters, whitener = build_filters(train.images, conf)
    labels = ClassLabelIndicators(NUM_CLASSES)(train.labels)
    pipeline = (
        Convolver(
            filters, IMAGE_SIZE, IMAGE_SIZE, NUM_CHANNELS,
            whitener=whitener, normalize_patches=True,
        )
        .and_then(SymmetricRectifier(alpha=conf.alpha))
        .and_then(Pooler(conf.pool_stride, conf.pool_size))
        .and_then(ImageVectorizer())
        .and_then(Cacher())
        .and_then(StandardScaler(), train.images)
        .and_then(
            KernelRidgeRegression(
                GaussianKernelGenerator(conf.gamma),
                conf.lam,
                conf.block_size,
                conf.num_epochs,
                block_permuter=conf.seed,
            ),
            train.images,
            labels,
        )
        .and_then(MaxClassifier())
    )
    metrics = MulticlassClassifierEvaluator(NUM_CLASSES).evaluate(
        pipeline(test.images), test.labels
    )
    return pipeline, metrics


@dataclasses.dataclass
class RandomCifarAugmentedConfig(RandomCifarConfig):
    augment_patch_size: int = 24
    augment_copies: int = 10


def random_patch_cifar_augmented(
    train: LabeledImages,
    test: LabeledImages,
    conf: RandomCifarAugmentedConfig,
):
    """RandomPatchCifar with random-crop train augmentation and
    center/corner test augmentation merged by the augmented evaluator
    (reference: RandomPatchCifarAugmented.scala:33)."""
    aug_size = conf.augment_patch_size
    patcher = RandomPatcher(
        conf.augment_copies, aug_size, aug_size, seed=conf.seed
    )
    aug_images = patcher.apply_batch(train.images)
    aug_labels_int = np.repeat(
        np.asarray(train.labels.array()), conf.augment_copies
    )
    aug_labels = ClassLabelIndicators(NUM_CLASSES)(
        Dataset.from_array(jnp.asarray(aug_labels_int))
    )

    filters, whitener = build_filters(aug_images, conf)
    featurizer = (
        Convolver(
            filters, aug_size, aug_size, NUM_CHANNELS,
            whitener=whitener, normalize_patches=True,
        )
        .and_then(SymmetricRectifier(alpha=conf.alpha))
        .and_then(Pooler(conf.pool_stride, conf.pool_size))
        .and_then(ImageVectorizer())
        .and_then(Cacher())
    )
    pipeline = featurizer.and_then(
        StandardScaler(), aug_images
    ).and_then(
        BlockLeastSquaresEstimator(4096, num_iter=1, lam=conf.lam),
        aug_images,
        aug_labels,
    )

    test_patcher = CenterCornerPatcher(aug_size, aug_size, horizontal_flips=True)
    test_aug = test_patcher.apply_batch(test.images)
    per_image = test_patcher.patches_per_image
    names = np.repeat(np.arange(test.images.n), per_image)
    test_labels_aug = np.repeat(np.asarray(test.labels.array()), per_image)

    scores = pipeline(test_aug).get()
    metrics = AugmentedExamplesEvaluator(
        list(names), NUM_CLASSES
    ).evaluate(scores, test_labels_aug)
    return pipeline, metrics


@dataclasses.dataclass
class RandomCifarAugmentedKernelConfig(RandomCifarAugmentedConfig):
    gamma: float = 2e-4
    block_size: int = 512
    num_epochs: int = 1
    flip_chance: float = 0.5


def random_patch_cifar_augmented_kernel(
    train: LabeledImages,
    test: LabeledImages,
    conf: RandomCifarAugmentedKernelConfig,
):
    """Augmented CIFAR featurization solved by Gauss-Seidel kernel ridge
    regression; train crops get an extra random horizontal flip, test
    copies are merged by the augmented evaluator (reference:
    RandomPatchCifarAugmentedKernel.scala:33-120)."""
    from keystone_tpu.ops.images import RandomImageTransformer

    aug_size = conf.augment_patch_size
    patcher = RandomPatcher(
        conf.augment_copies, aug_size, aug_size, seed=conf.seed
    )
    flipper = RandomImageTransformer(
        flip_chance=conf.flip_chance, seed=conf.seed + 1
    )
    aug_images = flipper.apply_batch(patcher.apply_batch(train.images))
    # LabelAugmenter equivalent: each source label repeated per crop
    aug_labels_int = np.repeat(
        np.asarray(train.labels.array()), conf.augment_copies
    )
    aug_labels = ClassLabelIndicators(NUM_CLASSES)(
        Dataset.from_array(jnp.asarray(aug_labels_int))
    )

    filters, whitener = build_filters(aug_images, conf)
    pipeline = (
        Convolver(
            filters, aug_size, aug_size, NUM_CHANNELS,
            whitener=whitener, normalize_patches=True,
        )
        .and_then(SymmetricRectifier(alpha=conf.alpha))
        .and_then(Pooler(conf.pool_stride, conf.pool_size))
        .and_then(ImageVectorizer())
        .and_then(Cacher())
        .and_then(StandardScaler(), aug_images)
        .and_then(
            KernelRidgeRegression(
                GaussianKernelGenerator(conf.gamma),
                conf.lam,
                conf.block_size,
                conf.num_epochs,
                block_permuter=conf.seed,
            ),
            aug_images,
            aug_labels,
        )
    )

    test_patcher = CenterCornerPatcher(
        aug_size, aug_size, horizontal_flips=True
    )
    test_aug = test_patcher.apply_batch(test.images)
    per_image = test_patcher.patches_per_image  # 10: 5 crops x flips
    names = np.repeat(np.arange(test.images.n), per_image)
    test_labels_aug = np.repeat(np.asarray(test.labels.array()), per_image)

    scores = pipeline(test_aug).get()
    metrics = AugmentedExamplesEvaluator(
        list(names), NUM_CLASSES
    ).evaluate(scores, test_labels_aug)
    return pipeline, metrics
