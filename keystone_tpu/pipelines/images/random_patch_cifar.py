"""RandomPatchCifar — random-patch convolutional features + ZCA + pooling
+ block least squares.

Reference: pipelines/images/cifar/RandomPatchCifar.scala:21 — sample random
patches via Windower, normalize + ZCA-whiten them into a filter bank
(computed eagerly at pipeline-construction time, :45-57), then
Convolver -> SymmetricRectifier -> sum Pooler -> vectorize ->
StandardScaler -> BlockLeastSquaresEstimator(4096, 1, λ) -> argmax.
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

import jax.numpy as jnp
import numpy as np

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.cifar import CifarLoader, LabeledImages
from keystone_tpu.ops.images import (
    Convolver,
    ImageVectorizer,
    Pooler,
    SymmetricRectifier,
    Windower,
)
from keystone_tpu.ops.learning import (
    BlockLeastSquaresEstimator,
    ZCAWhitenerEstimator,
)
from keystone_tpu.ops.stats import Sampler, StandardScaler
from keystone_tpu.ops.util.nodes import ClassLabelIndicators, MaxClassifier
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Pipeline

NUM_CLASSES = 10
IMAGE_SIZE = 32
NUM_CHANNELS = 3
WHITENER_SAMPLE = 100_000


@dataclasses.dataclass
class RandomCifarConfig:
    train_location: str = ""
    test_location: str = ""
    num_filters: int = 100
    whitening_epsilon: float = 0.1
    patch_size: int = 6
    patch_steps: int = 1
    pool_size: int = 14
    pool_stride: int = 13
    alpha: float = 0.25
    lam: float = 0.0
    seed: int = 0


def _normalize_rows(mat: np.ndarray, alpha: float) -> np.ndarray:
    """Stats.normalizeRows (reference: utils/Stats.scala:112-123)."""
    means = np.nan_to_num(mat.mean(axis=1))
    var = ((mat - means[:, None]) ** 2).sum(axis=1) / (mat.shape[1] - 1)
    sds = np.sqrt(var + alpha)
    sds = np.where(np.isnan(sds), np.sqrt(alpha), sds)
    return (mat - means[:, None]) / sds[:, None]


def build_filters(train_images: Dataset, conf: RandomCifarConfig):
    """Sample patches, normalize, fit ZCA, emit whitened filter bank
    (reference: RandomPatchCifar.scala:45-57)."""
    patches = Windower(conf.patch_steps, conf.patch_size).apply(train_images)
    vecs = ImageVectorizer().apply_batch(patches)
    sample = Sampler(WHITENER_SAMPLE, seed=conf.seed).apply(vecs)
    base = _normalize_rows(np.asarray(sample.array(), np.float64), 10.0)
    whitener = ZCAWhitenerEstimator(eps=conf.whitening_epsilon).fit_single(
        jnp.asarray(base, jnp.float32)
    )
    rng = np.random.default_rng(conf.seed)
    idx = rng.choice(
        base.shape[0], size=min(conf.num_filters, base.shape[0]),
        replace=False,
    )
    unnorm = np.asarray(whitener.apply(jnp.asarray(base[idx], jnp.float32)))
    norms = np.sqrt((unnorm**2).sum(axis=1))
    filters = (unnorm / (norms[:, None] + 1e-10)) @ np.asarray(
        whitener.whitener
    ).T
    return jnp.asarray(filters, jnp.float32), whitener


def build_pipeline(
    train: LabeledImages, conf: RandomCifarConfig
) -> Pipeline:
    filters, whitener = build_filters(train.images, conf)
    labels = ClassLabelIndicators(NUM_CLASSES)(train.labels)
    featurizer = (
        Convolver(
            filters, IMAGE_SIZE, IMAGE_SIZE, NUM_CHANNELS,
            whitener=whitener, normalize_patches=True,
        )
        .and_then(SymmetricRectifier(alpha=conf.alpha))
        .and_then(Pooler(conf.pool_stride, conf.pool_size))
        .and_then(ImageVectorizer())
    )
    return (
        featurizer.and_then(StandardScaler(), train.images)
        .and_then(
            BlockLeastSquaresEstimator(4096, num_iter=1, lam=conf.lam),
            train.images,
            labels,
        )
        .and_then(MaxClassifier())
    )


def run(train: LabeledImages, test: LabeledImages, conf: RandomCifarConfig):
    pipeline = build_pipeline(train, conf)
    evaluator = MulticlassClassifierEvaluator(NUM_CLASSES)
    metrics = evaluator.evaluate(pipeline(test.images), test.labels)
    return pipeline, metrics


def synthetic_cifar(n_train=256, n_test=64, seed=0):
    """Class-dependent color blobs standing in for CIFAR."""
    rng = np.random.default_rng(seed)
    means = rng.uniform(30, 220, size=(NUM_CLASSES, NUM_CHANNELS))

    def make(n):
        y = rng.integers(0, NUM_CLASSES, n)
        imgs = (
            means[y][:, None, None, :]
            + rng.normal(0, 20, (n, IMAGE_SIZE, IMAGE_SIZE, NUM_CHANNELS))
        ).clip(0, 255)
        return LabeledImages(
            labels=Dataset.from_array(jnp.asarray(y.astype(np.int32))),
            images=Dataset.from_array(jnp.asarray(imgs.astype(np.float32))),
        )

    return make(n_train), make(n_test)


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(description="RandomPatchCifar")
    p.add_argument("--trainLocation", default="")
    p.add_argument("--testLocation", default="")
    p.add_argument("--numFilters", type=int, default=100)
    p.add_argument("--whiteningEpsilon", type=float, default=0.1)
    p.add_argument("--patchSize", type=int, default=6)
    p.add_argument("--patchSteps", type=int, default=1)
    p.add_argument("--poolSize", type=int, default=14)
    p.add_argument("--poolStride", type=int, default=13)
    p.add_argument("--alpha", type=float, default=0.25)
    p.add_argument("--lambda", dest="lam", type=float, default=0.0)
    a = p.parse_args(argv)
    conf = RandomCifarConfig(
        a.trainLocation, a.testLocation, a.numFilters, a.whiteningEpsilon,
        a.patchSize, a.patchSteps, a.poolSize, a.poolStride, a.alpha, a.lam,
    )
    if conf.train_location:
        train = CifarLoader(conf.train_location)
        test = CifarLoader(conf.test_location)
    else:
        train, test = synthetic_cifar()
    t0 = time.time()
    _, metrics = run(train, test, conf)
    print(metrics.summary())
    print(f"Total time: {time.time() - t0:.2f}s")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
