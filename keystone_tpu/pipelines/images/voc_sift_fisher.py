"""VOCSIFTFisher — SIFT -> PCA -> Fisher Vectors -> BlockLS, evaluated by
VOC mean average precision.

Reference: pipelines/images/voc/VOCSIFTFisher.scala:23-110.
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

import jax.numpy as jnp
import numpy as np

from keystone_tpu.evaluation import MeanAveragePrecisionEvaluator
from keystone_tpu.loaders.image_loaders import (
    MultiLabelExtractor,
    VOCLoader,
)
from keystone_tpu.ops.images.fisher_vector import (
    FisherVector,
    GMMFisherVectorEstimator,
)
from keystone_tpu.ops.images.sift import SIFTExtractor
from keystone_tpu.ops.images.core import GrayScaler, PixelScaler
from keystone_tpu.ops.learning import (
    BatchPCATransformer,
    BlockLeastSquaresEstimator,
    ColumnPCAEstimator,
)
from keystone_tpu.ops.learning.gmm import GaussianMixtureModel
from keystone_tpu.ops.stats import (
    ColumnSampler,
    NormalizeRows,
    SignedHellingerMapper,
)
from keystone_tpu.ops.util.cacher import Cacher
from keystone_tpu.ops.util.nodes import (
    ClassLabelIndicatorsFromIntArrayLabels,
    FloatToDouble,
    MatrixVectorizer,
)
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Pipeline

NUM_VOC_CLASSES = 20


@dataclasses.dataclass
class SIFTFisherConfig:
    train_location: str = ""
    test_location: str = ""
    label_path: str = ""
    lam: float = 0.5
    desc_dim: int = 80
    vocab_size: int = 256
    scale_step: int = 0
    num_pca_samples_per_image: int = 10
    num_gmm_samples_per_image: int = 10
    num_classes: int = NUM_VOC_CLASSES
    seed: int = 0
    pca_file: Optional[str] = None
    gmm_files: Optional[tuple] = None


def build_pipeline(
    training_data: Dataset, training_labels, conf: SIFTFisherConfig
) -> Pipeline:
    sift_extractor = (
        PixelScaler()
        .and_then(GrayScaler())
        .and_then(Cacher())
        .and_then(SIFTExtractor(scale_step=conf.scale_step))
    )

    if conf.pca_file is not None:
        pca_mat = np.loadtxt(conf.pca_file, delimiter=",").astype(np.float32)
        pca_featurizer = sift_extractor.and_then(
            BatchPCATransformer(jnp.asarray(pca_mat).T)
        )
    else:
        sampled = ColumnSampler(
            conf.num_pca_samples_per_image, seed=conf.seed
        )(sift_extractor(training_data))
        pca = ColumnPCAEstimator(conf.desc_dim).with_data(sampled)
        pca_featurizer = sift_extractor.and_then(pca)
    pca_featurizer = pca_featurizer.and_then(Cacher())

    if conf.gmm_files is not None:
        gmm = GaussianMixtureModel.load(*conf.gmm_files)
        fisher_featurizer = pca_featurizer.and_then(FisherVector(gmm))
    else:
        sampled = ColumnSampler(
            conf.num_gmm_samples_per_image, seed=conf.seed + 1
        )(pca_featurizer(training_data))
        fv = GMMFisherVectorEstimator(
            conf.vocab_size, seed=conf.seed
        ).with_data(sampled)
        fisher_featurizer = pca_featurizer.and_then(fv)

    fisher_featurizer = (
        fisher_featurizer.and_then(FloatToDouble())
        .and_then(MatrixVectorizer())
        .and_then(NormalizeRows())
        .and_then(SignedHellingerMapper())
        .and_then(NormalizeRows())
        .and_then(Cacher())
    )

    return fisher_featurizer.and_then(
        BlockLeastSquaresEstimator(
            4096, 1, conf.lam,
            num_features=2 * conf.desc_dim * conf.vocab_size,
        ),
        training_data,
        training_labels,
    )


def run(train_data: Dataset, test_data: Dataset, conf: SIFTFisherConfig):
    training_images = train_data.map(lambda li: li.image)
    label_grabber = ClassLabelIndicatorsFromIntArrayLabels(conf.num_classes)
    training_labels = label_grabber.apply_batch(
        MultiLabelExtractor.apply(train_data)
    )
    predictor = build_pipeline(training_images, training_labels, conf)

    test_images = test_data.map(lambda li: li.image)
    test_actuals = MultiLabelExtractor.apply(test_data).items()
    predictions = predictor(test_images).get()
    aps = MeanAveragePrecisionEvaluator(conf.num_classes).evaluate(
        test_actuals, predictions
    )
    return predictor, float(np.mean(aps))


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(description="VOCSIFTFisher")
    p.add_argument("--trainLocation", required=True)
    p.add_argument("--testLocation", required=True)
    p.add_argument("--labelPath", required=True)
    p.add_argument("--lambda", dest="lam", type=float, default=0.5)
    p.add_argument("--descDim", type=int, default=80)
    p.add_argument("--vocabSize", type=int, default=256)
    p.add_argument("--scaleStep", type=int, default=0)
    a = p.parse_args(argv)
    conf = SIFTFisherConfig(
        a.trainLocation, a.testLocation, a.labelPath, a.lam, a.descDim,
        a.vocabSize, a.scaleStep,
    )
    train = VOCLoader(conf.train_location, conf.label_path)
    test = VOCLoader(conf.test_location, conf.label_path)
    t0 = time.time()
    _, mean_ap = run(train, test, conf)
    print(f"TEST MAP is: {mean_ap:.4f}")
    print(f"Total time: {time.time() - t0:.2f}s")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
