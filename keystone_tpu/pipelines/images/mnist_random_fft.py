"""MnistRandomFFT — the minimum end-to-end application.

Reference: pipelines/images/mnist/MnistRandomFFT.scala:21,40-49 —
gather(numFFTs × [RandomSignNode → PaddedFFT → LinearRectifier]) →
VectorCombiner → BlockLeastSquaresEstimator(blockSize=BlockSize, 1 pass) →
MaxClassifier, evaluated with MulticlassClassifierEvaluator.

Each FFT branch is an independent DAG branch sharing the one source; after
fit, the whole apply path is a single XLA program over the sharded batch.
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

import numpy as np

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders import LabeledData
from keystone_tpu.ops.learning import BlockLeastSquaresEstimator
from keystone_tpu.ops.stats import (
    LinearRectifier,
    PaddedFFT,
    RandomFFTFeatures,
    RandomSignNode,
)
from keystone_tpu.ops.util.nodes import (
    ClassLabelIndicators,
    MaxClassifier,
    VectorCombiner,
)
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Pipeline

NUM_CLASSES = 10
MNIST_DIM = 784


@dataclasses.dataclass
class MnistRandomFFTConfig:
    train_location: str = ""
    test_location: str = ""
    num_ffts: int = 4
    block_size: int = 2048
    lam: float = 0.0
    seed: int = 0
    fused: bool = True  # one batched program for all branches
    # (RandomFFTFeatures) vs the reference's literal per-branch gather


def build_pipeline(
    train: LabeledData, conf: MnistRandomFFTConfig, d: int = MNIST_DIM
) -> Pipeline:
    if conf.fused:
        featurizer = RandomFFTFeatures.create(
            d, conf.num_ffts, seed=conf.seed
        ).to_pipeline()
    else:
        branches = [
            RandomSignNode.create(d, seed=conf.seed + i)
            .and_then(PaddedFFT())
            .and_then(LinearRectifier(0.0))
            for i in range(conf.num_ffts)
        ]
        featurizer = Pipeline.gather(branches).and_then(VectorCombiner())
    labels = ClassLabelIndicators(NUM_CLASSES)(train.labels)
    return featurizer.and_then(
        BlockLeastSquaresEstimator(conf.block_size, num_iter=1, lam=conf.lam),
        train.data,
        labels,
    ).and_then(MaxClassifier())


def run(train: LabeledData, test: LabeledData, conf: MnistRandomFFTConfig):
    pipeline = build_pipeline(train, conf)
    evaluator = MulticlassClassifierEvaluator(NUM_CLASSES)
    metrics = evaluator.evaluate(pipeline(test.data), test.labels)
    return pipeline, metrics


def synthetic_mnist(
    n_train: int = 512, n_test: int = 128, seed: int = 0
) -> tuple:
    """Deterministic synthetic stand-in when no CSV paths are given: one
    Gaussian blob per class in pixel space."""
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((NUM_CLASSES, MNIST_DIM)) * 2.0

    def make(n):
        y = rng.integers(0, NUM_CLASSES, n)
        x = centers[y] + rng.standard_normal((n, MNIST_DIM))
        return LabeledData.of(y.astype(np.int32), x.astype(np.float32))

    return make(n_train), make(n_test)


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(description="MnistRandomFFT")
    p.add_argument("--trainLocation", default="")
    p.add_argument("--testLocation", default="")
    p.add_argument("--numFFTs", type=int, default=4)
    p.add_argument("--blockSize", type=int, default=2048)
    p.add_argument("--lambda", dest="lam", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    a = p.parse_args(argv)
    conf = MnistRandomFFTConfig(
        a.trainLocation, a.testLocation, a.numFFTs, a.blockSize, a.lam, a.seed
    )
    if conf.train_location:
        train = LabeledData.from_csv(conf.train_location, label_offset=1)
        test = LabeledData.from_csv(conf.test_location, label_offset=1)
    else:
        train, test = synthetic_mnist(seed=conf.seed)
    t0 = time.time()
    _, metrics = run(train, test, conf)
    elapsed = time.time() - t0
    print(metrics.summary())
    print(f"Total time: {elapsed:.2f}s")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
