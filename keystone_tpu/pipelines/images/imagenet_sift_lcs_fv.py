"""ImageNetSiftLcsFV — the flagship pipeline: SIFT + LCS branches, each
PCA -> GMM Fisher Vectors -> normalization, gathered and fed to the
mixture-weighted block least-squares solver, Top-5 output.

Reference: pipelines/images/imagenet/ImageNetSiftLcsFV.scala:29-151.
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

import jax.numpy as jnp
import numpy as np

from keystone_tpu.loaders.image_loaders import (
    ImageExtractor,
    ImageNetLoader,
    LabelExtractor,
    NUM_IMAGENET_CLASSES,
)
from keystone_tpu.ops.images.fisher_vector import GMMFisherVectorEstimator
from keystone_tpu.ops.images.lcs import LCSExtractor
from keystone_tpu.ops.images.sift import SIFTExtractor
from keystone_tpu.ops.images.core import GrayScaler, PixelScaler
from keystone_tpu.ops.learning import BatchPCATransformer, ColumnPCAEstimator
from keystone_tpu.ops.learning.gmm import GaussianMixtureModel
from keystone_tpu.ops.learning.weighted_ls import (
    BlockWeightedLeastSquaresEstimator,
)
from keystone_tpu.ops.stats import (
    ColumnSampler,
    NormalizeRows,
    SignedHellingerMapper,
)
from keystone_tpu.ops.util.cacher import Cacher
from keystone_tpu.ops.util.nodes import (
    ClassLabelIndicators,
    FloatToDouble,
    MatrixVectorizer,
    TopKClassifier,
    VectorCombiner,
)
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Pipeline


@dataclasses.dataclass
class ImageNetSiftLcsFVConfig:
    train_location: str = ""
    test_location: str = ""
    label_path: str = ""
    lam: float = 6e-5
    mixture_weight: float = 0.25
    desc_dim: int = 64
    vocab_size: int = 16
    sift_scale_step: int = 1
    lcs_stride: int = 4
    lcs_border: int = 16
    lcs_patch: int = 6
    num_pca_samples_per_image: int = 10
    num_gmm_samples_per_image: int = 10
    num_classes: int = NUM_IMAGENET_CLASSES
    seed: int = 0
    # optional warm-start files (reference: pcaFile/gmmMeanFile/...)
    sift_pca_file: Optional[str] = None
    sift_gmm_files: Optional[tuple] = None  # (means, vars, weights)
    lcs_pca_file: Optional[str] = None
    lcs_gmm_files: Optional[tuple] = None


def compute_pca_and_fisher_branch(
    prefix: Pipeline,
    training_data,
    conf: ImageNetSiftLcsFVConfig,
    pca_file: Optional[str],
    gmm_files: Optional[tuple],
) -> Pipeline:
    """reference: ImageNetSiftLcsFV.computePCAandFisherBranch:29-80."""
    if pca_file is not None:
        pca_mat = np.loadtxt(pca_file, delimiter=",").astype(np.float32)
        pca_pipeline = BatchPCATransformer(jnp.asarray(pca_mat).T).to_pipeline()
    else:
        sampled = ColumnSampler(
            conf.num_pca_samples_per_image, seed=conf.seed
        )(prefix(training_data))
        pca_pipeline = ColumnPCAEstimator(conf.desc_dim).with_data(sampled)

    if gmm_files is not None:
        gmm = GaussianMixtureModel.load(*gmm_files)
        from keystone_tpu.ops.images.fisher_vector import FisherVector

        fv_pipeline = FisherVector(gmm).to_pipeline()
    else:
        sampled = ColumnSampler(
            conf.num_gmm_samples_per_image, seed=conf.seed + 1
        )(prefix(training_data))
        fv_pipeline = GMMFisherVectorEstimator(
            conf.vocab_size, seed=conf.seed
        ).with_data(pca_pipeline.apply(sampled))

    return (
        prefix.and_then(pca_pipeline)
        .and_then(fv_pipeline)
        .and_then(FloatToDouble())
        .and_then(MatrixVectorizer())
        .and_then(NormalizeRows())
        .and_then(SignedHellingerMapper())
        .and_then(NormalizeRows())
    )


def build_pipeline(
    train_images: Dataset, train_labels, conf: ImageNetSiftLcsFVConfig
) -> Pipeline:
    indicator_labels = ClassLabelIndicators(conf.num_classes)(train_labels)

    sift_prefix = (
        PixelScaler()
        .and_then(GrayScaler())
        .and_then(SIFTExtractor(scale_step=conf.sift_scale_step))
        .and_then(SignedHellingerMapper())
    )
    sift_branch = compute_pca_and_fisher_branch(
        sift_prefix, train_images, conf, conf.sift_pca_file,
        conf.sift_gmm_files,
    )

    lcs_prefix = LCSExtractor(
        conf.lcs_stride, conf.lcs_border, conf.lcs_patch
    ).to_pipeline()
    lcs_branch = compute_pca_and_fisher_branch(
        lcs_prefix, train_images, conf, conf.lcs_pca_file,
        conf.lcs_gmm_files,
    )

    num_features = 2 * 2 * conf.desc_dim * conf.vocab_size
    return (
        Pipeline.gather([sift_branch, lcs_branch])
        .and_then(VectorCombiner())
        .and_then(Cacher())
        .and_then(
            BlockWeightedLeastSquaresEstimator(
                4096, 1, conf.lam, conf.mixture_weight,
                num_features=num_features,
            ),
            train_images,
            indicator_labels,
        )
        .and_then(TopKClassifier(5))
    )


def run(train_data: Dataset, test_data: Dataset, conf: ImageNetSiftLcsFVConfig):
    train_images = ImageExtractor.apply(train_data)
    train_labels = LabelExtractor.apply(train_data)
    test_images = ImageExtractor.apply(test_data)
    test_labels = LabelExtractor.apply(test_data)

    predictor = build_pipeline(train_images, train_labels, conf)
    predicted = predictor(test_images).get()
    top5 = np.asarray(predicted.array())
    actual = np.asarray(test_labels.array())
    err = 1.0 - np.mean([a in p for a, p in zip(actual, top5)])
    return predictor, err


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(description="ImageNetSiftLcsFV")
    p.add_argument("--trainLocation", required=True)
    p.add_argument("--testLocation", required=True)
    p.add_argument("--labelPath", required=True)
    p.add_argument("--lambda", dest="lam", type=float, default=6e-5)
    p.add_argument("--mixtureWeight", type=float, default=0.25)
    p.add_argument("--descDim", type=int, default=64)
    p.add_argument("--vocabSize", type=int, default=16)
    p.add_argument("--siftScaleStep", type=int, default=1)
    a = p.parse_args(argv)
    conf = ImageNetSiftLcsFVConfig(
        a.trainLocation, a.testLocation, a.labelPath, a.lam,
        a.mixtureWeight, a.descDim, a.vocabSize, a.siftScaleStep,
    )
    train = ImageNetLoader(conf.train_location, conf.label_path)
    test = ImageNetLoader(conf.test_location, conf.label_path)
    t0 = time.time()
    _, err = run(train, test, conf)
    print(f"TEST Top-5 error is {100 * err:.2f}%")
    print(f"Total time: {time.time() - t0:.2f}s")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
