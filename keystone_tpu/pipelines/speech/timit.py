"""TimitPipeline — random cosine features + block least squares for
phone classification.

Reference: pipelines/speech/TimitPipeline.scala:37-100 —
gather(numCosines x CosineRandomFeatures(440 -> 4096, gaussian or cauchy))
-> VectorCombiner -> BlockLeastSquaresEstimator(4096, numEpochs, lambda)
-> MaxClassifier.
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Optional

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.csv_loader import LabeledData
from keystone_tpu.loaders.text_loaders import (
    TIMIT_DIMENSION,
    TIMIT_NUM_CLASSES,
    TimitFeaturesDataLoader,
)
from keystone_tpu.ops.learning import BlockLeastSquaresEstimator
from keystone_tpu.ops.stats import CosineRandomFeatures
from keystone_tpu.ops.util.nodes import (
    ClassLabelIndicators,
    MaxClassifier,
    VectorCombiner,
)
from keystone_tpu.workflow.api import Pipeline

NUM_COSINE_FEATURES = 4096


@dataclasses.dataclass
class TimitConfig:
    train_data_location: str = ""
    train_labels_location: str = ""
    test_data_location: str = ""
    test_labels_location: str = ""
    num_cosines: int = 40
    gamma: float = 0.05555
    num_epochs: int = 5
    lam: float = 0.0
    rf_type: str = "gaussian"  # or "cauchy"
    seed: int = 123
    num_cosine_features: int = NUM_COSINE_FEATURES
    dim: int = TIMIT_DIMENSION
    num_classes: int = TIMIT_NUM_CLASSES


def build_pipeline(train: LabeledData, conf: TimitConfig) -> Pipeline:
    labels = ClassLabelIndicators(conf.num_classes)(train.labels)
    branches = [
        CosineRandomFeatures.create(
            conf.dim,
            conf.num_cosine_features,
            conf.gamma,
            seed=conf.seed + i,
            distribution=conf.rf_type,
        )
        for i in range(conf.num_cosines)
    ]
    featurizer = Pipeline.gather(branches).and_then(VectorCombiner())
    return featurizer.and_then(
        BlockLeastSquaresEstimator(
            conf.num_cosine_features, num_iter=conf.num_epochs, lam=conf.lam
        ),
        train.data,
        labels,
    ).and_then(MaxClassifier())


def run(train: LabeledData, test: LabeledData, conf: TimitConfig):
    predictor = build_pipeline(train, conf)
    evaluator = MulticlassClassifierEvaluator(conf.num_classes)
    metrics = evaluator.evaluate(predictor(test.data), test.labels)
    return predictor, metrics


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(description="TimitPipeline")
    p.add_argument("--trainDataLocation", required=True)
    p.add_argument("--trainLabelsLocation", required=True)
    p.add_argument("--testDataLocation", required=True)
    p.add_argument("--testLabelsLocation", required=True)
    p.add_argument("--numCosines", type=int, default=40)
    p.add_argument("--gamma", type=float, default=0.05555)
    p.add_argument("--numEpochs", type=int, default=5)
    p.add_argument("--lambda", dest="lam", type=float, default=0.0)
    p.add_argument("--rfType", default="gaussian")
    a = p.parse_args(argv)
    conf = TimitConfig(
        a.trainDataLocation, a.trainLabelsLocation, a.testDataLocation,
        a.testLabelsLocation, a.numCosines, a.gamma, a.numEpochs, a.lam,
        a.rfType,
    )
    data = TimitFeaturesDataLoader(
        conf.train_data_location, conf.train_labels_location,
        conf.test_data_location, conf.test_labels_location,
    )
    t0 = time.time()
    _, metrics = run(data.train, data.test, conf)
    print(metrics.summary())
    print(f"Total time: {time.time() - t0:.2f}s")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
