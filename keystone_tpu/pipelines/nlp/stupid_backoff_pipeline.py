"""StupidBackoffPipeline — n-gram language model estimation.

Reference: pipelines/nlp/StupidBackoffPipeline.scala:13-40 — tokens ->
WordFrequencyEncoder -> NGramsFeaturizer -> NGramsCounts ->
StupidBackoffEstimator.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional

from keystone_tpu.ops.nlp import (
    NGramsCounts,
    NGramsFeaturizer,
    StupidBackoffEstimator,
    Tokenizer,
    WordFrequencyEncoder,
)
from keystone_tpu.parallel.dataset import Dataset


@dataclasses.dataclass
class StupidBackoffConfig:
    train_location: str = ""
    n: int = 3


def run(text: Dataset, conf: StupidBackoffConfig):
    """Returns the fitted StupidBackoffModel over frequency-encoded
    tokens."""
    tokens = Tokenizer().apply_batch(text)
    encoder = WordFrequencyEncoder().fit(tokens)
    encoded = encoder.apply_batch(tokens)
    ngrams = NGramsFeaturizer(range(2, conf.n + 1)).apply_batch(encoded)
    counts = NGramsCounts("noAdd").apply(ngrams)
    model = StupidBackoffEstimator(encoder.unigram_counts).fit(counts)
    return model, encoder


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(description="StupidBackoffPipeline")
    p.add_argument("--trainLocation", required=True)
    p.add_argument("--n", type=int, default=3)
    a = p.parse_args(argv)
    with open(a.trainLocation) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    model, _ = run(
        Dataset.from_items(lines), StupidBackoffConfig(a.trainLocation, a.n)
    )
    print(f"model over {model.num_tokens} tokens, alpha={model.alpha}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
