"""NewsgroupsPipeline — n-gram Naive Bayes text classification.

Reference: pipelines/text/NewsgroupsPipeline.scala:18-45 — Trim ->
LowerCase -> Tokenizer -> NGramsFeaturizer(1..n) -> TermFrequency(x=>1) ->
CommonSparseFeatures(100k) -> NaiveBayes -> MaxClassifier.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.csv_loader import LabeledData
from keystone_tpu.loaders.text_loaders import (
    NEWSGROUPS_CLASSES,
    NewsgroupsDataLoader,
)
from keystone_tpu.ops.learning.classifiers import NaiveBayesEstimator
from keystone_tpu.ops.nlp import (
    FusedTextHashTF,
    LowerCase,
    NGramsFeaturizer,
    Tokenizer,
    Trim,
)
from keystone_tpu.ops.stats import TermFrequency
from keystone_tpu.ops.util.nodes import CommonSparseFeatures, MaxClassifier
from keystone_tpu.workflow.api import Pipeline


@dataclasses.dataclass
class NewsgroupsConfig:
    train_location: str = ""
    test_location: str = ""
    n_grams: int = 2
    common_features: int = 100_000
    hashing: bool = False  # hashed n-gram features via the fused native
    # C++ featurizer instead of string-keyed top-K selection (reference
    # alternative: nodes/nlp/HashingTF.scala)


def build_pipeline(train: LabeledData, conf: NewsgroupsConfig) -> Pipeline:
    num_classes = len(NEWSGROUPS_CLASSES)
    if conf.hashing:
        featurizer = FusedTextHashTF(
            range(1, conf.n_grams + 1), conf.common_features,
            binarize=True,
        ).to_pipeline()
        return featurizer.and_then(
            NaiveBayesEstimator(num_classes), train.data, train.labels
        ).and_then(MaxClassifier())
    featurizer = (
        Trim()
        .and_then(LowerCase())
        .and_then(Tokenizer())
        .and_then(NGramsFeaturizer(range(1, conf.n_grams + 1)))
        .and_then(TermFrequency(lambda x: 1))
    )
    return featurizer.and_then(
        CommonSparseFeatures(conf.common_features), train.data
    ).and_then(
        NaiveBayesEstimator(num_classes), train.data, train.labels
    ).and_then(MaxClassifier())


def run(train: LabeledData, test: LabeledData, conf: NewsgroupsConfig):
    predictor = build_pipeline(train, conf)
    evaluator = MulticlassClassifierEvaluator(len(NEWSGROUPS_CLASSES))
    metrics = evaluator.evaluate(predictor(test.data), test.labels)
    return predictor, metrics


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(description="NewsgroupsPipeline")
    p.add_argument("--trainLocation", required=True)
    p.add_argument("--testLocation", required=True)
    p.add_argument("--nGrams", type=int, default=2)
    p.add_argument("--commonFeatures", type=int, default=100_000)
    p.add_argument("--hashing", action="store_true",
                   help="fused native hashed n-gram features")
    a = p.parse_args(argv)
    conf = NewsgroupsConfig(
        a.trainLocation, a.testLocation, a.nGrams, a.commonFeatures,
        a.hashing,
    )
    train = NewsgroupsDataLoader(conf.train_location)
    test = NewsgroupsDataLoader(conf.test_location)
    _, metrics = run(train, test, conf)
    print(metrics.summary(NEWSGROUPS_CLASSES))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
