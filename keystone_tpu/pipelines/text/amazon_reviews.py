"""AmazonReviewsPipeline — n-gram logistic regression sentiment.

Reference: pipelines/text/AmazonReviewsPipeline.scala:18-60 — Trim ->
LowerCase -> Tokenizer -> NGramsFeaturizer(1..n) -> TermFrequency(x=>1) ->
CommonSparseFeatures -> LogisticRegression(2 classes), evaluated with the
binary evaluator.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional

import numpy as np

from keystone_tpu.evaluation import BinaryClassifierEvaluator
from keystone_tpu.loaders.csv_loader import LabeledData
from keystone_tpu.loaders.text_loaders import AmazonReviewsDataLoader
from keystone_tpu.ops.learning.classifiers import (
    LogisticRegressionEstimator,
)
from keystone_tpu.ops.nlp import (
    FusedTextHashTF,
    LowerCase,
    NGramsFeaturizer,
    Tokenizer,
    Trim,
)
from keystone_tpu.ops.stats import TermFrequency
from keystone_tpu.ops.util.nodes import CommonSparseFeatures
from keystone_tpu.workflow.api import Pipeline


@dataclasses.dataclass
class AmazonReviewsConfig:
    train_location: str = ""
    test_location: str = ""
    threshold: float = 3.5
    n_grams: int = 2
    common_features: int = 100_000
    num_iters: int = 20
    hashing: bool = False  # hashed n-gram features via the fused native
    # C++ featurizer (FusedTextHashTF) instead of the string-keyed
    # NGramsFeaturizer -> CommonSparseFeatures chain — same binarized
    # n-gram model family (reference ships HashingTF as the alternative:
    # nodes/nlp/HashingTF.scala), one multi-threaded pass per batch


def build_pipeline(train: LabeledData, conf: AmazonReviewsConfig) -> Pipeline:
    if conf.hashing:
        featurizer = FusedTextHashTF(
            range(1, conf.n_grams + 1), conf.common_features,
            binarize=True,
        ).to_pipeline()
        return featurizer.and_then(
            LogisticRegressionEstimator(2, num_iters=conf.num_iters),
            train.data,
            train.labels,
        )
    featurizer = (
        Trim()
        .and_then(LowerCase())
        .and_then(Tokenizer())
        .and_then(NGramsFeaturizer(range(1, conf.n_grams + 1)))
        .and_then(TermFrequency(lambda x: 1))
    )
    return featurizer.and_then(
        CommonSparseFeatures(conf.common_features), train.data
    ).and_then(
        LogisticRegressionEstimator(2, num_iters=conf.num_iters),
        train.data,
        train.labels,
    )


def run(train: LabeledData, test: LabeledData, conf: AmazonReviewsConfig):
    predictor = build_pipeline(train, conf)
    pred = np.asarray(predictor(test.data).get().array())
    metrics = BinaryClassifierEvaluator().evaluate(
        pred > 0, np.asarray(test.labels.array()) > 0
    )
    return predictor, metrics


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(description="AmazonReviewsPipeline")
    p.add_argument("--trainLocation", required=True)
    p.add_argument("--testLocation", required=True)
    p.add_argument("--threshold", type=float, default=3.5)
    p.add_argument("--nGrams", type=int, default=2)
    p.add_argument("--commonFeatures", type=int, default=100_000)
    p.add_argument("--numIters", type=int, default=20)
    p.add_argument("--hashing", action="store_true",
                   help="fused native hashed n-gram features")
    a = p.parse_args(argv)
    conf = AmazonReviewsConfig(
        a.trainLocation, a.testLocation, a.threshold, a.nGrams,
        a.commonFeatures, a.numIters, a.hashing,
    )
    train = AmazonReviewsDataLoader(conf.train_location, conf.threshold)
    test = AmazonReviewsDataLoader(conf.test_location, conf.threshold)
    _, metrics = run(train, test, conf)
    print(metrics.summary())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
