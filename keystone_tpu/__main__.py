"""Single CLI entry: ``python -m keystone_tpu <AppName> [app args...]``.

Reference: bin/run-pipeline.sh selects the pipeline class by fully
qualified name as argv[1]; here short app names map to the app modules'
``main``.
"""

from __future__ import annotations

import sys

APPS = {
    "MnistRandomFFT": "keystone_tpu.pipelines.images.mnist_random_fft",
    "RandomPatchCifar": "keystone_tpu.pipelines.images.random_patch_cifar",
    "ImageNetSiftLcsFV": "keystone_tpu.pipelines.images.imagenet_sift_lcs_fv",
    "VOCSIFTFisher": "keystone_tpu.pipelines.images.voc_sift_fisher",
    "TimitPipeline": "keystone_tpu.pipelines.speech.timit",
    "NewsgroupsPipeline": "keystone_tpu.pipelines.text.newsgroups",
    "AmazonReviewsPipeline": "keystone_tpu.pipelines.text.amazon_reviews",
    "StupidBackoffPipeline": "keystone_tpu.pipelines.nlp.stupid_backoff_pipeline",
}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--admin-port" in argv:
        # observability plane: /metrics (Prometheus), /varz, /healthz,
        # /tracez on a background thread, span tracing enabled so
        # executor/serving spans land in /tracez. Peeled before app
        # dispatch so EVERY app (and serve-bench) is scrapeable.
        i = argv.index("--admin-port")
        try:
            port = int(argv[i + 1])
        except (IndexError, ValueError):
            print("--admin-port requires an integer port (0 = ephemeral)")
            return 2
        del argv[i : i + 2]
        from keystone_tpu.observability import (
            enable_tracing,
            start_admin_server,
        )

        enable_tracing()
        server = start_admin_server(port=port)
        print(f"admin endpoint: {server.url()} "
              "(/metrics /varz /healthz /tracez /profilez)", flush=True)
    if "--otlp-endpoint" in argv:
        # OTLP/HTTP span export: every finished span batches to a
        # collector's /v1/traces on a background thread (stdlib urllib,
        # nothing to install). Peeled before app dispatch like
        # --admin-port; implies tracing on.
        i = argv.index("--otlp-endpoint")
        try:
            endpoint = argv[i + 1]
            if endpoint.startswith("-"):
                raise ValueError(endpoint)
        except (IndexError, ValueError):
            print("--otlp-endpoint requires a collector URL "
                  "(e.g. http://127.0.0.1:4318)")
            return 2
        del argv[i : i + 2]

        def peel_value(flag, default):
            if flag not in argv:
                return default
            j = argv.index(flag)
            try:
                value = argv[j + 1]
                if value.startswith("-"):
                    raise ValueError(value)
            except (IndexError, ValueError):
                raise SystemExit(f"{flag} requires a value") from None
            del argv[j : j + 2]
            return value

        import os
        import socket

        # resource identity: which SERVICE (router vs gateway vs app)
        # and which REPLICA this process is — what lets an external
        # collector lay the fleet's halves of one trace out as the
        # same topology the router's stitched /debugz shows. The app
        # name is a sensible service default; cross-host fleets pass
        # --otlp-replica the advertised host:port.
        default_service = (
            f"keystone-{argv[0].removeprefix('serve-')}"
            if argv and not argv[0].startswith("-")
            else "keystone-tpu"
        )
        service = peel_value("--otlp-service", default_service)
        replica = peel_value(
            "--otlp-replica", f"{socket.gethostname()}:{os.getpid()}"
        )
        from keystone_tpu.observability import (
            OtlpSpanExporter,
            enable_tracing,
        )

        enable_tracing()
        exporter = OtlpSpanExporter(
            endpoint,
            service_name=service,
            resource_attrs={"replica": replica},
        )
        exporter.install()
        print(
            f"otlp export: {exporter.endpoint} "
            f"(service.name={service} replica={replica})",
            flush=True,
        )
    gateway_port = None
    if "--gateway-port" in argv:
        # request plane: admission control + replica lanes + live
        # engine swap in front of a compiled pipeline, HTTP /predict
        # frontend (keystone_tpu/gateway/). Peeled here so
        # `python -m keystone_tpu --gateway-port N` alone stands up the
        # serve-gateway demo (bench pipeline); with an explicit
        # serve-gateway app the port just rides along.
        i = argv.index("--gateway-port")
        try:
            gateway_port = int(argv[i + 1])
        except (IndexError, ValueError):
            print("--gateway-port requires an integer port (0 = ephemeral)")
            return 2
        del argv[i : i + 2]
        if not argv or argv[0].startswith("-"):
            # no app named: everything left is serve-gateway options
            argv = ["serve-gateway"] + argv
        if argv[0] != "serve-gateway":
            print("--gateway-port only applies to the serve-gateway app")
            return 2
    if "--debug-optimizer" in argv:
        # Per-rule optimizer trace: node-count deltas at INFO, full DOT
        # graphs after each effective rule at DEBUG (reference logs DOT on
        # every rule application, RuleExecutor.scala:44-50).
        argv.remove("--debug-optimizer")
        import logging

        logging.basicConfig()
        for mod in ("keystone_tpu.workflow.rules",
                    "keystone_tpu.workflow.auto_cache",
                    "keystone_tpu.workflow.node_optimization"):
            logging.getLogger(mod).setLevel(logging.DEBUG)
    if not argv or argv[0] in ("-h", "--help"):
        print(
            "usage: python -m keystone_tpu [--debug-optimizer] "
            "[--admin-port N] [--gateway-port N] [--otlp-endpoint URL] "
            "<AppName> [app args...]"
        )
        print("apps:")
        for name in sorted(APPS):
            print(f"  {name}")
        print("  serve-bench  (serving engine benchmarks; see "
              "keystone_tpu/serving/bench.py)")
        print("  serve-gateway  (HTTP request plane over the bench "
              "pipeline; keystone_tpu/gateway/. --shard-model serves "
              "the model mesh-sharded over the local devices — "
              "keystone_tpu/serving/sharding.py)")
        print("  serve-router  (fleet tier: cross-host router over N "
              "serve-gateway replicas — replica registry with "
              "--replica URLs + POST /registerz self-registration, "
              "background health probes with half-open recovery, "
              "least-loaded routing with retry-on-another-replica, "
              "federated /metrics + /slz over the replicas' scraped "
              "le buckets, /fleetz roster; keystone_tpu/fleet/)")
        print("  serve-loadgen  (trace-driven open-loop load generator "
              "+ chaos harness against a live gateway; replays "
              "--request-log recordings or synthesizes Poisson/heavy-"
              "tail arrivals, arms fault points mid-run via /chaosz, "
              "and exits nonzero unless the serving invariants held; "
              "keystone_tpu/loadgen/)")
        print("  serve-autoscale  (autonomous fleet elasticity: an "
              "in-process fleet router + a supervisor spawning "
              "serve-gateway replicas as subprocesses + an SLO-driven "
              "control loop — scrapes the router's federated /metrics "
              "+ /slz, scales out when queue_wait-dominated latency "
              "burns the SLO, replaces kill -9'd replicas, and "
              "drain-retires idle ones; every decision is a JSON "
              "event, keystone_autoscale_* series, and a trace span; "
              "keystone_tpu/autoscale/)")
        print("  serve-capacity-plan  (replay a recorded --request-log "
              "peak x1..xN against 1..K supervised replicas, fit the "
              "replicas-vs-offered-load curve, and write the JSON "
              "plan artifact serve-autoscale --plan loads — the "
              "policy thresholds are measured, not guessed; "
              "keystone_tpu/autoscale/planner.py)")
        print("  serve-lifecycle  (operator controls for a gateway's "
              "online model lifecycle — status/tick/rollback against "
              "a serve-gateway --refit frontend's /lifecyclez: "
              "streaming refit from POST /feedback, shadow-mirrored "
              "candidates, deterministic canary fractions, atomic "
              "promote with auto-rollback; keystone_tpu/lifecycle/)")
        print("  serve-aot-build  (pre-populate the AOT serialized-"
              "executable store: compile every bucket once and "
              "serialize the executables so a brand-new host's "
              "serve-gateway goes from exec() to serving with zero "
              "XLA compiles; keystone_tpu/serving/aot.py)")
        print("  bench-diff  (compare two bench-round JSONs and exit "
              "nonzero on headline-metric regressions beyond per-row "
              "tolerance — bin/bench-diff last-green.json "
              "this-round.json; keystone_tpu/bench_diff.py)")
        print("  keystone-lint  (AST contract analyzer over this "
              "repo's own source: lock discipline, blocking-under-"
              "lock, strippable asserts, absent-not-zero metrics, "
              "hot-path host syncs, fault-point catalog drift; "
              "nonzero exit on unbaselined findings — the CI gate; "
              "keystone_tpu/analysis/)")
        print("options:")
        print("  --gateway-port N shorthand for `serve-gateway "
              "--gateway-port N`: admission-")
        print("                   controlled HTTP inference frontend "
              "(POST /predict, GET /readyz,")
        print("                   POST /swap) with N replica lanes and "
              "live re-bucketing. Lanes")
        print("                   run as staged pipelines — host-prep/"
              "upload/compute of")
        print("                   consecutive windows overlap "
              "(--pipeline-depth 0 reverts to")
        print("                   serial dispatch). N=0 picks an "
              "ephemeral port.")
        print("  --admin-port N   serve metrics on http://127.0.0.1:N —"
              " /metrics (Prometheus")
        print("                   text exposition of every live engine's"
              " compile/dispatch/latency")
        print("                   counters), /varz (JSON + build info),"
              " /healthz, /tracez (recent")
        print("                   spans; add ?format=chrome for a"
              " Perfetto/chrome://tracing trace),")
        print("                   /slz (SLO burn rates), /debugz (flight"
              " recorder), /profilez")
        print("                   (on-demand jax.profiler capture of"
              " ?seconds=N of live traffic).")
        print("                   N=0 picks an ephemeral port. Off by"
              " default — zero overhead when")
        print("                   absent.")
        print("  --otlp-endpoint URL  export spans to an OTLP/HTTP"
              " collector (POST")
        print("                   URL/v1/traces, background batching,"
              " stdlib-only). Implies")
        print("                   tracing on. Off by default."
              " --otlp-service NAME and")
        print("                   --otlp-replica HOST:PORT stamp the"
              " service.name/replica")
        print("                   resource attrs (defaults: the app"
              " name, hostname:pid) so an")
        print("                   external collector sees the fleet's"
              " stitched topology.")
        return 0 if argv else 2
    app = argv[0]
    if app == "serve-bench":
        from keystone_tpu.serving.bench import main as serve_bench_main

        return serve_bench_main(argv[1:])
    if app == "serve-gateway":
        from keystone_tpu.gateway.http import main as serve_gateway_main

        rest = argv[1:]
        if gateway_port is not None:
            rest = ["--gateway-port", str(gateway_port)] + rest
        return serve_gateway_main(rest)
    if app == "serve-router":
        from keystone_tpu.fleet.router import main as serve_router_main

        return serve_router_main(argv[1:])
    if app == "serve-loadgen":
        from keystone_tpu.loadgen.cli import main as serve_loadgen_main

        return serve_loadgen_main(argv[1:])
    if app == "serve-autoscale":
        from keystone_tpu.autoscale.cli import main as serve_autoscale_main

        return serve_autoscale_main(argv[1:])
    if app == "serve-capacity-plan":
        from keystone_tpu.autoscale.planner import main as capacity_plan_main

        return capacity_plan_main(argv[1:])
    if app == "serve-lifecycle":
        # stdlib-only HTTP client: no jax import for operator controls
        from keystone_tpu.lifecycle.cli import main as lifecycle_main

        return lifecycle_main(argv[1:])
    if app == "serve-aot-build":
        from keystone_tpu.serving.aot import build_main

        return build_main(argv[1:])
    if app == "bench-diff":
        # stdlib-only like the linter: regression gating runs in CI
        # hooks without paying the jax import
        from keystone_tpu.bench_diff import main as bench_diff_main

        return bench_diff_main(argv[1:])
    if app == "keystone-lint":
        # stdlib-only path by design: the linter must run in hooks and
        # CI without paying the jax import (analysis/ never imports it)
        from keystone_tpu.analysis.cli import main as lint_main

        return lint_main(argv[1:])
    if app not in APPS:
        print(f"unknown app {app!r}; run with --help for the list")
        return 2
    import importlib

    # join the multi-host runtime when launched as one process per pod
    # host (no-op on a single host; see parallel/runtime.py)
    from keystone_tpu.parallel.runtime import initialize

    initialize()
    module = importlib.import_module(APPS[app])
    return module.main(argv[1:])


if __name__ == "__main__":
    raise SystemExit(main())
