"""Process-global metrics registry.

One named, labeled catalogue of counters / gauges / latency summaries
that every subsystem publishes into and every exporter reads out of —
the single observability plane the serving engine, the micro-batcher,
the workflow executor, and the auto-cache profiler all feed (scraped by
the admin endpoint in ``observability/admin.py``, rendered by
``observability/prometheus.py``).

Built on the existing thread-safe primitives in ``utils/profiling.py``:
a registry counter is a ``Counter`` whose cells are keyed by
label-value tuples; a latency summary is one ``LatencyRecorder`` per
label set. Gauges come in two flavours — settable (a locked float per
label set) and callback-backed (a zero-state function polled at collect
time, so live objects like a ``ServingMetrics`` never copy state into
the registry on the hot path).

Collection is pull-based: ``collect()`` snapshots every metric into
``MetricFamily`` records. Live objects can also register a *collector*
callback (held by weakref via a closure, so registration never extends
an engine's lifetime) that yields families at scrape time.
"""

from __future__ import annotations

import bisect
import dataclasses
import math
import threading
import time
from typing import (
    Callable,
    Dict,
    Iterable,
    List,
    Optional,
    Sequence,
    Tuple,
)

from keystone_tpu.utils.profiling import Counter, LatencyRecorder

LabelValues = Tuple[str, ...]

# quantiles a latency summary exports (matches LatencyRecorder's
# p50/p95/p99 surface; Prometheus summary convention)
SUMMARY_QUANTILES = (0.5, 0.95, 0.99)

# default `le` bounds of a RegistryHistogram, tuned for request/queue
# latencies in seconds: sub-ms through 10s, roughly 2.5x apart
DEFAULT_HISTOGRAM_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


@dataclasses.dataclass
class Exemplar:
    """An OpenMetrics exemplar: one concrete observation (typically
    carrying a ``trace_id``) pinned to a histogram bucket, so the
    bucket's aggregate links back to a forensic trace."""

    labels: Dict[str, str]  # e.g. {"trace_id": "4bf9..."}
    value: float  # the exemplified observation itself
    timestamp_s: float  # epoch seconds when it was observed


@dataclasses.dataclass
class Sample:
    """One exposition line: ``name+suffix{labels} value``."""

    suffix: str  # "" for the bare metric, "_count"/"_sum" for summaries
    labels: Dict[str, str]
    value: float
    exemplar: Optional[Exemplar] = None


@dataclasses.dataclass
class MetricFamily:
    """A snapshot of one metric and all its label cells."""

    name: str
    mtype: str  # "counter" | "gauge" | "summary"
    help: str
    samples: List[Sample]


def _label_dict(
    labelnames: Sequence[str], values: LabelValues
) -> Dict[str, str]:
    return dict(zip(labelnames, values))


class _Metric:
    def __init__(self, name: str, help: str, labelnames: Sequence[str]):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)

    def _check(self, labels: Optional[LabelValues]) -> LabelValues:
        values = tuple(str(v) for v in (labels or ()))
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, "
                f"got values {values}"
            )
        return values


class RegistryCounter(_Metric):
    """Monotonic counter; cells keyed by label-value tuples."""

    mtype = "counter"

    def __init__(self, name, help, labelnames):
        super().__init__(name, help, labelnames)
        self._cells = Counter()

    def inc(self, labels: Optional[LabelValues] = None, by: float = 1):
        if by < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        self._cells.inc(self._check(labels), by)

    def get(self, labels: Optional[LabelValues] = None) -> float:
        return self._cells.get(self._check(labels))

    def collect(self) -> MetricFamily:
        cells = self._cells.snapshot()
        return MetricFamily(
            self.name, self.mtype, self.help,
            [
                Sample("", _label_dict(self.labelnames, values), v)
                for values, v in sorted(cells.items())
            ],
        )


class RegistryGauge(_Metric):
    """Settable gauge; one locked float per label set."""

    mtype = "gauge"

    def __init__(self, name, help, labelnames):
        super().__init__(name, help, labelnames)
        self._cells: Dict[LabelValues, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, labels: Optional[LabelValues] = None):
        with self._lock:
            self._cells[self._check(labels)] = float(value)

    def get(self, labels: Optional[LabelValues] = None) -> Optional[float]:
        with self._lock:
            return self._cells.get(self._check(labels))

    def collect(self) -> MetricFamily:
        with self._lock:
            cells = dict(self._cells)
        return MetricFamily(
            self.name, self.mtype, self.help,
            [
                Sample("", _label_dict(self.labelnames, values), v)
                for values, v in sorted(cells.items())
            ],
        )


class RegistryFuncGauge(_Metric):
    """Callback-backed gauge: ``fn`` runs at collect time and returns
    either a float (unlabeled) or a dict of label-values tuple ->
    float. Zero state, zero hot-path cost."""

    mtype = "gauge"

    def __init__(self, name, help, labelnames, fn: Callable):
        super().__init__(name, help, labelnames)
        self._fn = fn

    def collect(self) -> MetricFamily:
        out = self._fn()
        if not isinstance(out, dict):
            out = {(): out}
        samples = [
            Sample(
                "",
                _label_dict(
                    self.labelnames, tuple(str(v) for v in values)
                ),
                float(v),
            )
            for values, v in sorted(out.items())
            if v is not None
        ]
        return MetricFamily(self.name, self.mtype, self.help, samples)


class RegistrySummary(_Metric):
    """Latency summary: one ``LatencyRecorder`` per label set, exported
    as Prometheus quantile samples plus ``_count``/``_sum``."""

    mtype = "summary"

    def __init__(self, name, help, labelnames, window: int = 4096):
        super().__init__(name, help, labelnames)
        self._window = window
        self._cells: Dict[LabelValues, LatencyRecorder] = {}
        self._lock = threading.Lock()

    def recorder(
        self, labels: Optional[LabelValues] = None
    ) -> LatencyRecorder:
        """The live recorder for one label set (cacheable by callers so
        the per-observation path is one deque append)."""
        values = self._check(labels)
        with self._lock:
            rec = self._cells.get(values)
            if rec is None:
                rec = self._cells[values] = LatencyRecorder(self._window)
            return rec

    def observe(self, seconds: float, labels: Optional[LabelValues] = None):
        self.recorder(labels).record(seconds)

    def collect(self) -> MetricFamily:
        with self._lock:
            cells = dict(self._cells)
        samples: List[Sample] = []
        for values, rec in sorted(cells.items()):
            snap = rec.snapshot()
            base = _label_dict(self.labelnames, values)
            for q in SUMMARY_QUANTILES:
                v = snap[f"p{int(q * 100)}"]
                if v is not None:
                    samples.append(
                        Sample("", {**base, "quantile": repr(q)}, v)
                    )
            samples.append(Sample("_count", base, snap["count"]))
            samples.append(Sample("_sum", base, snap["total"]))
        return MetricFamily(self.name, self.mtype, self.help, samples)


class RegistryHistogram(_Metric):
    """Native Prometheus histogram: cumulative ``le``-bucket counts plus
    ``_sum``/``_count`` per label set.

    Unlike ``RegistrySummary`` (whose client-side quantiles cannot be
    aggregated across scrapes or instances), histogram buckets ADD —
    ``histogram_quantile(0.99, sum by (le) (rate(...)))`` is exact
    across every gateway/lane/host publishing the same family, which is
    why the gateway's queue-wait and request-latency series use this
    type. Observation is O(log buckets) (one bisect + one lock)."""

    mtype = "histogram"

    def __init__(
        self,
        name,
        help,
        labelnames,
        buckets: Sequence[float] = DEFAULT_HISTOGRAM_BUCKETS,
    ):
        super().__init__(name, help, labelnames)
        bounds = tuple(float(b) for b in buckets)
        if not bounds:
            raise ValueError(f"histogram {name} needs at least one bucket")
        if not all(math.isfinite(b) for b in bounds):
            # +Inf is implicit (collect() always appends it); accepting
            # an explicit inf bound would emit a duplicate le="+Inf"
            # series, which Prometheus rejects scrape-wide
            raise ValueError(
                f"histogram {name} buckets must be finite (+Inf is "
                f"implicit): {bounds}"
            )
        if list(bounds) != sorted(set(bounds)):
            raise ValueError(
                f"histogram {name} buckets must be strictly ascending: "
                f"{bounds}"
            )
        self.bounds = bounds
        # per label set: ([per-bound counts..., +Inf overflow], sum,
        # {bucket idx -> Exemplar})
        self._cells: Dict[
            LabelValues, Tuple[List[int], List[float], Dict[int, Exemplar]]
        ] = {}
        self._lock = threading.Lock()

    def observe(
        self,
        value: float,
        labels: Optional[LabelValues] = None,
        trace_id: Optional[str] = None,
    ):
        """Record one observation. ``trace_id`` (when the caller is
        inside a traced request) pins this observation as the bucket's
        OpenMetrics exemplar — the scrape then links the aggregate
        bucket straight to the flight-recorder entry for that trace."""
        values = self._check(labels)
        value = float(value)
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            cell = self._cells.get(values)
            if cell is None:
                cell = self._cells[values] = (
                    [0] * (len(self.bounds) + 1), [0.0], {},
                )
            cell[0][idx] += 1
            cell[1][0] += value
            if trace_id:
                cell[2][idx] = Exemplar(
                    {"trace_id": str(trace_id)}, value, time.time()
                )

    def get_count(self, labels: Optional[LabelValues] = None) -> int:
        values = self._check(labels)
        with self._lock:
            cell = self._cells.get(values)
            return sum(cell[0]) if cell else 0

    # -- windowed readers (the SLO evaluator's inputs) ---------------------

    def le_index(self, threshold: float) -> int:
        """Index of the smallest bound >= ``threshold``
        (``len(bounds)`` means only +Inf covers it). The SLO layer uses
        this to snap a latency objective onto bucket resolution."""
        return bisect.bisect_left(self.bounds, float(threshold))

    def cumulative_count(
        self, bound_index: int, labels: Optional[LabelValues] = None
    ) -> int:
        """Observations <= ``bounds[bound_index]`` (cumulative ``le``
        semantics; an index past the last bound counts everything)."""
        values = self._check(labels)
        with self._lock:
            cell = self._cells.get(values)
            if cell is None:
                return 0
            return sum(cell[0][: bound_index + 1])

    def get_sum(self, labels: Optional[LabelValues] = None) -> float:
        values = self._check(labels)
        with self._lock:
            cell = self._cells.get(values)
            return cell[1][0] if cell else 0.0

    def collect(self) -> MetricFamily:
        with self._lock:
            cells = {
                k: (list(counts), totals[0], dict(exemplars))
                for k, (counts, totals, exemplars) in self._cells.items()
            }
        # local import: prometheus.py imports MetricFamily from here
        from keystone_tpu.observability.prometheus import format_le

        samples: List[Sample] = []
        for values, (counts, total, exemplars) in sorted(cells.items()):
            base = _label_dict(self.labelnames, values)
            cum = 0
            for i, (bound, c) in enumerate(zip(self.bounds, counts)):
                cum += c
                samples.append(
                    Sample(
                        "_bucket", {**base, "le": format_le(bound)}, cum,
                        exemplar=exemplars.get(i),
                    )
                )
            cum += counts[-1]
            samples.append(
                Sample(
                    "_bucket", {**base, "le": "+Inf"}, cum,
                    exemplar=exemplars.get(len(self.bounds)),
                )
            )
            samples.append(Sample("_count", base, cum))
            samples.append(Sample("_sum", base, total))
        return MetricFamily(self.name, self.mtype, self.help, samples)


class MetricsRegistry:
    """The named catalogue. ``counter``/``gauge``/``gauge_func``/
    ``summary``/``histogram`` are get-or-create: re-registering the same
    name with the same type and labelnames returns the existing metric
    (subsystems in different modules can share a family); a mismatch
    raises."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: List[Callable[[], Iterable[MetricFamily]]] = []
        self._lock = threading.Lock()

    # -- registration ------------------------------------------------------

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        labelnames = tuple(labelnames)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (
                    type(existing) is not cls
                    or existing.labelnames != labelnames
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}{existing.labelnames}, "
                        f"asked for {cls.__name__}{labelnames}"
                    )
                return existing
            metric = cls(name, help, labelnames, **kw)
            self._metrics[name] = metric
            return metric

    def counter(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> RegistryCounter:
        return self._get_or_create(RegistryCounter, name, help, labelnames)

    def gauge(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> RegistryGauge:
        return self._get_or_create(RegistryGauge, name, help, labelnames)

    def gauge_func(
        self,
        name: str,
        fn: Callable,
        help: str = "",
        labelnames: Sequence[str] = (),
    ) -> RegistryFuncGauge:
        return self._get_or_create(
            RegistryFuncGauge, name, help, labelnames, fn=fn
        )

    def summary(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        window: int = 4096,
    ) -> RegistrySummary:
        return self._get_or_create(
            RegistrySummary, name, help, labelnames, window=window
        )

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_HISTOGRAM_BUCKETS,
    ) -> RegistryHistogram:
        hist = self._get_or_create(
            RegistryHistogram, name, help, labelnames, buckets=buckets
        )
        if hist.bounds != tuple(float(b) for b in buckets):
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{hist.bounds}, asked for {tuple(buckets)}"
            )
        return hist

    def register_collector(
        self, fn: Callable[[], Optional[Iterable[MetricFamily]]]
    ) -> None:
        """A callback polled at collect time; return an iterable of
        ``MetricFamily`` or None to be pruned (the ServingMetrics
        bridge returns None once its engine is garbage-collected)."""
        with self._lock:
            self._collectors.append(fn)

    # -- scraping ----------------------------------------------------------

    def collect(self) -> List[MetricFamily]:
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        families = [m.collect() for m in metrics]
        dead = []
        for fn in collectors:
            out = fn()
            if out is None:
                dead.append(fn)
                continue
            families.extend(out)
        if dead:
            with self._lock:
                self._collectors = [
                    f for f in self._collectors if f not in dead
                ]
        # merge same-name families collectors may emit in parallel
        # (several engines export keystone_serving_* under different
        # engine labels) so exposition has one TYPE block per name
        merged: Dict[str, MetricFamily] = {}
        for fam in families:
            cur = merged.get(fam.name)
            if cur is None:
                merged[fam.name] = dataclasses.replace(
                    fam, samples=list(fam.samples)
                )
            else:
                cur.samples.extend(fam.samples)
        return list(merged.values())

    def varz(self) -> Dict:
        """The whole registry as one plain-JSON-able dict (``/varz``)."""
        out: Dict = {}
        for fam in self.collect():
            entry = out.setdefault(
                fam.name, {"type": fam.mtype, "help": fam.help, "values": []}
            )
            for s in fam.samples:
                entry["values"].append(
                    {
                        "suffix": s.suffix,
                        "labels": s.labels,
                        "value": s.value,
                    }
                )
        return out


_global_registry: Optional[MetricsRegistry] = None
_global_lock = threading.Lock()


def get_global_registry() -> MetricsRegistry:
    """The process-global registry every subsystem publishes into."""
    global _global_registry
    if _global_registry is None:
        with _global_lock:
            if _global_registry is None:
                _global_registry = MetricsRegistry()
    return _global_registry


def reset_global_registry() -> None:
    """Drop the process-global registry (tests)."""
    global _global_registry
    with _global_lock:
        _global_registry = None
