"""Cross-process trace stitching: one request, one forensic object.

The fleet made tracing multi-process: the router opens
``router.forward`` spans and sends a W3C ``traceparent`` downstream;
the replica adopts the trace id, so its ``gateway.admit →
microbatch.coalesce → serving.dispatch`` (or staged-pipeline) chain
rides the router's id — but the two halves live in two processes'
tracer rings. This module federates them back into ONE tree:

- ``TraceStitcher.stitch(trace_id, resolve_url)`` collects the
  router-side spans, reads which replicas served attempts off the
  ``router.forward`` spans' attrs, fetches each replica's
  ``GET /debugz?trace_id=`` (pinned flight records when the request
  was tail-sampled, the live tracer ring otherwise — see
  ``flight.debugz_status``), and grafts the replica's root spans under
  the router-hop span that carried them. Span ids are
  process-qualified (``router:17`` vs ``replica:host:port:17``) —
  the two processes' integer id counters collide by construction.
- The result renders as JSON (``to_dict``) or a Chrome trace-event
  document (``to_chrome_trace``) with one ``pid`` per process, so
  chrome://tracing / Perfetto shows the router hop and the replica's
  admit/coalesce/dispatch chain in one timeline.
- **Phase decomposition**: every stitched request is decomposed into
  ``router_hop / queue_wait / coalesce / device / deliver``
  milliseconds (see ``phase_decomposition`` for the exact span
  arithmetic) — the "where did this request's 40 ms go" answer — and
  each phase lands on the ``keystone_request_phase_seconds{phase=}``
  histogram, which federates through ``prometheus.merge_expositions``
  like every other ``le``-bucket family.
- **Partial traces are a feature, not a failure**: a replica that is
  unreachable, restarted (ring gone), or running with tracing off —
  or a forward whose ``traceparent`` was stripped by the
  ``router.trace.drop`` chaos point, leaving the replica to mint its
  own id — yields the router-side partial tree, marked
  ``partial: true`` with per-replica detail and counted on
  ``keystone_trace_stitch_partial_total{reason=}``.

Clock discipline: ``router_hop`` is computed as a DIFFERENCE of
durations (router-measured total minus the replica-measured span
envelope), never as a difference of two hosts' wall clocks, so modest
cross-host clock skew cannot turn the network hop negative. The Chrome
render does plot each process on its own wall clock — on one host
(tests, smoke) they align; across hosts skew shows as a visual offset
only.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import threading
import urllib.parse
import urllib.request
from typing import Any, Callable, Deque, Dict, List, Optional

from keystone_tpu.observability.tracing import Tracer, get_tracer

logger = logging.getLogger(__name__)

# the decomposition's phase names, in pipeline order
PHASES = ("router_hop", "queue_wait", "coalesce", "device", "deliver")

# request phases span µs (a warm device dispatch) to seconds (a queue
# under overload): finer-than-default low buckets so sub-ms phases
# don't all land in one bin
PHASE_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)

# traces whose phases were already observed onto the histogram: the
# stitcher remembers this many trace ids so repeated /debugz queries
# of one request don't multiply-count it
OBSERVED_TRACES_CAPACITY = 4096

# span names the decomposition keys on (the serving chain's contract)
_ADMIT = "gateway.admit"
_COALESCE = "microbatch.coalesce"
_DISPATCH = "serving.dispatch"
_FORWARD = "router.forward"
_PIPELINE_DEVICE = ("pipeline.upload", "pipeline.compute")


def _start(s: Dict[str, Any]) -> float:
    return float(s["start_s"])


def _end(s: Dict[str, Any]) -> float:
    return float(s["start_s"]) + float(s["duration_ms"]) / 1e3


def _dur_s(s: Dict[str, Any]) -> float:
    return float(s["duration_ms"]) / 1e3


def qualify_spans(
    spans: List[Dict[str, Any]], process: str
) -> List[Dict[str, Any]]:
    """Namespace one process's span dicts (``Span.to_dict`` shape) so
    they can share a tree with another process's: ids become
    ``<process>:<id>`` strings, a parent id that points outside the
    provided set (fell out of the ring, or a remote parent the replica
    recorded as an attr) degrades to a root."""
    ids = {s.get("span_id") for s in spans}
    out = []
    for s in spans:
        q = dict(s)
        q["process"] = process
        q["span_id"] = f"{process}:{s.get('span_id')}"
        parent = s.get("parent_id")
        q["parent_id"] = (
            f"{process}:{parent}" if parent in ids and parent is not None
            else None
        )
        out.append(q)
    return out


def phase_decomposition(
    spans: List[Dict[str, Any]], router_process: str
) -> Dict[str, Any]:
    """One stitched trace's spans -> the per-request latency
    decomposition. Phase definitions (all clamped >= 0):

    - ``total``      — the winning ``router.forward`` span's duration
                       (the request as the router measured it); with
                       no router spans, the whole-trace envelope.
    - ``router_hop`` — total minus the replica-side span envelope:
                       network + serialization + router overhead
                       (durations subtracted, never cross-host clocks).
    - ``queue_wait`` — first ``microbatch.coalesce`` start minus first
                       ``gateway.admit`` start: admission-queue time
                       before a window opened for this request.
    - ``coalesce``   — window formation: with a dispatch span present
                       (serial lanes — where the REAL coalesce span
                       ENCLOSES the dispatch it triggers), first
                       dispatch start minus first coalesce start, so
                       device time is never counted twice; with
                       staged lanes, the coalesce span's own duration
                       (it ends at the pipeline handoff).
    - ``device``     — ``serving.dispatch`` (serial lanes) or
                       ``pipeline.upload`` + ``pipeline.compute``
                       (staged lanes): H2D + device compute.
    - ``deliver``    — the remainder (result download, future
                       resolution, response write): total minus every
                       phase above. Defined as the remainder so the
                       phases PARTITION the request — what is not
                       attributable to a named span is delivery-side
                       by construction, and the acceptance check
                       "phases sum ≈ measured latency" stays honest
                       because every OTHER phase is span-measured.

    Multi-window traces (a multi-instance POST split across windows)
    use the widest window per phase — the request resolves when its
    slowest instance does."""
    router = [s for s in spans if s.get("process") == router_process]
    remote = [s for s in spans if s.get("process") != router_process]
    forwards = [s for s in router if s.get("name") == _FORWARD]
    if forwards:
        # attempts are recorded in order; the last sibling is the one
        # that produced the response the client saw
        total_s = _dur_s(forwards[-1])
        # the envelope/queue arithmetic below must read ONE process's
        # clock: a retried trace can carry spans from a failed attempt
        # on ANOTHER replica host, and mixing two hosts' wall clocks
        # would turn their skew into phantom queue time — restrict the
        # remote side to the WINNING attempt's replica
        win = (forwards[-1].get("attrs") or {}).get("replica")
        if win:
            # possibly empty (the winner's half is missing): phases
            # then degrade to hop-only rather than decomposing the
            # winning request with a FAILED attempt's spans
            remote = [
                s for s in remote
                if s.get("process") == f"replica:{win}"
            ]
    elif spans:
        total_s = max(_end(s) for s in spans) - min(
            _start(s) for s in spans
        )
    else:
        return {"total_ms": None, "phases_ms": {}}

    def named(name: str) -> List[Dict[str, Any]]:
        return [s for s in remote if s.get("name") == name]

    admits = named(_ADMIT)
    coalesces = named(_COALESCE)
    dispatches = named(_DISPATCH)
    if not remote:
        # router-side partial: the hop is all that was MEASURED. The
        # replica phases are unknown, not zero — absent, so a partial
        # stitch can never drag the federated phase quantiles toward
        # zero (the repo's absent-not-zero doctrine)
        return {
            "total_ms": round(total_s * 1e3, 3),
            "phases_ms": {"router_hop": round(total_s * 1e3, 3)},
        }
    phases = dict.fromkeys(PHASES, 0.0)
    if remote:
        envelope = max(_end(s) for s in remote) - min(
            _start(s) for s in remote
        )
        phases["router_hop"] = max(0.0, total_s - envelope) if forwards else 0.0
        if admits and coalesces:
            phases["queue_wait"] = max(
                0.0,
                min(_start(s) for s in coalesces)
                - min(_start(s) for s in admits),
            )
        if coalesces:
            if dispatches:
                # serial lanes: the live coalesce span ENCLOSES the
                # dispatch it triggers (batching.py applies the engine
                # inside the with block) — formation time is up to the
                # dispatch start, or device time would count twice
                phases["coalesce"] = max(
                    0.0,
                    min(_start(s) for s in dispatches)
                    - min(_start(s) for s in coalesces),
                )
            else:
                phases["coalesce"] = max(_dur_s(s) for s in coalesces)
        if dispatches:
            phases["device"] = max(_dur_s(s) for s in dispatches)
        else:
            stage_device = [
                s for s in remote if s.get("name") in _PIPELINE_DEVICE
            ]
            if stage_device:
                phases["device"] = sum(
                    _dur_s(s) for s in stage_device
                )
        phases["deliver"] = max(
            0.0,
            total_s
            - phases["router_hop"]
            - phases["queue_wait"]
            - phases["coalesce"]
            - phases["device"],
        )
    return {
        "total_ms": round(total_s * 1e3, 3),
        "phases_ms": {
            k: round(v * 1e3, 3) for k, v in phases.items()
        },
    }


@dataclasses.dataclass
class StitchedTrace:
    """One cross-process trace: identity, the grafted span forest,
    which processes contributed, the phase decomposition, and whether
    any replica's half is missing (with per-replica detail)."""

    trace_id: str
    spans: List[Dict[str, Any]]
    processes: List[str]
    partial: bool
    partial_detail: List[str]
    phases: Dict[str, Any]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "processes": list(self.processes),
            "partial": self.partial,
            "partial_detail": list(self.partial_detail),
            "total_ms": self.phases.get("total_ms"),
            "phases_ms": self.phases.get("phases_ms", {}),
            "spans": list(self.spans),
        }

    def to_chrome_trace(self) -> Dict[str, Any]:
        """The stitched tree as Chrome trace-event JSON: one ``pid``
        per PROCESS (named via ``process_name`` metadata events), so
        Perfetto lays the router hop and the replica chain out as the
        separate processes they are — under one trace."""
        pids = {p: i for i, p in enumerate(self.processes)}
        events: List[Dict[str, Any]] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": process},
            }
            for process, pid in pids.items()
        ]
        for s in self.spans:
            events.append(
                {
                    "name": s.get("name"),
                    "ph": "X",
                    "ts": _start(s) * 1e6,
                    "dur": float(s.get("duration_ms", 0.0)) * 1e3,
                    "pid": pids.get(s.get("process"), 0),
                    "tid": s.get("thread_id", 0),
                    "args": {
                        **dict(s.get("attrs") or {}),
                        "span_id": s.get("span_id"),
                        "parent_id": s.get("parent_id"),
                        "trace_id": self.trace_id,
                    },
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}


class TraceStitcher:
    """The router's stitch engine over its own tracer + the fleet's
    ``/debugz`` surfaces. Owns the phase histogram and the
    partial-stitch counter so every ``/debugz?trace_id=`` served by
    the router also feeds the federated metrics plane."""

    def __init__(
        self,
        *,
        name: str = "router",
        tracer: Optional[Tracer] = None,
        registry=None,
        fetch_timeout_s: float = 5.0,
    ):
        self.name = name
        self._tracer = tracer
        self.fetch_timeout_s = float(fetch_timeout_s)
        if registry is None:
            from keystone_tpu.observability.registry import (
                get_global_registry,
            )

            registry = get_global_registry()
        self._phases = registry.histogram(
            "keystone_request_phase_seconds",
            "per-request end-to-end latency decomposition from "
            "stitched cross-process traces, by phase",
            ("phase",),
            buckets=PHASE_BUCKETS,
        )
        self._partials = registry.counter(
            "keystone_trace_stitch_partial_total",
            "stitches missing a replica's half of the trace, by why "
            "(unreachable scrape, no spans at the replica, unknown "
            "replica)",
            ("reason",),
        )
        # the histogram is PER-REQUEST: only the first stitch of a
        # trace observes its phases, or a human re-querying /debugz
        # would skew the family toward investigated requests
        self._observed: set = set()  # guarded-by: _observed_lock
        self._observed_order: Deque[str] = (
            collections.deque()
        )  # guarded-by: _observed_lock
        self._observed_lock = threading.Lock()

    @property
    def tracer(self) -> Tracer:
        return self._tracer if self._tracer is not None else get_tracer()

    # -- replica fetch ------------------------------------------------------

    def _fetch_debugz(self, url: str, trace_id: str) -> Dict[str, Any]:
        with urllib.request.urlopen(
            url.rstrip("/")
            + "/debugz?trace_id="
            + urllib.parse.quote(trace_id),
            timeout=self.fetch_timeout_s,
        ) as resp:
            return json.loads(resp.read())

    @staticmethod
    def _replica_spans(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Span dicts out of one replica ``/debugz`` document: the
        live-ring ``spans`` plus any pinned records' trees, deduped by
        span id (a tail-sampled request appears in both)."""
        seen = set()
        out: List[Dict[str, Any]] = []
        span_lists = [doc.get("spans") or []]
        for record in doc.get("records") or []:
            span_lists.append(record.get("spans") or [])
        for spans in span_lists:
            for s in spans:
                sid = s.get("span_id")
                if sid in seen:
                    continue
                seen.add(sid)
                out.append(s)
        return out

    # -- the stitch ---------------------------------------------------------

    def stitch(
        self,
        trace_id: str,
        resolve_url: Callable[[str], Optional[str]],
    ) -> Optional[StitchedTrace]:
        """Build the stitched trace, or None when this router's ring
        holds nothing for ``trace_id`` (unknown/lapped trace — the
        HTTP layer 404s). ``resolve_url`` maps a replica NAME (the
        ``router.forward`` span's ``replica`` attr) to its base URL —
        the registry lookup, so the stitch only ever dials replicas
        the fleet actually knows."""
        # the ROUTER-origin spans of this trace: router spans stamp a
        # ``router=<name>`` attr at creation. In a real router process
        # this filter is a no-op (its ring holds nothing else for the
        # trace); with a SHARED tracer (in-process tests, the bench
        # A/B rig) it is what keeps the replica's admit/coalesce chain
        # from double-counting as router-side spans.
        own = [
            s.to_dict()
            for s in self.tracer.spans_for_trace(trace_id)
            if (s.attrs or {}).get("router") == self.name
        ]
        local = qualify_spans(own, self.name)
        if not local:
            return None
        # identity of the router's own spans, so a replica /debugz
        # that shares this process's tracer echoing them back cannot
        # masquerade them as replica-side spans. Raw span ids alone
        # can't be the key — two real processes both count from 1 —
        # but a full (id, name, start, duration, thread) match across
        # processes is impossible outside the shared-tracer case.
        local_keys = {
            (
                s.get("span_id"), s.get("name"), s.get("start_s"),
                s.get("duration_ms"), s.get("thread_id"),
            )
            for s in own
        }
        forwards = [s for s in local if s.get("name") == _FORWARD]
        replica_names: List[str] = []
        for s in forwards:
            rname = (s.get("attrs") or {}).get("replica")
            if rname and rname not in replica_names:
                replica_names.append(rname)
        spans = list(local)
        processes = [self.name]
        partial_detail: List[str] = []
        for rname in replica_names:
            url = resolve_url(rname)
            if not url:
                partial_detail.append(f"{rname}: not in the registry")
                self._partials.inc(("unknown_replica",))
                continue
            try:
                doc = self._fetch_debugz(url, trace_id)
            except Exception as e:
                partial_detail.append(
                    f"{rname}: /debugz fetch failed "
                    f"({type(e).__name__}: {e})"
                )
                self._partials.inc(("unreachable",))
                continue
            raw = [
                s
                for s in self._replica_spans(doc)
                if (
                    s.get("span_id"), s.get("name"), s.get("start_s"),
                    s.get("duration_ms"), s.get("thread_id"),
                )
                not in local_keys
            ]
            if not raw:
                # the replica answered but holds nothing under this
                # id: ring lapped, process restarted, tracing off, or
                # the traceparent was dropped on the forward path
                # (router.trace.drop) and the replica self-minted
                partial_detail.append(
                    f"{rname}: no spans for this trace (ring lapped, "
                    "restarted, tracing off, or traceparent dropped)"
                )
                self._partials.inc(("no_spans",))
                continue
            process = f"replica:{rname}"
            qualified = qualify_spans(raw, process)
            # graft: the replica's roots hang under the LAST router
            # hop that dialed it (the attempt that carried them)
            anchor = next(
                (
                    s["span_id"]
                    for s in reversed(forwards)
                    if (s.get("attrs") or {}).get("replica") == rname
                ),
                None,
            )
            for s in qualified:
                if s["parent_id"] is None and anchor is not None:
                    s["parent_id"] = anchor
                    s["grafted"] = True
            spans.extend(qualified)
            processes.append(process)
        phases = phase_decomposition(spans, self.name)
        with self._observed_lock:
            first_stitch = trace_id not in self._observed
            if first_stitch:
                self._observed.add(trace_id)
                self._observed_order.append(trace_id)
                while len(self._observed_order) > OBSERVED_TRACES_CAPACITY:
                    self._observed.discard(
                        self._observed_order.popleft()
                    )
        if first_stitch:
            for phase, ms in phases.get("phases_ms", {}).items():
                self._phases.observe(
                    ms / 1e3, (phase,), trace_id=trace_id
                )
        return StitchedTrace(
            trace_id=trace_id,
            spans=spans,
            processes=processes,
            partial=bool(partial_detail),
            partial_detail=partial_detail,
            phases=phases,
        )

    def document(
        self,
        trace_id: Optional[str],
        fmt: str,
        resolve_url: Callable[[str], Optional[str]],
    ) -> tuple:
        """The router's ``/debugz`` routing -> ``(status, json_doc)``,
        mirroring ``flight.debugz_document``'s shape: JSON stitched
        tree by default, the cross-process Chrome trace with
        ``format=chrome``."""
        if not trace_id:
            return 400, {
                "error": "the router's /debugz stitches one trace: "
                "pass ?trace_id= (find ids in X-Keystone-Trace "
                "response headers, /tracez, or a --request-log)"
            }
        stitched = self.stitch(trace_id, resolve_url)
        if stitched is None:
            return 404, {
                "error": f"no spans for trace {trace_id} in this "
                "router's ring (lapped, or tracing is off)"
            }
        if fmt == "chrome":
            return 200, stitched.to_chrome_trace()
        return 200, stitched.to_dict()


__all__ = [
    "PHASES",
    "PHASE_BUCKETS",
    "StitchedTrace",
    "TraceStitcher",
    "phase_decomposition",
    "qualify_spans",
]
