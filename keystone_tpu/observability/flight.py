"""Tail-sampled flight recorder: full forensics for the requests that
went wrong.

The tracer's ring holds *recent* spans of *every* request — great for
"what is the process doing", useless for "what happened to THE slow
request from 40 seconds ago" once the ring laps. The flight recorder is
the tail-sampling layer on top: the capture decision happens at request
END (when the latency and outcome are known — that is what makes it
*tail* sampling), and only requests that breached the SLO threshold or
errored get their full span tree + attrs pinned into a separate bounded
ring that ordinary traffic can never evict.

The gateway's admission ``_finish`` hook drives ``maybe_capture``; each
``FlightRecord`` is browsable at ``/debugz`` (JSON) and individually
dumpable as a Chrome trace-event document (``?trace_id=...&format=
chrome``) that loads in chrome://tracing / Perfetto. Histogram
exemplars carry the same ``trace_id``, so a spike on the latency
histogram links straight to its record here.

Disabled is free: a recorder exists only where constructed (the module
keeps a weak set for ``/debugz``), and ``maybe_capture`` on a disabled
recorder is one attribute read.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time
import weakref
from typing import Any, Deque, Dict, List, Optional, Tuple

from keystone_tpu.observability.tracing import Span, Tracer, get_tracer

DEFAULT_CAPACITY = 64

# every live recorder, for /debugz (weak: dies with its gateway)
_recorders: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()


def recorders() -> List["FlightRecorder"]:
    return list(_recorders)


def debugz_status(trace_id: Optional[str] = None) -> Dict:
    """The admin ``/debugz`` document: every record of every live
    recorder (newest first), optionally filtered to one trace. A
    ``trace_id`` query ALSO returns that trace's live spans straight
    from the tracer ring (``"spans"``): ordinary requests are never
    tail-sampled into a flight record, but the fleet router's
    cross-process stitch (``observability/stitch.py``) still needs
    their span tree while the ring holds it — pinned forensics when
    they exist, the ring as the fallback."""
    records: List[FlightRecord] = []
    for rec in recorders():
        records.extend(rec.records())
    records.sort(key=lambda r: r.captured_at, reverse=True)
    doc: Dict[str, Any] = {"recorders": len(recorders())}
    if trace_id is not None:
        records = [r for r in records if r.trace_id == trace_id]
        doc["trace_id"] = trace_id
        doc["spans"] = [
            s.to_dict() for s in get_tracer().spans_for_trace(trace_id)
        ]
    doc["records"] = [r.to_dict() for r in records]
    return doc


def find_record(trace_id: str) -> Optional["FlightRecord"]:
    for rec in recorders():
        found = rec.find(trace_id)
        if found is not None:
            return found
    return None


def debugz_document(
    trace_id: Optional[str], fmt: str = ""
) -> Tuple[int, Dict]:
    """The ``/debugz`` routing, shared by the admin and gateway HTTP
    handlers -> ``(status_code, json_document)``: the record listing by
    default, one record as a Chrome trace with ``fmt == "chrome"``
    (which requires a ``trace_id``)."""
    if fmt == "chrome":
        if not trace_id:
            return 400, {"error": "format=chrome requires trace_id="}
        record = find_record(trace_id)
        if record is None:
            return 404, {"error": f"no flight record for trace {trace_id}"}
        return 200, record.to_chrome_trace()
    return 200, debugz_status(trace_id)


@dataclasses.dataclass
class FlightRecord:
    """One captured request: identity, verdict, and the span tree."""

    trace_id: str
    reason: str  # "slo_breach" | "error" | "drift"
    captured_at: float  # epoch seconds
    duration_s: Optional[float]
    attrs: Dict[str, Any]
    spans: List[Span]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "reason": self.reason,
            "captured_at": self.captured_at,
            "duration_ms": (
                round(self.duration_s * 1e3, 6)
                if self.duration_s is not None
                else None
            ),
            "attrs": dict(self.attrs),
            "spans": [s.to_dict() for s in self.spans],
        }

    def to_chrome_trace(self) -> Dict[str, Any]:
        """This record alone as Chrome trace-event JSON (the same
        object format ``Tracer.to_chrome_trace`` emits) — one request's
        tree, loadable in chrome://tracing / Perfetto."""
        pid = os.getpid()
        events = [
            {
                "name": s.name,
                "ph": "X",
                "ts": s.start_s * 1e6,
                "dur": s.duration_s * 1e6,
                "pid": pid,
                "tid": s.thread_id,
                "args": {
                    **s.attrs,
                    "span_id": s.span_id,
                    "parent_id": s.parent_id,
                    "trace_id": s.trace_id,
                },
            }
            for s in self.spans
        ]
        events.append(
            {
                "name": f"flight:{self.reason}",
                "ph": "i",  # instant event marking the capture verdict
                "ts": self.captured_at * 1e6,
                "pid": pid,
                "tid": 0,
                "s": "g",
                "args": dict(self.attrs),
            }
        )
        return {"traceEvents": events, "displayTimeUnit": "ms"}


class FlightRecorder:
    """Bounded ring of tail-sampled ``FlightRecord``s."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        *,
        latency_threshold_s: Optional[float] = None,
        tracer: Optional[Tracer] = None,
        enabled: bool = True,
        registry=None,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.enabled = enabled
        self.latency_threshold_s = latency_threshold_s
        self._tracer = tracer
        self._ring: Deque[FlightRecord] = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        if registry is None:
            from keystone_tpu.observability.registry import (
                get_global_registry,
            )

            registry = get_global_registry()
        self._captured = registry.counter(
            "keystone_flight_records_total",
            "requests tail-sampled into the flight recorder, by reason",
            ("reason",),
        )
        _recorders.add(self)

    @property
    def tracer(self) -> Tracer:
        return self._tracer if self._tracer is not None else get_tracer()

    # -- capture -----------------------------------------------------------

    def maybe_capture(
        self,
        trace_id: Optional[str],
        duration_s: Optional[float] = None,
        error: Optional[BaseException] = None,
        threshold_s: Optional[float] = None,
        **attrs: Any,
    ) -> Optional[FlightRecord]:
        """The tail-sampling decision, called once per finished
        request: capture when it errored or overran the latency
        threshold (per-call override, else the recorder's); drop — for
        free — otherwise."""
        if not self.enabled:
            return None
        if error is not None:
            attrs["error"] = f"{type(error).__name__}: {error}"
            return self.capture(
                trace_id, "error", duration_s=duration_s, **attrs
            )
        threshold = (
            threshold_s if threshold_s is not None
            else self.latency_threshold_s
        )
        if (
            threshold is not None
            and duration_s is not None
            and duration_s > threshold
        ):
            attrs["threshold_ms"] = round(threshold * 1e3, 6)
            return self.capture(
                trace_id, "slo_breach", duration_s=duration_s, **attrs
            )
        return None

    def capture(
        self,
        trace_id: Optional[str],
        reason: str,
        duration_s: Optional[float] = None,
        **attrs: Any,
    ) -> FlightRecord:
        """Pin the trace's full span tree (what the tracer ring still
        holds of it — capture runs at request end, so normally all of
        it) into the forensic ring."""
        spans = (
            self.tracer.spans_for_trace(trace_id) if trace_id else []
        )
        record = FlightRecord(
            trace_id=trace_id or "",
            reason=reason,
            captured_at=time.time(),
            duration_s=duration_s,
            attrs=attrs,
            spans=spans,
        )
        with self._lock:
            self._ring.append(record)
        self._captured.inc((reason,))
        return record

    # -- queries -----------------------------------------------------------

    def records(self, n: Optional[int] = None) -> List[FlightRecord]:
        """Captured records, oldest first."""
        with self._lock:
            records = list(self._ring)
        return records if n is None else records[-n:]

    def find(self, trace_id: str) -> Optional[FlightRecord]:
        with self._lock:
            for record in reversed(self._ring):
                if record.trace_id == trace_id:
                    return record
        return None

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


__all__ = [
    "DEFAULT_CAPACITY",
    "FlightRecord",
    "FlightRecorder",
    "debugz_document",
    "debugz_status",
    "find_record",
    "recorders",
]
