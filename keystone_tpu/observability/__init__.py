"""Observability subsystem: one plane for metrics and spans.

KeystoneML's operator decisions (auto-caching, solver selection) run on
*measured* profiles; this package gives the runtime the same treatment:

- ``MetricsRegistry`` (registry.py): process-global catalogue of named,
  labeled counters / gauges / latency summaries / native histograms
  (``RegistryHistogram``: Prometheus ``le`` buckets that aggregate
  exactly across scrapes and replicas), built on the
  ``Counter``/``LatencyRecorder`` primitives in ``utils/profiling.py``.
  ``ServingMetrics`` registers itself here; the executor, auto-cache
  profiler, ``PhaseTimer``, and the request gateway publish here.
- ``Tracer`` (tracing.py): Dapper-style spans with parent links and a
  bounded ring of recent spans; Chrome trace-event JSON export for
  chrome://tracing / Perfetto. Disabled by default (one attribute read
  per call site when off).
- ``AdminServer`` (admin.py): stdlib-http background thread serving
  ``/metrics`` (Prometheus text exposition v0.0.4), ``/varz`` (JSON),
  ``/healthz``, and ``/tracez`` (recent spans). Off unless started —
  ``python -m keystone_tpu --admin-port 8080 <App>`` wires it up.

The serving engine's per-bucket compile/dispatch counters, the
micro-batcher's queue depth and request latency, workflow executor node
spans, and auto-cache phase timings all land here, so the bucket
autoscaler (``serving/autoscale.py``) and any external scraper read one
consistent surface.
"""

from keystone_tpu.observability.admin import (
    AdminServer,
    build_info,
    start_admin_server,
    stop_admin_server,
)
from keystone_tpu.observability.attribution import (
    AttributionLedger,
    EngineAttribution,
    RowClaimQueue,
    attribution_document,
    attribution_from_samples,
)
from keystone_tpu.observability.drift import DriftDetector, psi
from keystone_tpu.observability.device import (
    DeviceMemorySampler,
    compiled_cost_model,
    device_memory_stats,
    device_table,
    peaks_for,
)
from keystone_tpu.observability.flight import (
    FlightRecord,
    FlightRecorder,
)
from keystone_tpu.observability.otlp import OtlpSpanExporter
from keystone_tpu.observability.registry import (
    DEFAULT_HISTOGRAM_BUCKETS,
    Exemplar,
    MetricFamily,
    MetricsRegistry,
    RegistryHistogram,
    Sample,
    get_global_registry,
    reset_global_registry,
)
from keystone_tpu.observability.slo import Slo, SloMonitor
from keystone_tpu.observability.stitch import (
    StitchedTrace,
    TraceStitcher,
    phase_decomposition,
)
from keystone_tpu.observability.tracing import (
    Span,
    TraceContext,
    Tracer,
    disable_tracing,
    enable_tracing,
    format_traceparent,
    get_tracer,
    parse_traceparent,
)

__all__ = [
    "AdminServer",
    "AttributionLedger",
    "DEFAULT_HISTOGRAM_BUCKETS",
    "DeviceMemorySampler",
    "DriftDetector",
    "EngineAttribution",
    "RowClaimQueue",
    "attribution_document",
    "attribution_from_samples",
    "psi",
    "compiled_cost_model",
    "device_memory_stats",
    "device_table",
    "peaks_for",
    "Exemplar",
    "FlightRecord",
    "FlightRecorder",
    "MetricFamily",
    "MetricsRegistry",
    "OtlpSpanExporter",
    "RegistryHistogram",
    "Sample",
    "Slo",
    "SloMonitor",
    "Span",
    "StitchedTrace",
    "TraceContext",
    "TraceStitcher",
    "Tracer",
    "build_info",
    "disable_tracing",
    "enable_tracing",
    "format_traceparent",
    "get_global_registry",
    "get_tracer",
    "parse_traceparent",
    "phase_decomposition",
    "reset_global_registry",
    "start_admin_server",
    "stop_admin_server",
]
