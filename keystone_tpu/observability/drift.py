"""Workload drift detection: live request histograms vs the plan's.

The placement planner (``zoo/optimizer.py``) chooses buckets, lanes,
and sharding from a request-size histogram per model — and then the
plan flies blind: traffic whose size mixture shifts after planning
quietly pays padding waste (or chunking) the plan was built to avoid.
The ``DriftDetector`` watches for exactly that: each model's live
request sizes are kept as a trailing-window event deque (windowed
deltas, so yesterday's traffic can't mask today's shift), the plan's
assumed ``ModelProfile`` histogram is the pinned baseline, and the
distance between them is the **population stability index**:

    ``PSI = sum_i (live_i - base_i) * ln(live_i / base_i)``

over the union of size bins, with both fractions clipped to a small
epsilon so bins present on one side only contribute finitely. PSI is
symmetric-ish, zero for identical mixtures, and the industry folklore
thresholds apply: < 0.1 stable, 0.1-0.25 moderate, > 0.25 shifted —
the default trip threshold here.

Crossing the threshold does three things, none of them auto-apply:
``keystone_drift_score{model}`` (a gauge, federated by MAX across the
fleet — the worst replica's drift is the fleet's drift), a flight-
recorder capture (reason ``drift``) so the moment of the shift keeps
its forensics, and the ``/driftz`` audit: the zoo re-runs
``plan_placement`` on the LIVE profiles and publishes the diff of what
*would* change (``zoo/optimizer.diff_plans``) as a recommendation.
Applying it stays an operator decision (ROADMAP follow-on).

Scores are absent-not-zero: a model scores only once it has a baseline
AND ``min_rows`` live observations in the window — a cold model is
unknown, not stable.
"""

from __future__ import annotations

import collections
import math
import threading
import time
import weakref
from typing import Deque, Dict, List, Mapping, Optional, Tuple

# PSI folklore: > 0.25 = the population has shifted
DEFAULT_THRESHOLD = 0.25
# live observations required before a score is emitted at all
DEFAULT_MIN_ROWS = 32
# trailing window of live request sizes
DEFAULT_WINDOW_S = 120.0
# fraction floor for one-sided bins (a bin seen live but never in the
# baseline must contribute a large-but-finite surprise, not infinity)
PSI_EPS = 1e-4


def psi(
    baseline: Mapping[int, float],
    live: Mapping[int, float],
    eps: float = PSI_EPS,
) -> Optional[float]:
    """Population stability index between two size histograms (raw
    counts or weights; normalized here). None when either side is
    empty — no distribution, no distance."""
    base_total = sum(baseline.values())
    live_total = sum(live.values())
    if base_total <= 0 or live_total <= 0:
        return None
    score = 0.0
    for size in set(baseline) | set(live):
        b = max(baseline.get(size, 0.0) / base_total, eps)
        l = max(live.get(size, 0.0) / live_total, eps)
        score += (l - b) * math.log(l / b)
    return score


class DriftDetector:
    """Per-model live-histogram drift against pinned plan baselines."""

    def __init__(
        self,
        *,
        threshold: float = DEFAULT_THRESHOLD,
        min_rows: int = DEFAULT_MIN_ROWS,
        window_s: float = DEFAULT_WINDOW_S,
        clock=time.monotonic,
        flight=None,
    ):
        self.threshold = float(threshold)
        self.min_rows = int(min_rows)
        self.window_s = float(window_s)
        self._clock = clock
        # flight recorder (observability/flight.py) for drift captures;
        # weakly held so the detector never extends a gateway's life
        self._flight = weakref.ref(flight) if flight is not None else None
        self._lock = threading.Lock()
        self._baselines: Dict[str, Dict[int, float]] = {}
        self._events: Dict[str, Deque[Tuple[float, int]]] = {}
        # models currently over threshold — capture fires on the
        # TRANSITION into drift, not on every scrape while drifted
        self._flagged: set = set()

    # -- inputs ------------------------------------------------------------

    def set_baseline(
        self, model: str, histogram: Mapping[int, float]
    ) -> None:
        """Pin the plan-assumed size histogram for one model (what the
        applied ``ModelProfile`` carried). An empty histogram clears —
        the model stops scoring rather than scoring against nothing."""
        hist = {
            int(s): float(c) for s, c in (histogram or {}).items() if c > 0
        }
        with self._lock:
            if hist:
                self._baselines[model] = hist
            else:
                self._baselines.pop(model, None)
                self._flagged.discard(model)

    def observe(self, model: str, size: int) -> None:
        """One live request of ``size`` rows for ``model``."""
        now = self._clock()
        cutoff = now - self.window_s
        with self._lock:
            events = self._events.get(model)
            if events is None:
                events = self._events[model] = collections.deque()
            events.append((now, int(size)))
            while events and events[0][0] < cutoff:
                events.popleft()

    # -- queries -----------------------------------------------------------

    def baselines(self) -> Dict[str, Dict[int, float]]:
        with self._lock:
            return {m: dict(h) for m, h in self._baselines.items()}

    def live_histogram(self, model: str) -> Dict[int, int]:
        """The trailing-window request-size histogram for one model."""
        now = self._clock()
        cutoff = now - self.window_s
        with self._lock:
            events = self._events.get(model, ())
            hist: Dict[int, int] = {}
            for t, size in events:
                if t >= cutoff:
                    hist[size] = hist.get(size, 0) + 1
        return hist

    def live_histograms(self) -> Dict[str, Dict[int, int]]:
        with self._lock:
            models = list(self._events)
        return {m: self.live_histogram(m) for m in models}

    def scores(self) -> Dict[str, float]:
        """PSI per model — only models with a baseline and at least
        ``min_rows`` windowed observations (absent, never zero)."""
        baselines = self.baselines()
        out: Dict[str, float] = {}
        for model, base in baselines.items():
            live = self.live_histogram(model)
            if sum(live.values()) < self.min_rows:
                continue
            score = psi(base, live)
            if score is not None:
                out[model] = score
        self._update_flags(out)
        return out

    def drifted(self) -> List[str]:
        """Models whose current score exceeds the threshold."""
        return sorted(
            m for m, s in self.scores().items() if s > self.threshold
        )

    def _update_flags(self, scores: Dict[str, float]) -> None:
        """Track threshold transitions; capture each model's ENTRY into
        drift in the flight recorder (reason ``drift``) so the moment
        keeps its forensics."""
        newly = []
        with self._lock:
            for model, score in scores.items():
                over = score > self.threshold
                if over and model not in self._flagged:
                    self._flagged.add(model)
                    newly.append((model, score))
                elif not over:
                    self._flagged.discard(model)
        if not newly:
            return
        flight = self._flight() if self._flight is not None else None
        if flight is None:
            # no recorder injected: capture into the process's live one
            # (the gateway's), when any exists — same weak posture as
            # /debugz, which browses the module-level recorder set
            from keystone_tpu.observability import flight as flight_mod

            live = flight_mod.recorders()
            flight = live[0] if live else None
        if flight is None:
            return
        for model, score in newly:
            try:
                flight.capture(
                    None, "drift",
                    model=model,
                    psi=round(score, 4),
                    threshold=self.threshold,
                )
            except Exception:  # forensics must never take down serving
                pass

    # -- MetricsRegistry bridge --------------------------------------------

    def register(self, registry=None) -> None:
        """Export ``keystone_drift_score{model}`` — a gauge that
        federates by MAX (``prometheus.MERGE_MAX_FAMILIES``): the worst
        replica's drift is the fleet's drift; two replicas each at 0.3
        are not a fleet at 0.6."""
        from keystone_tpu.observability.registry import get_global_registry

        reg = registry if registry is not None else get_global_registry()
        ref = weakref.ref(self)

        def read():
            det = ref()
            if det is None:
                return {}
            return {(m,): s for m, s in det.scores().items()}

        reg.gauge_func(
            "keystone_drift_score", read,
            "population stability index of the model's live windowed "
            "request-size histogram vs the applied plan's baseline "
            "(> threshold = the plan no longer matches the traffic)",
            ("model",),
        )

    def document(self) -> Dict:
        """The detector-level half of ``/driftz`` (the zoo wraps this
        with the re-plan recommendation)."""
        scores = self.scores()
        return {
            "threshold": self.threshold,
            "min_rows": self.min_rows,
            "window_s": self.window_s,
            "scores": {m: round(s, 4) for m, s in sorted(scores.items())},
            "drifted": sorted(
                m for m, s in scores.items() if s > self.threshold
            ),
            "baselines": {
                m: {str(k): v for k, v in sorted(h.items())}
                for m, h in sorted(self.baselines().items())
            },
            "live": {
                m: {str(k): v for k, v in sorted(h.items())}
                for m, h in sorted(self.live_histograms().items())
                if h
            },
        }


__all__ = [
    "DEFAULT_MIN_ROWS",
    "DEFAULT_THRESHOLD",
    "DEFAULT_WINDOW_S",
    "DriftDetector",
    "PSI_EPS",
    "psi",
]
