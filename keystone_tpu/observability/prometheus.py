"""Prometheus text exposition format v0.0.4.

Pure string rendering over ``MetricsRegistry.collect()`` snapshots — no
sockets here (the admin endpoint serves the result; golden-string tests
cover the format without one). Reference:
https://prometheus.io/docs/instrumenting/exposition_formats/

Rules implemented:
- metric names must match ``[a-zA-Z_:][a-zA-Z0-9_:]*`` — invalid
  characters are replaced with ``_`` and a leading digit is prefixed;
- label names must match ``[a-zA-Z_][a-zA-Z0-9_]*`` (no colons);
- label VALUES may contain any UTF-8 but backslash, double-quote and
  newline must be escaped as ``\\\\``, ``\\"`` and ``\\n``;
- HELP text escapes backslash and newline (quotes are legal there);
- every family gets one ``# HELP`` + ``# TYPE`` block, and the body
  ends with a trailing newline.
"""

from __future__ import annotations

import math
import re
from typing import Iterable

from keystone_tpu.observability.registry import MetricFamily

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_METRIC_INVALID = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_INVALID = re.compile(r"[^a-zA-Z0-9_]")


def sanitize_metric_name(name: str) -> str:
    name = _METRIC_INVALID.sub("_", name)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def sanitize_label_name(name: str) -> str:
    name = _LABEL_INVALID.sub("_", name)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def escape_label_value(value: str) -> str:
    # backslash FIRST or the other escapes' backslashes double-escape
    return (
        str(value)
        .replace("\\", r"\\")
        .replace('"', r"\"")
        .replace("\n", r"\n")
    )


def escape_help(text: str) -> str:
    return str(text).replace("\\", r"\\").replace("\n", r"\n")


def format_le(bound: float) -> str:
    """A histogram bucket bound as its canonical ``le`` label value
    (what promtool emits: ``0.005``, ``1``, ``2.5``, ``+Inf``) so the
    same bound always produces the same series identity."""
    if math.isinf(bound):
        return "+Inf" if bound > 0 else "-Inf"
    if float(bound).is_integer():
        return str(int(bound))
    return repr(float(bound))


def format_value(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int) or (isinstance(v, float) and v.is_integer()):
        return str(int(v))
    return repr(float(v))


def render_family(family: MetricFamily) -> str:
    name = sanitize_metric_name(family.name)
    lines = []
    if family.help:
        lines.append(f"# HELP {name} {escape_help(family.help)}")
    lines.append(f"# TYPE {name} {family.mtype}")
    for s in family.samples:
        if s.labels:
            labelstr = "{" + ",".join(
                f'{sanitize_label_name(k)}="{escape_label_value(v)}"'
                for k, v in s.labels.items()
            ) + "}"
        else:
            labelstr = ""
        lines.append(f"{name}{s.suffix}{labelstr} {format_value(s.value)}")
    return "\n".join(lines) + "\n"


def render(families: Iterable[MetricFamily]) -> str:
    """Families (from ``MetricsRegistry.collect()``) -> the full
    exposition body."""
    return "".join(
        render_family(f) for f in sorted(families, key=lambda f: f.name)
    )
